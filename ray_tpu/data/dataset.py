"""Dataset: lazy, distributed, streaming-executed data pipelines.

Reference: ``python/ray/data/dataset.py`` (class :178, ``map_batches`` :397,
``streaming_split`` :1149, ``iter_batches`` :3499). A Dataset wraps a logical
plan; transformations append logical ops; consumption plans + runs the
streaming executor over the cluster's task/actor substrate.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

from ..core.api import get as ray_get
from . import logical as L
from .aggregate import AggregateFn, Count, Max, Mean, Min, Std, Sum
from .block import BlockAccessor, BlockMetadata
from .context import DataContext
from .executor import StreamingExecutor, execute_to_bundles
from .operators import RefBundle, _iter_batches_of
from .planner import plan


def _normalize_compute(compute, concurrency):
    if concurrency is None and compute is None:
        return "tasks"
    if concurrency is not None:
        if isinstance(concurrency, int):
            return ("actors", concurrency, concurrency)
        mn, mx = concurrency
        return ("actors", mn, mx)
    return compute


class Dataset:
    def __init__(self, logical_op: L.LogicalOp):
        self._logical = logical_op
        self._materialized: Optional[List[RefBundle]] = None

    # -- plan helpers --------------------------------------------------------
    def _with(self, op: L.LogicalOp) -> "Dataset":
        op.input_op = self._logical
        return Dataset(op)

    def _execute(self) -> List[RefBundle]:
        if self._materialized is None:
            self._materialized = execute_to_bundles(plan(self._logical))
        return self._materialized

    def _stream(self) -> Iterator[RefBundle]:
        if self._materialized is not None:
            return iter(self._materialized)
        return StreamingExecutor(plan(self._logical)).start()

    # -- transformations -----------------------------------------------------
    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "default", compute=None,
                    concurrency=None, fn_args=(), fn_kwargs=None,
                    fn_constructor_args=(), zero_copy_batch: bool = False,
                    **ray_remote_args) -> "Dataset":
        return self._with(L.MapBatches(
            fn=fn, batch_size=batch_size, batch_format=batch_format,
            compute=_normalize_compute(compute, concurrency),
            fn_args=tuple(fn_args), fn_kwargs=dict(fn_kwargs or {}),
            fn_constructor_args=tuple(fn_constructor_args),
            zero_copy_batch=zero_copy_batch, ray_remote_args=ray_remote_args))

    def map(self, fn: Callable, *, compute=None, concurrency=None,
            **ray_remote_args) -> "Dataset":
        return self._with(L.MapRows(
            fn=fn, compute=_normalize_compute(compute, concurrency),
            ray_remote_args=ray_remote_args))

    def filter(self, fn: Callable, **ray_remote_args) -> "Dataset":
        return self._with(L.Filter(fn=fn, ray_remote_args=ray_remote_args))

    def flat_map(self, fn: Callable, **ray_remote_args) -> "Dataset":
        return self._with(L.FlatMap(fn=fn, ray_remote_args=ray_remote_args))

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch
        return self.map_batches(add, batch_format="pandas")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def drop(t: pa.Table):
            return t.drop_columns([c for c in cols if c in t.column_names])
        return self.map_batches(drop, batch_format="pyarrow")

    def select_columns(self, cols: List[str]) -> "Dataset":
        def select(t: pa.Table):
            return t.select(cols)
        return self.map_batches(select, batch_format="pyarrow")

    def rename_columns(self, mapping: Dict[str, str]) -> "Dataset":
        def ren(t: pa.Table):
            return t.rename_columns([mapping.get(c, c) for c in t.column_names])
        return self.map_batches(ren, batch_format="pyarrow")

    def limit(self, n: int) -> "Dataset":
        return self._with(L.Limit(n=n))

    def repartition(self, num_blocks: int, *, shuffle: bool = False) -> "Dataset":
        return self._with(L.Repartition(num_outputs=num_blocks, shuffle=shuffle))

    def random_shuffle(self, *, seed: Optional[int] = None,
                       num_blocks: Optional[int] = None) -> "Dataset":
        if seed is None:
            seed = DataContext.get_current().seed
        return self._with(L.RandomShuffle(seed=seed, num_outputs=num_blocks))

    def randomize_block_order(self, *, seed: Optional[int] = None) -> "Dataset":
        return self._with(L.RandomizeBlockOrder(seed=seed))

    def sort(self, key: str, descending: bool = False) -> "Dataset":
        return self._with(L.Sort(key=key, descending=descending))

    def union(self, *others: "Dataset") -> "Dataset":
        op = L.Union()
        op.extra_inputs = [o._logical for o in others]
        return self._with(op)

    def zip(self, other: "Dataset") -> "Dataset":
        op = L.Zip()
        op.extra_inputs = [other._logical]
        return self._with(op)

    def groupby(self, key: Optional[str]) -> "GroupedData":
        return GroupedData(self, key)

    # -- aggregations --------------------------------------------------------
    def aggregate(self, *aggs: AggregateFn) -> Dict[str, Any]:
        ds = self._with(L.Aggregate(key=None, aggs=list(aggs)))
        rows = ds.take_all()
        return rows[0] if rows else {}

    def count(self) -> int:
        # Fast path: sum block metadata row counts.
        total = 0
        for b in self._stream():
            n = b.num_rows()
            if n is None:
                return self.aggregate(Count())["count()"]
            total += n
        return total

    def sum(self, on: str):
        return self.aggregate(Sum(on))[f"sum({on})"]

    def min(self, on: str):
        return self.aggregate(Min(on))[f"min({on})"]

    def max(self, on: str):
        return self.aggregate(Max(on))[f"max({on})"]

    def mean(self, on: str):
        return self.aggregate(Mean(on))[f"mean({on})"]

    def std(self, on: str, ddof: int = 1):
        return self.aggregate(Std(on, ddof))[f"std({on})"]

    # -- consumption ---------------------------------------------------------
    def take(self, n: int = 20) -> List[Any]:
        out: List[Any] = []
        stream = self.limit(n)._stream()
        for bundle in stream:
            for ref, _ in bundle.blocks:
                acc = BlockAccessor.for_block(ray_get(ref))
                out.extend(acc.take(n - len(out)))
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Any]:
        out: List[Any] = []
        for bundle in self._stream():
            for ref, _ in bundle.blocks:
                out.extend(BlockAccessor.for_block(ray_get(ref)).iter_rows())
        return out

    def take_batch(self, batch_size: int = 20, *, batch_format: str = "default"):
        fmt = batch_format if batch_format != "default" else \
            DataContext.get_current().default_batch_format
        for batch in self.iter_batches(batch_size=batch_size, batch_format=fmt):
            return batch
        raise ValueError("dataset is empty")

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def schema(self):
        for bundle in self._stream():
            for ref, meta in bundle.blocks:
                if meta.schema is not None:
                    return meta.schema
                return BlockAccessor.for_block(ray_get(ref)).schema()
        return None

    def columns(self) -> Optional[List[str]]:
        s = self.schema()
        return list(s.names) if isinstance(s, pa.Schema) else None

    def num_blocks(self) -> int:
        return sum(len(b.blocks) for b in self._execute())

    def size_bytes(self) -> int:
        return sum(b.size_bytes() for b in self._execute())

    def materialize(self) -> "Dataset":
        self._execute()
        out = Dataset(L.InputData(bundles=self._materialized))
        out._materialized = self._materialized
        return out

    def stats(self) -> str:
        bundles = self._execute()
        rows = sum(b.num_rows() or 0 for b in bundles)
        return (f"Dataset: {len(bundles)} bundles, "
                f"{sum(len(b.blocks) for b in bundles)} blocks, {rows} rows, "
                f"{sum(b.size_bytes() for b in bundles)} bytes")

    # -- iteration -----------------------------------------------------------
    def iter_rows(self) -> Iterator[Any]:
        for bundle in self._stream():
            for ref, _ in bundle.blocks:
                yield from BlockAccessor.for_block(ray_get(ref)).iter_rows()

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "default", prefetch_batches: int = 1,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        from .iterator import iter_batches_over_bundles
        yield from iter_batches_over_bundles(
            self._stream(), batch_size=batch_size, batch_format=batch_format,
            prefetch_batches=prefetch_batches, drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           dtypes=None, device: Optional[str] = None,
                           **kwargs) -> Iterator[Any]:
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            out = {}
            for k, v in batch.items():
                t = torch.as_tensor(v)
                if device:
                    t = t.to(device)
                out[k] = t
            yield out

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, **kwargs) -> Iterator[Any]:
        """TPU-first batch iterator: yields dicts of jax.Arrays, optionally
        placed with a NamedSharding (device_put overlapped with consumption)."""
        import jax
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            if sharding is not None:
                yield {k: jax.device_put(v, sharding) for k, v in batch.items()}
            else:
                yield {k: jax.numpy.asarray(v) for k, v in batch.items()}

    def streaming_split(self, n: int, *, equal: bool = False,
                        locality_hints=None) -> List["DataIterator"]:
        from .iterator import build_streaming_split
        return build_streaming_split(self, n, equal=equal)

    def split(self, n: int) -> List["Dataset"]:
        bundles = self._execute()
        blocks = [blk for b in bundles for blk in b.blocks]
        out = []
        for i in range(n):
            part = blocks[i::n]
            ds = Dataset(L.InputData(bundles=[RefBundle(part)] if part else []))
            ds._materialized = [RefBundle(part)] if part else []
            out.append(ds)
        return out

    # -- export --------------------------------------------------------------
    def to_pandas(self):
        import pandas as pd
        dfs = []
        for bundle in self._stream():
            for ref, _ in bundle.blocks:
                dfs.append(BlockAccessor.for_block(ray_get(ref)).to_pandas())
        return pd.concat(dfs, ignore_index=True) if dfs else pd.DataFrame()

    def to_arrow(self) -> pa.Table:
        ts = []
        for bundle in self._stream():
            for ref, _ in bundle.blocks:
                ts.append(BlockAccessor.for_block(ray_get(ref)).to_arrow())
        return pa.concat_tables(ts, promote_options="default") if ts else pa.table({})

    def to_numpy(self) -> Dict[str, np.ndarray]:
        return BlockAccessor.for_block(self.to_arrow()).to_numpy()

    def _write(self, path: str, fmt: str, **writer_args):
        ds = self._with(L.Write(path=path, file_format=fmt,
                                writer_args=writer_args))
        paths = []
        for bundle in execute_to_bundles(plan(ds._logical), "write"):
            for ref, _ in bundle.blocks:
                paths.extend(ray_get(ref).column("path").to_pylist())
        return paths

    def write_parquet(self, path: str, **kw):
        return self._write(path, "parquet", **kw)

    def write_csv(self, path: str, **kw):
        return self._write(path, "csv", **kw)

    def write_json(self, path: str, **kw):
        return self._write(path, "json", **kw)

    def write_numpy(self, path: str, **kw):
        return self._write(path, "npy", **kw)

    def write_tfrecords(self, path: str, **kw):
        return self._write(path, "tfrecords", **kw)

    def write_orc(self, path: str, **kw):
        return self._write(path, "orc", **kw)

    def write_webdataset(self, path: str, **kw):
        return self._write(path, "tar", **kw)

    def write_sql(self, sql: str, connection_factory) -> int:
        """Execute ``sql`` (an INSERT with ? placeholders) once per row;
        returns rows written (reference: ``Dataset.write_sql``)."""
        n = 0
        conn = connection_factory()
        try:
            cur = conn.cursor()
            for bundle in self._stream():
                for ref, _ in bundle.blocks:
                    acc = BlockAccessor.for_block(ray_get(ref))
                    rows = [tuple(r.values()) if isinstance(r, dict) else (r,)
                            for r in acc.iter_rows()]
                    cur.executemany(sql, rows)  # one round trip per block
                    n += len(rows)
            conn.commit()
        finally:
            conn.close()
        return n

    def write_mongo(self, uri: str, database: str, collection: str, *,
                    client_factory=None) -> int:
        """Insert every row as a document; returns documents written
        (reference: ``Dataset.write_mongo``; client_factory injects the
        pymongo client on this no-pymongo image).  One client serves the
        whole write, like write_sql's single connection."""
        from .datasource import _close_quietly, _default_mongo_client
        factory = client_factory or _default_mongo_client(uri)
        n = 0
        client = factory()
        try:
            coll = client[database][collection]
            for bundle in self._stream():
                for ref, _ in bundle.blocks:
                    acc = BlockAccessor.for_block(ray_get(ref))
                    docs = [dict(r) if isinstance(r, dict) else {"value": r}
                            for r in acc.iter_rows()]
                    if docs:
                        coll.insert_many(docs)
                        n += len(docs)
        finally:
            _close_quietly(client)
        return n

    def __repr__(self):
        names = [op.name() for op in self._logical.chain()]
        return f"Dataset({' -> '.join(names)})"


class GroupedData:
    """Reference: ``python/ray/data/grouped_data.py``."""

    def __init__(self, ds: Dataset, key: Optional[str]):
        self._ds = ds
        self._key = key

    def aggregate(self, *aggs: AggregateFn) -> Dataset:
        return self._ds._with(L.Aggregate(key=self._key, aggs=list(aggs)))

    def count(self) -> Dataset:
        return self.aggregate(Count())

    def sum(self, on: str) -> Dataset:
        return self.aggregate(Sum(on))

    def min(self, on: str) -> Dataset:
        return self.aggregate(Min(on))

    def max(self, on: str) -> Dataset:
        return self.aggregate(Max(on))

    def mean(self, on: str) -> Dataset:
        return self.aggregate(Mean(on))

    def std(self, on: str, ddof: int = 1) -> Dataset:
        return self.aggregate(Std(on, ddof))

    def map_groups(self, fn: Callable, *, batch_format: str = "default") -> Dataset:
        key = self._key
        sorted_ds = self._ds.sort(key) if key else self._ds

        def apply_groups(t: pa.Table):
            import pyarrow.compute as pc
            outs = []
            if t.num_rows == 0:
                return t
            keys = t.column(key).to_numpy(zero_copy_only=False)
            uniq = list(dict.fromkeys(keys.tolist()))
            fmt = batch_format if batch_format != "default" else \
                DataContext.get_current().default_batch_format
            for kv in uniq:
                sub = t.filter(pc.equal(t.column(key), pa.scalar(kv)))
                batch = BlockAccessor.for_block(sub).to_batch(fmt)
                out = fn(batch)
                from .block import batch_to_block
                outs.append(BlockAccessor.for_block(batch_to_block(out)).to_arrow())
            return pa.concat_tables(outs, promote_options="default")

        return sorted_ds.map_batches(apply_groups, batch_format="pyarrow",
                                     batch_size=None)
