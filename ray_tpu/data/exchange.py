"""All-to-all exchanges: shuffle, repartition, sort, groupby-aggregate.

Reference: ``python/ray/data/_internal/planner/exchange/`` and
``push_based_shuffle.py`` — a two-stage exchange: map tasks partition each
input block into N sub-blocks; reduce tasks merge partition i from every map
task. Driver coordinates over refs only (no block data crosses the driver).
"""

from __future__ import annotations

from typing import Any, Callable, List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..core.api import get as ray_get
from ..core.api import put as ray_put
from ..core.api import remote as ray_remote
from .aggregate import AggregateFn
from .block import Block, BlockAccessor, BlockMetadata
from .operators import RefBundle


# -- remote task bodies -----------------------------------------------------

def _split_block(block: Block, n: int, mode: str, meta: Any) -> List[Block]:
    """Partition one block into n sub-blocks. mode: 'random'|'hash'|'range'|'round'."""
    t = BlockAccessor.for_block(block).to_arrow()
    rows = t.num_rows
    if rows == 0:
        return [t.slice(0, 0)] * n
    if mode == "random":
        seed = meta
        rng = np.random.default_rng(seed)
        assign = rng.integers(0, n, size=rows)
    elif mode == "round":
        assign = np.arange(rows) % n
    elif mode == "hash":
        # Stable across worker processes (Python's hash() is salted per
        # process, which would scatter equal keys to different partitions).
        import zlib
        key = meta
        col = t.column(key).to_numpy(zero_copy_only=False)
        assign = np.array([zlib.crc32(repr(v).encode()) % n for v in col])
    elif mode == "range":
        key, boundaries, descending = meta
        col = t.column(key).to_numpy(zero_copy_only=False)
        assign = np.searchsorted(np.asarray(boundaries), col,
                                 side="right")
        if descending:
            assign = (n - 1) - assign
    else:
        raise ValueError(mode)
    out = []
    for i in range(n):
        mask = assign == i
        out.append(t.filter(pa.array(mask)))
    return out


def _merge_blocks(sort_key, descending: bool, *parts: Block) -> List[tuple]:
    tables = [BlockAccessor.for_block(p).to_arrow() for p in parts
              if BlockAccessor.for_block(p).num_rows() > 0]
    if not tables:
        return []
    merged = pa.concat_tables(tables, promote_options="default")
    if sort_key is not None:
        order = "descending" if descending else "ascending"
        merged = merged.sort_by([(sort_key, order)])
    return [(ray_put(merged), BlockAccessor.for_block(merged).metadata())]


def _agg_partition(key: Optional[str], aggs: List[AggregateFn], *parts: Block
                   ) -> List[tuple]:
    tables = [BlockAccessor.for_block(p).to_arrow() for p in parts
              if BlockAccessor.for_block(p).num_rows() > 0]
    if not tables:
        return []
    merged = pa.concat_tables(tables, promote_options="default")
    if key is None:
        row = {a.name: a.finalize(a.block_acc(merged)) for a in aggs}
        t = pa.table({k: [v] for k, v in row.items()})
    else:
        groups: dict = {}
        keycol = merged.column(key).to_numpy(zero_copy_only=False)
        uniq = pa.compute.unique(merged.column(key)).to_pylist()
        cols: dict = {key: []}
        for a in aggs:
            cols[a.name] = []
        for kv in sorted(uniq, key=lambda x: (x is None, x)):
            mask = pa.array(keycol == kv) if kv is not None else pa.array(
                [v is None for v in keycol])
            sub = merged.filter(mask)
            cols[key].append(kv)
            for a in aggs:
                cols[a.name].append(a.finalize(a.block_acc(sub)))
        t = pa.table(cols)
    return [(ray_put(t), BlockAccessor.for_block(t).metadata())]


def _sample_block(block: Block, key: str, n: int, seed: int) -> list:
    t = BlockAccessor.for_block(block).to_arrow()
    if t.num_rows == 0:
        return []
    rng = np.random.default_rng(seed)
    idx = rng.choice(t.num_rows, size=min(n, t.num_rows), replace=False)
    return t.column(key).take(pa.array(idx)).to_pylist()


# -- driver-side exchange builders -----------------------------------------

def _all_refs(bundles: List[RefBundle]) -> List[Tuple[Any, BlockMetadata]]:
    out = []
    for b in bundles:
        out.extend(b.blocks)
    return out


def run_exchange(bundles: List[RefBundle], *, num_outputs: Optional[int],
                 mode: str, meta_for_block: Callable[[int], Any],
                 sort_key=None, descending: bool = False,
                 reduce_fn=None) -> List[RefBundle]:
    """Generic 2-stage exchange over block refs."""
    blocks = _all_refs(bundles)
    if not blocks:
        return []
    n_out = num_outputs or len(blocks)
    split = ray_remote(_split_block).options(num_returns=n_out if n_out > 1 else 1)
    # Map stage: split every block into n_out partitions.
    parts: List[List[Any]] = [[] for _ in range(n_out)]
    for i, (ref, _) in enumerate(blocks):
        res = split.remote(ref, n_out, mode, meta_for_block(i))
        if n_out == 1:
            res = [res]
        for j, r in enumerate(res):
            parts[j].append(r)
    # Reduce stage.
    reduce_task = ray_remote(reduce_fn or _merge_blocks)
    out_refs = []
    for j in range(n_out):
        if reduce_fn is None:
            out_refs.append(reduce_task.remote(sort_key, descending, *parts[j]))
        else:
            out_refs.append(reduce_task.remote(*parts[j]))
    out: List[RefBundle] = []
    for r in out_refs:
        bundle_list = ray_get(r)
        if bundle_list:
            out.append(RefBundle(list(bundle_list)))
    return out


def random_shuffle_fn(seed: Optional[int], num_outputs: Optional[int]):
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        base = seed if seed is not None else np.random.randint(0, 2**31)
        return run_exchange(bundles, num_outputs=num_outputs, mode="random",
                            meta_for_block=lambda i: base + i)
    return bulk


def repartition_fn(num_outputs: int, shuffle: bool):
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        if shuffle:
            return run_exchange(bundles, num_outputs=num_outputs, mode="round",
                                meta_for_block=lambda i: None)
        # Fast path: split/concat by row counts without a full exchange.
        return _repartition_by_slicing(bundles, num_outputs)
    return bulk


def _repartition_by_slicing(bundles: List[RefBundle], n: int) -> List[RefBundle]:
    blocks = _all_refs(bundles)
    total = sum(m.num_rows or 0 for _, m in blocks)
    if total == 0:
        return []
    per = -(-total // n)
    # Build slice plan: output i takes rows [i*per, min((i+1)*per, total)).
    slice_task = ray_remote(_slice_concat)
    spans = []  # per input block: (ref, start_row_global)
    acc = 0
    for ref, m in blocks:
        spans.append((ref, acc, acc + (m.num_rows or 0)))
        acc += m.num_rows or 0
    out = []
    for i in range(n):
        lo, hi = i * per, min((i + 1) * per, total)
        if lo >= hi:
            break
        pieces = []
        for ref, s, e in spans:
            os_, oe = max(lo, s), min(hi, e)
            if os_ < oe:
                pieces.append((ref, os_ - s, oe - s))
        refs = [p[0] for p in pieces]
        cuts = [(p[1], p[2]) for p in pieces]
        out_ref = slice_task.remote(cuts, *refs)
        bl = ray_get(out_ref)
        if bl:
            out.append(RefBundle(list(bl)))
    return out


def _slice_concat(cuts: List[Tuple[int, int]], *blocks: Block) -> List[tuple]:
    tables = []
    for (s, e), b in zip(cuts, blocks):
        t = BlockAccessor.for_block(b).to_arrow().slice(s, e - s)
        if t.num_rows:
            tables.append(t)
    if not tables:
        return []
    merged = pa.concat_tables(tables, promote_options="default")
    return [(ray_put(merged), BlockAccessor.for_block(merged).metadata())]


def sort_fn(key: str, descending: bool):
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        blocks = _all_refs(bundles)
        if not blocks:
            return []
        n_out = len(blocks)
        # Sample boundaries.
        sample = ray_remote(_sample_block)
        sample_refs = [sample.remote(ref, key, 20, i) for i, (ref, _) in
                       enumerate(blocks)]
        samples = sorted(s for lst in ray_get(sample_refs) for s in lst)
        if not samples:
            return []
        if n_out > 1:
            qs = np.linspace(0, len(samples) - 1, n_out + 1)[1:-1]
            boundaries = [samples[int(q)] for q in qs]
            # dedupe to keep searchsorted monotonic
            boundaries = sorted(set(boundaries))
        else:
            boundaries = []
        n_out = len(boundaries) + 1
        return run_exchange(bundles, num_outputs=n_out, mode="range",
                            meta_for_block=lambda i: (key, boundaries, descending),
                            sort_key=key, descending=descending)
    return bulk


def aggregate_fn(key: Optional[str], aggs: List[AggregateFn]):
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        blocks = _all_refs(bundles)
        if not blocks:
            return []
        if key is None:
            # Global aggregate: single reduce over all blocks.
            task = ray_remote(_agg_partition)
            res = ray_get(task.remote(None, aggs, *[r for r, _ in blocks]))
            return [RefBundle(list(res))] if res else []
        n_out = min(len(blocks), 8)
        return run_exchange(bundles, num_outputs=n_out, mode="hash",
                            meta_for_block=lambda i: key,
                            reduce_fn=lambda *parts: _agg_partition(key, aggs, *parts))
    return bulk


def randomize_block_order_fn(seed: Optional[int]):
    def bulk(bundles: List[RefBundle]) -> List[RefBundle]:
        blocks = _all_refs(bundles)
        rng = np.random.default_rng(seed)
        idx = rng.permutation(len(blocks))
        return [RefBundle([blocks[i]]) for i in idx]
    return bulk


def zip_fn(right_bundles_getter: Callable[[], List[RefBundle]]):
    def bulk(left: List[RefBundle]) -> List[RefBundle]:
        right = right_bundles_getter()
        lrefs = _all_refs(left)
        rrefs = _all_refs(right)
        task = ray_remote(_zip_all)
        res = ray_get(task.remote([r for r, _ in lrefs], [r for r, _ in rrefs]))
        return [RefBundle(list(res))] if res else []
    return bulk


def _zip_all(left_refs, right_refs) -> List[tuple]:
    lt = [BlockAccessor.for_block(ray_get(r)).to_arrow() for r in left_refs]
    rt = [BlockAccessor.for_block(ray_get(r)).to_arrow() for r in right_refs]
    lcat = pa.concat_tables(lt, promote_options="default") if lt else pa.table({})
    rcat = pa.concat_tables(rt, promote_options="default") if rt else pa.table({})
    if lcat.num_rows != rcat.num_rows:
        raise ValueError(
            f"zip requires equal row counts, got {lcat.num_rows} vs {rcat.num_rows}")
    cols = {}
    for name in lcat.column_names:
        cols[name] = lcat.column(name)
    for name in rcat.column_names:
        out_name = name if name not in cols else f"{name}_1"
        cols[out_name] = rcat.column(name)
    t = pa.table(cols)
    return [(ray_put(t), BlockAccessor.for_block(t).metadata())]
