"""Streaming executor: pull-based pipelined execution with backpressure.

Reference: ``python/ray/data/_internal/execution/streaming_executor.py`` (:49
executor thread, ``run`` :180) and ``streaming_executor_state.py``
(``process_completed_tasks`` :313, ``select_operator_to_run`` :376). The loop:
move finished task outputs downstream, then dispatch new tasks preferring the
most-downstream operator with ready input, subject to per-op in-flight caps and
a global queued-bytes budget.
"""

from __future__ import annotations

import queue
import threading
import traceback
from typing import Iterator, List, Optional

from ..core.api import wait as ray_wait
from .context import DataContext
from .operators import PhysicalOperator, RefBundle

_SENTINEL = object()


class ExecutionError(RuntimeError):
    pass


def _toposort(out_op: PhysicalOperator) -> List[PhysicalOperator]:
    order: List[PhysicalOperator] = []
    seen = set()

    def visit(op):
        if id(op) in seen:
            return
        seen.add(id(op))
        for i in op.input_ops:
            visit(i)
        order.append(op)

    visit(out_op)
    return order


class StreamingExecutor:
    """Executes an operator DAG, streaming final-op outputs to the consumer."""

    def __init__(self, output_op: PhysicalOperator, name: str = "dataset"):
        self._out_op = output_op
        self._topology = _toposort(output_op)
        self._outq: "queue.Queue" = queue.Queue(maxsize=64)
        self._error: Optional[BaseException] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run_safe,
                                        name=f"StreamingExecutor-{name}",
                                        daemon=True)
        self.ctx = DataContext.get_current()

    # -- public -------------------------------------------------------------
    def start(self) -> Iterator[RefBundle]:
        self._thread.start()
        return self._iter_outputs()

    def stop(self):
        self._stop.set()

    def _iter_outputs(self) -> Iterator[RefBundle]:
        while True:
            item = self._outq.get()
            if item is _SENTINEL:
                if self._error is not None:
                    raise ExecutionError(
                        f"dataset execution failed: {self._error}") from self._error
                return
            yield item

    # -- loop ---------------------------------------------------------------
    def _run_safe(self):
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            self._error = e
            traceback.print_exc()
        finally:
            for op in self._topology:
                try:
                    op.shutdown()
                except Exception:
                    pass
            self._outq.put(_SENTINEL)

    def _downstream_of(self, op: PhysicalOperator) -> Optional[PhysicalOperator]:
        for other in self._topology:
            if op in other.input_ops:
                return other
        return None

    def _run(self):
        topo = self._topology
        while not self._stop.is_set():
            progressed = False

            # 1. Move outputs downstream; propagate done-ness.
            for op in topo:
                down = self._downstream_of(op)
                while op.output_queue:
                    bundle = op.output_queue.popleft()
                    progressed = True
                    if down is None:
                        self._outq.put(bundle)
                    else:
                        down.add_input(bundle)
                if down is not None and op.is_finished() and not op.output_queue:
                    if not down._inputs_done and all(
                            i.is_finished() and not i.output_queue
                            for i in down.input_ops):
                        down.mark_inputs_done()
                        progressed = True

            # 2. Check termination.
            if all(op.is_finished() and not op.output_queue for op in topo):
                return

            # 3. Dispatch, most-downstream first (keeps the pipeline draining).
            total_queued = sum(op.queued_bytes() for op in topo)
            over_budget = (total_queued
                           > self.ctx.streaming_output_backpressure_bytes)
            for op in reversed(topo):
                while op.can_dispatch():
                    op.dispatch_one()
                    progressed = True
                if over_budget and op.input_queue:
                    # Under pressure, only the most-downstream op with queued
                    # input gets to run; skip dispatching anything upstream.
                    break

            # 4. Drain streaming-generator yields (non-blocking): blocks
            # flow downstream while their producing tasks are still running.
            streams_live = False
            for op in topo:
                if op.gen_in_flight:
                    streams_live = True
                    if op.poll_streams():
                        progressed = True

            # 5. Wait for any in-flight task.
            in_flight = {}
            for op in topo:
                for ref in op.pending_refs():
                    in_flight[ref] = op
            if in_flight:
                ready, _ = ray_wait(list(in_flight), num_returns=1, timeout=0.1)
                for ref in ready:
                    in_flight[ref].on_task_done(ref)
                    progressed = True
            elif not progressed:
                # Nothing moved: park briefly (short tick while streams are
                # live so fresh yields are picked up promptly).
                self._stop.wait(0.005 if streams_live else 0.02)


def execute_to_bundles(output_op: PhysicalOperator, name: str = "dataset"
                       ) -> List[RefBundle]:
    ex = StreamingExecutor(output_op, name)
    return list(ex.start())
