"""Physical operators for the streaming executor.

Reference: ``python/ray/data/_internal/execution/operators/`` — operators hold
input queues of ``RefBundle``s, dispatch distributed tasks over block refs, and
expose completed outputs for the executor loop to move downstream.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

from .. import core as _core_api  # noqa: F401  (ray_tpu.core re-exports api)
from ..core.api import get as ray_get
from ..core.api import put as ray_put
from ..core.api import remote as ray_remote
from ..core.object_ref import ObjectRef
from .block import Block, BlockAccessor, BlockMetadata, DelegatingBlockBuilder
from .context import DataContext
from .datasource import ReadTask, write_block


@dataclass
class RefBundle:
    """A group of (block ref, metadata) pairs — the unit moved between
    operators (reference: ``_internal/execution/interfaces/ref_bundle.py``)."""

    blocks: List[Tuple[ObjectRef, BlockMetadata]]

    def num_rows(self) -> Optional[int]:
        total = 0
        for _, m in self.blocks:
            if m.num_rows is None:
                return None
            total += m.num_rows
        return total

    def size_bytes(self) -> int:
        return sum(m.size_bytes or 0 for _, m in self.blocks)

    def refs(self) -> List[ObjectRef]:
        return [r for r, _ in self.blocks]


# ---------------------------------------------------------------------------
# Map transformer: the fused chain of row/batch transforms run inside one task
# ---------------------------------------------------------------------------

@dataclass
class MapStage:
    kind: str  # "batches" | "rows" | "filter" | "flat_map" | "write"
    fn: Any  # callable, or class when constructor is not None
    batch_size: Optional[int] = None
    batch_format: str = "numpy"
    fn_args: Tuple = ()
    fn_kwargs: Dict[str, Any] = None
    is_class: bool = False
    fn_constructor_args: Tuple = ()


def _iter_batches_of(blocks: Iterable[Block], batch_size: Optional[int],
                     batch_format: str):
    """Re-batch a stream of blocks to `batch_size` rows (None = per-block)."""
    if batch_size is None:
        for b in blocks:
            acc = BlockAccessor.for_block(b)
            if acc.num_rows():
                yield acc.to_batch(batch_format)
        return
    builder = DelegatingBlockBuilder()
    for b in blocks:
        acc = BlockAccessor.for_block(b)
        n = acc.num_rows()
        i = 0
        while i < n:
            take = min(batch_size - builder.num_rows(), n - i)
            builder.add_block(acc.slice(i, i + take))
            i += take
            if builder.num_rows() >= batch_size:
                out = builder.build()
                builder = DelegatingBlockBuilder()
                yield BlockAccessor.for_block(out).to_batch(batch_format)
    if builder.num_rows():
        yield BlockAccessor.for_block(builder.build()).to_batch(batch_format)


def _instantiate(stage: MapStage, cache: dict):
    fn = stage.fn
    if stage.is_class:
        key = id(stage.fn)
        if key not in cache:
            cache[key] = stage.fn(*stage.fn_constructor_args)
        fn = cache[key]
    return fn


def apply_stages(stages: List[MapStage], blocks: Iterable[Block],
                 target_block_size: int, fn_cache: Optional[dict] = None
                 ) -> Iterable[Block]:
    """Run the fused stage chain over input blocks, yielding output blocks of
    bounded size."""
    from .block import batch_to_block
    fn_cache = fn_cache if fn_cache is not None else {}
    stream: Iterable[Block] = blocks
    for stage in stages:
        fn = _instantiate(stage, fn_cache)
        if stage.kind == "batches":
            def gen_batches(stream=stream, stage=stage, fn=fn):
                for batch in _iter_batches_of(stream, stage.batch_size,
                                              stage.batch_format):
                    out = fn(batch, *stage.fn_args, **(stage.fn_kwargs or {}))
                    if hasattr(out, "__next__"):  # generator UDF
                        for o in out:
                            yield batch_to_block(o)
                    else:
                        yield batch_to_block(out)
            stream = gen_batches()
        elif stage.kind in ("rows", "filter", "flat_map"):
            def gen_rows(stream=stream, stage=stage, fn=fn):
                builder = DelegatingBlockBuilder()
                for b in stream:
                    for row in BlockAccessor.for_block(b).iter_rows():
                        if stage.kind == "rows":
                            builder.add(fn(row, *stage.fn_args,
                                           **(stage.fn_kwargs or {})))
                        elif stage.kind == "filter":
                            if fn(row, *stage.fn_args, **(stage.fn_kwargs or {})):
                                builder.add(row)
                        else:
                            for o in fn(row, *stage.fn_args,
                                        **(stage.fn_kwargs or {})):
                                builder.add(o)
                        if builder.num_rows() >= 64 * 1024:
                            yield builder.build()
                            builder = DelegatingBlockBuilder()
                if builder.num_rows():
                    yield builder.build()
            stream = gen_rows()
        else:
            raise ValueError(f"unknown stage kind {stage.kind}")
    # Final size-bounded re-blocking.
    for b in stream:
        yield b


# ---------------------------------------------------------------------------
# Remote task bodies (module-level so the function registry ships them once)
# ---------------------------------------------------------------------------

def _bundle_of(blocks: Iterable[Block], input_files=None) -> List[tuple]:
    out = []
    for b in blocks:
        acc = BlockAccessor.for_block(b)
        if acc.num_rows() == 0:
            continue
        out.append((ray_put(b), acc.metadata(input_files)))
    return out


def _run_read_task(task: ReadTask) -> List[tuple]:
    return _bundle_of(task(), input_files=task.metadata.input_files)


def _run_map_task(stages: List[MapStage], target_block_size: int,
                  *blocks: Block) -> List[tuple]:
    return _bundle_of(apply_stages(stages, blocks, target_block_size))


def _yield_block_pairs(blocks: Iterable[Block], input_files=None):
    """Streaming body core: alternately yield block, then its metadata, per
    non-empty output block (reference:
    data/_internal/execution/operators/map_operator.py generator returns).

    The block ships as a streamed RETURN object (caller-owned) rather than a
    worker-side ray_put: a put inside the task leaves the transient worker
    (or pool actor) as the ref's owner, and its idle-reaping/shutdown would
    strand every block it produced ("owner died") before downstream
    consumed them."""
    for b in blocks:
        acc = BlockAccessor.for_block(b)
        if acc.num_rows() == 0:
            continue
        yield b
        yield acc.metadata(input_files)


def _stream_map_task(stages: List[MapStage], target_block_size: int,
                     *blocks: Block):
    yield from _yield_block_pairs(
        apply_stages(stages, blocks, target_block_size))


def _stream_read_task(task: ReadTask):
    yield from _yield_block_pairs(task(),
                                  input_files=task.metadata.input_files)


def _run_write_task(path: str, file_format: str, writer_args: dict,
                    index: int, *blocks: Block) -> List[tuple]:
    import pyarrow as pa
    paths = []
    for j, b in enumerate(blocks):
        paths.append(write_block(b, path, file_format, index * 1000 + j,
                                 **writer_args))
    t = pa.table({"path": pa.array(paths)})
    return [(ray_put(t), BlockAccessor.for_block(t).metadata())]


def _slice_block_task(block: Block, start: int, end: int) -> List[tuple]:
    out = BlockAccessor.for_block(block).slice(start, end)
    return [(ray_put(out), BlockAccessor.for_block(out).metadata())]


# ---------------------------------------------------------------------------
# Physical operators
# ---------------------------------------------------------------------------

class PhysicalOperator:
    def __init__(self, name: str, input_ops: List["PhysicalOperator"]):
        self.name = name
        self.input_ops = input_ops
        self.input_queue: collections.deque[RefBundle] = collections.deque()
        self.output_queue: collections.deque[RefBundle] = collections.deque()
        self._inputs_done = False
        self.in_flight: Dict[ObjectRef, Any] = {}
        # completed-but-unreleased task results (see on_task_done ordering)
        self._done_tasks: Dict[ObjectRef, Any] = {}
        # streaming-generator tasks currently producing for this operator
        self.gen_in_flight: List[Any] = []
        self.ctx = DataContext.get_current()
        self.metrics = collections.Counter()

    # input side
    def add_input(self, bundle: RefBundle):
        self.input_queue.append(bundle)

    def mark_inputs_done(self):
        self._inputs_done = True

    def queued_bytes(self) -> int:
        return sum(b.size_bytes() for b in self.input_queue)

    # work dispatch
    def can_dispatch(self) -> bool:
        return (bool(self.input_queue)
                and len(self.in_flight) + len(self.gen_in_flight)
                < self.ctx.max_tasks_in_flight_per_op)

    def dispatch_one(self):
        raise NotImplementedError

    def pending_refs(self) -> List[ObjectRef]:
        """Refs the executor should still wait on (completed-but-held results
        are excluded so they aren't re-delivered)."""
        return [r for r in self.in_flight if r not in self._done_tasks]

    def on_task_done(self, ref: ObjectRef):
        """Buffer out-of-order completions; release results in DISPATCH order
        (in_flight's insertion order), so downstream block order matches the
        input order instead of ray_wait readiness order."""
        self._done_tasks[ref] = ray_get(ref)
        while self.in_flight:
            first = next(iter(self.in_flight))
            if first not in self._done_tasks:
                break
            ctx = self.in_flight.pop(first)
            self._handle_result(ctx, self._done_tasks.pop(first))
            self.metrics["tasks_finished"] += 1

    def poll_streams(self) -> bool:
        """Drain whatever streaming tasks have yielded so far (non-blocking).
        Each yield is one (block_ref, metadata) pair — it becomes an output
        bundle immediately, while the producing task keeps running.

        Yields are released in task-DISPATCH order: only the head stream
        feeds the output queue; younger streams hold their yields (bounded
        by generator_backpressure) until the head completes.  Without this,
        whichever task yields first wins and take()/iteration order diverges
        from the buffered path."""
        progressed = False
        while self.gen_in_flight:
            g = self.gen_in_flight[0]
            while True:
                ref = g.try_next()
                if ref is None:
                    break
                # Yields alternate block, metadata (see _yield_block_pairs):
                # the block ref passes through un-fetched; only the small
                # metadata yield is materialized here.
                pending = getattr(g, "_pending_block", None)
                if pending is None:
                    g._pending_block = ref
                else:
                    g._pending_block = None
                    self._handle_result(None, [(pending, ray_get(ref))])
                progressed = True
            if not g.completed():
                break
            pending = getattr(g, "_pending_block", None)
            if pending is not None:
                # A lone trailing yield is the task's error item (pairs are
                # produced atomically): fetching it raises the task error.
                g._pending_block = None
                ray_get(pending)
            self.gen_in_flight.pop(0)
            self._on_stream_complete(g)
            self.metrics["tasks_finished"] += 1
            progressed = True  # next stream's buffered yields drain next pass
        return progressed

    def _on_stream_complete(self, g) -> None:
        """Hook: a streaming task finished and was released (ActorPool uses
        this to return the producing actor to the idle pool)."""

    def _handle_result(self, ctx, bundle_list):
        metas = [BlockMetadata(**m.__dict__) if not isinstance(m, BlockMetadata)
                 else m for _, m in bundle_list]
        bundle = RefBundle(list(zip([r for r, _ in bundle_list], metas)))
        if bundle.blocks:
            self.output_queue.append(bundle)
        self.metrics["rows_out"] += bundle.num_rows() or 0

    # completion
    def is_finished(self) -> bool:
        return (self._inputs_done and not self.input_queue
                and not self.in_flight and not self.gen_in_flight)

    def shutdown(self):
        pass


class InputDataBuffer(PhysicalOperator):
    """Holds pre-materialized bundles; no tasks."""

    def __init__(self, bundles: List[RefBundle]):
        super().__init__("Input", [])
        self.output_queue.extend(bundles)
        self._inputs_done = True

    def can_dispatch(self):
        return False

    def is_finished(self):
        return True


class ReadOperator(PhysicalOperator):
    def __init__(self, name: str, read_tasks: List[ReadTask]):
        super().__init__(name, [])
        self._tasks = collections.deque(read_tasks)
        self._inputs_done = True
        self._remote = ray_remote(_run_read_task)
        self._stream_remote = ray_remote(_stream_read_task).options(
            num_returns="streaming",
            # 2 yields per block: keep the backpressure knob block-denominated
            generator_backpressure=2 * self.ctx.generator_backpressure)

    def can_dispatch(self):
        return (bool(self._tasks)
                and len(self.in_flight) + len(self.gen_in_flight)
                < self.ctx.max_tasks_in_flight_per_op)

    def dispatch_one(self):
        task = self._tasks.popleft()
        if self.ctx.use_streaming_generators:
            self.gen_in_flight.append(self._stream_remote.remote(task))
            return
        ref = self._remote.remote(task)
        self.in_flight[ref] = task

    def is_finished(self):
        return (not self._tasks and not self.in_flight
                and not self.gen_in_flight)


class TaskPoolMapOperator(PhysicalOperator):
    def __init__(self, name: str, input_op: PhysicalOperator,
                 stages: List[MapStage], ray_remote_args: Dict[str, Any] = None):
        super().__init__(name, [input_op])
        self._stages = stages
        self._remote = ray_remote(_run_map_task).options(**(ray_remote_args or {}))
        self._stream_remote = ray_remote(_stream_map_task).options(
            num_returns="streaming",
            generator_backpressure=2 * self.ctx.generator_backpressure,
            **(ray_remote_args or {}))

    def dispatch_one(self):
        bundle = self.input_queue.popleft()
        if self.ctx.use_streaming_generators:
            gen = self._stream_remote.remote(self._stages,
                                             self.ctx.target_max_block_size,
                                             *bundle.refs())
            self.gen_in_flight.append(gen)
            return
        ref = self._remote.remote(self._stages,
                                  self.ctx.target_max_block_size,
                                  *bundle.refs())
        self.in_flight[ref] = bundle


class _MapWorker:
    """Actor hosting a constructed class-based UDF (reference:
    ``actor_pool_map_operator.py`` ``_MapWorker``)."""

    def __init__(self):
        self._fn_cache: dict = {}

    def ready(self):
        return "ok"

    def run(self, stages, target_block_size, *blocks):
        return _bundle_of(apply_stages(stages, blocks, target_block_size,
                                       fn_cache=self._fn_cache))

    def run_stream(self, stages, target_block_size, *blocks):
        yield from _yield_block_pairs(apply_stages(
            stages, blocks, target_block_size, fn_cache=self._fn_cache))


class ActorPoolMapOperator(PhysicalOperator):
    def __init__(self, name: str, input_op: PhysicalOperator,
                 stages: List[MapStage], min_size: int, max_size: int,
                 ray_remote_args: Dict[str, Any] = None):
        super().__init__(name, [input_op])
        self._stages = stages
        self._min, self._max = min_size, max_size
        self._remote_args = ray_remote_args or {}
        self._actors: List[Any] = []
        self._idle: collections.deque = collections.deque()
        self._gen_actor: Dict[int, Any] = {}  # id(gen) -> producing actor
        self._started = False

    def _ensure_actors(self):
        if self._started:
            return
        self._started = True
        from ..core.actor import ActorClass
        cls = ActorClass(_MapWorker, dict(self._remote_args))
        for _ in range(self._min):
            a = cls.remote()
            self._actors.append(a)
            self._idle.append(a)

    def can_dispatch(self):
        self._ensure_actors()
        if not self.input_queue:
            return False
        if self._idle:
            return True
        if len(self._actors) < self._max:
            from ..core.actor import ActorClass
            a = ActorClass(_MapWorker, dict(self._remote_args)).remote()
            self._actors.append(a)
            self._idle.append(a)
            return True
        return False

    def dispatch_one(self):
        bundle = self.input_queue.popleft()
        actor = self._idle.popleft()
        if self.ctx.use_streaming_generators:
            g = actor.run_stream.options(
                num_returns="streaming",
                generator_backpressure=2 * self.ctx.generator_backpressure,
            ).remote(self._stages, self.ctx.target_max_block_size,
                     *bundle.refs())
            self.gen_in_flight.append(g)
            self._gen_actor[id(g)] = actor
            return
        ref = actor.run.remote(self._stages, self.ctx.target_max_block_size,
                               *bundle.refs())
        self.in_flight[ref] = (bundle, actor)

    def on_task_done(self, ref: ObjectRef):
        if ref not in self._done_tasks:
            _, actor = self.in_flight[ref]
            self._idle.append(actor)  # free at completion, not release
        super().on_task_done(ref)

    def _on_stream_complete(self, g) -> None:
        actor = self._gen_actor.pop(id(g), None)
        if actor is not None:
            self._idle.append(actor)

    def shutdown(self):
        from ..core.api import kill
        for a in self._actors:
            try:
                kill(a)
            except Exception:
                pass


class WriteOperator(PhysicalOperator):
    def __init__(self, input_op: PhysicalOperator, path: str, file_format: str,
                 writer_args: Dict[str, Any]):
        super().__init__(f"Write({file_format})", [input_op])
        self._path, self._fmt, self._wargs = path, file_format, writer_args
        self._remote = ray_remote(_run_write_task)
        self._index = 0

    def dispatch_one(self):
        bundle = self.input_queue.popleft()
        ref = self._remote.remote(self._path, self._fmt, self._wargs,
                                  self._index, *bundle.refs())
        self._index += 1
        self.in_flight[ref] = bundle


class LimitOperator(PhysicalOperator):
    def __init__(self, input_op: PhysicalOperator, n: int):
        super().__init__(f"Limit({n})", [input_op])
        self._remaining = n
        self._slice = ray_remote(_slice_block_task)

    def can_dispatch(self):
        return bool(self.input_queue) and not self.in_flight

    def dispatch_one(self):
        bundle = self.input_queue.popleft()
        if self._remaining <= 0:
            return
        kept: List[Tuple[ObjectRef, BlockMetadata]] = []
        for ref, meta in bundle.blocks:
            if self._remaining <= 0:
                break
            rows = meta.num_rows
            if rows is None or rows <= self._remaining:
                kept.append((ref, meta))
                self._remaining -= rows or 0
            else:
                sref = self._slice.remote(ref, 0, self._remaining)
                self.in_flight[sref] = None
                self._remaining = 0
        if kept:
            self.output_queue.append(RefBundle(kept))
        if self._remaining <= 0:
            # swallow the rest of the stream
            self.input_queue.clear()
            self._inputs_done = True

    def add_input(self, bundle):
        if self._remaining > 0 or self.in_flight:
            super().add_input(bundle)

    def is_finished(self):
        return super().is_finished() or (self._remaining <= 0 and not self.in_flight)


class AllToAllOperator(PhysicalOperator):
    """Barrier operator: collects every input bundle, then runs ``bulk_fn``
    (which may submit its own tasks) to produce output bundles."""

    def __init__(self, name: str, input_op: PhysicalOperator,
                 bulk_fn: Callable[[List[RefBundle]], List[RefBundle]]):
        super().__init__(name, [input_op])
        self._bulk_fn = bulk_fn
        self._collected: List[RefBundle] = []
        self._ran = False

    def add_input(self, bundle):
        self._collected.append(bundle)

    def can_dispatch(self):
        return self._inputs_done and not self._ran

    def dispatch_one(self):
        self._ran = True
        for out in self._bulk_fn(self._collected):
            if out.blocks:
                self.output_queue.append(out)

    def is_finished(self):
        return self._ran


class UnionOperator(PhysicalOperator):
    """Pass-through over multiple inputs (streams interleave)."""

    def __init__(self, input_ops: List[PhysicalOperator]):
        super().__init__("Union", input_ops)

    def can_dispatch(self):
        return bool(self.input_queue)

    def dispatch_one(self):
        self.output_queue.append(self.input_queue.popleft())

    def is_finished(self):
        return self._inputs_done and not self.input_queue and not self.in_flight
