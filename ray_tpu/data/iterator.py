"""Batch iteration with prefetch + streaming_split for multi-worker ingest.

Reference: ``python/ray/data/iterator.py`` (``DataIterator``),
``_internal/block_batching/`` (prefetching, format conversion) and the
``streaming_split`` coordinator (``_internal/execution/operators/
output_splitter.py`` + ``StreamSplitDataIterator``): one coordinator actor runs
the streaming executor; N consumers (train worker actors on different hosts)
pull coherent disjoint shards per epoch.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Iterable, Iterator, List, Optional

import numpy as np

from ..core.api import get as ray_get
from ..core.api import remote as ray_remote
from .block import BlockAccessor
from .context import DataContext
from .operators import RefBundle, _iter_batches_of

_SENTINEL = object()


def iter_batches_over_bundles(bundles: Iterable[RefBundle], *,
                              batch_size: Optional[int] = 256,
                              batch_format: str = "default",
                              prefetch_batches: int = 1,
                              drop_last: bool = False,
                              local_shuffle_buffer_size: Optional[int] = None,
                              local_shuffle_seed: Optional[int] = None
                              ) -> Iterator[Any]:
    """Fetch blocks (prefetching ahead in a background thread) and re-batch."""
    fmt = batch_format if batch_format != "default" else \
        DataContext.get_current().default_batch_format
    q: "queue.Queue" = queue.Queue(maxsize=max(2, prefetch_batches * 2))
    err: List[BaseException] = []

    def fetcher():
        try:
            for bundle in bundles:
                for ref, _ in bundle.blocks:
                    q.put(ray_get(ref))
        except BaseException as e:  # noqa: BLE001
            err.append(e)
        finally:
            q.put(_SENTINEL)

    t = threading.Thread(target=fetcher, daemon=True, name="block-fetcher")
    t.start()

    def block_stream():
        while True:
            item = q.get()
            if item is _SENTINEL:
                if err:
                    raise err[0]
                return
            yield item

    stream = block_stream()
    if local_shuffle_buffer_size:
        stream = _shuffle_blocks(stream, local_shuffle_buffer_size,
                                 local_shuffle_seed)
    last = None
    for batch in _iter_batches_of(stream, batch_size, fmt):
        if last is not None:
            yield last
        last = batch
    if last is not None:
        if drop_last and batch_size and _batch_rows(last) < batch_size:
            return
        yield last


def _batch_rows(batch) -> int:
    if isinstance(batch, dict):
        return len(next(iter(batch.values()))) if batch else 0
    return len(batch)


def _shuffle_blocks(stream, buffer_rows: int, seed):
    """Row-level local shuffle: maintain a buffer of >= buffer_rows rows,
    emit shuffled slices (reference: ``ShufflingBatcher``)."""
    rng = np.random.default_rng(seed)
    import pyarrow as pa
    buf: List[Any] = []
    nrows = 0
    for block in stream:
        t = BlockAccessor.for_block(block).to_arrow()
        buf.append(t)
        nrows += t.num_rows
        while nrows >= buffer_rows * 2:
            merged = pa.concat_tables(buf, promote_options="default")
            perm = rng.permutation(merged.num_rows)
            merged = merged.take(pa.array(perm))
            out = merged.slice(0, merged.num_rows - buffer_rows)
            keep = merged.slice(merged.num_rows - buffer_rows)
            buf, nrows = [keep], keep.num_rows
            yield out
    if buf:
        merged = pa.concat_tables(buf, promote_options="default")
        if merged.num_rows:
            perm = rng.permutation(merged.num_rows)
            yield merged.take(pa.array(perm))


# ---------------------------------------------------------------------------
# streaming_split
# ---------------------------------------------------------------------------

class _SplitCoordinator:
    """Actor that executes the dataset once per epoch and deals blocks to n
    output splits (round-robin; ``equal=True`` truncates to equal row counts
    after the epoch's plan finishes executing)."""

    def __init__(self, ds, n: int, equal: bool):
        self._ds = ds
        self._n = n
        self._equal = equal
        self._epoch = -1
        self._lock = threading.Lock()
        self._queues: List[collections.deque] = []
        self._done = False
        self._error: Optional[str] = None

    def start_epoch(self, epoch: int) -> int:
        with self._lock:
            if epoch <= self._epoch:
                return self._epoch
            self._epoch = epoch
            self._queues = [collections.deque() for _ in range(self._n)]
            self._done = False
            self._error = None
            threading.Thread(target=self._feed, daemon=True).start()
            return self._epoch

    def _feed(self):
        try:
            pending: List[List] = [[] for _ in range(self._n)]
            rows: List[int] = [0] * self._n
            i = 0
            ds = self._ds
            # re-execute from the logical plan each epoch
            from .executor import StreamingExecutor
            from .planner import plan
            stream = StreamingExecutor(plan(ds._logical), "split").start() \
                if ds._materialized is None else iter(ds._materialized)
            flat: List = []
            for bundle in stream:
                for blk in bundle.blocks:
                    if self._equal:
                        flat.append(blk)
                    else:
                        tgt = min(range(self._n), key=lambda j: rows[j])
                        self._queues[tgt].append([blk])
                        rows[tgt] += blk[1].num_rows or 0
                    i += 1
            if self._equal:
                self._equalize(flat)
        except BaseException as e:  # noqa: BLE001
            self._error = repr(e)
        finally:
            self._done = True

    def _equalize(self, blocks: List):
        """Deal exactly ``total // n`` rows to each split, slicing blocks that
        straddle a split boundary (only the remainder rows are dropped)."""
        total = sum(m.num_rows or 0 for _, m in blocks)
        target = total // self._n
        slice_task = ray_remote(_slice_range)
        # global row span of each block
        spans, acc = [], 0
        for ref, meta in blocks:
            n = meta.num_rows or 0
            spans.append((ref, meta, acc, acc + n))
            acc += n
        for j in range(self._n):
            lo, hi = j * target, (j + 1) * target
            for ref, meta, s, e in spans:
                os_, oe = max(lo, s), min(hi, e)
                if os_ >= oe:
                    continue
                if os_ == s and oe == e:
                    self._queues[j].append([(ref, meta)])
                else:
                    res = ray_get(slice_task.remote(ref, os_ - s, oe - s))
                    if res:
                        self._queues[j].append(list(res))

    def next_blocks(self, split: int, epoch: int):
        """Returns (blocks|None, done: bool). Non-blocking poll."""
        if epoch != self._epoch:
            return None, False
        if self._error:
            raise RuntimeError(f"streaming_split failed: {self._error}")
        q = self._queues[split]
        if q:
            return q.popleft(), False
        return None, self._done

    def stats(self):
        return {"epoch": self._epoch, "done": self._done,
                "queued": [len(q) for q in self._queues]}


def _slice_range(block, start: int, end: int):
    acc = BlockAccessor.for_block(block)
    out = acc.slice(start, end)
    if BlockAccessor.for_block(out).num_rows() == 0:
        return []
    from ..core.api import put as ray_put
    return [(ray_put(out), BlockAccessor.for_block(out).metadata())]


class DataIterator:
    """One consumer's handle onto a streaming split. Picklable — send it to a
    train worker actor and call ``iter_batches`` there each epoch."""

    def __init__(self, coordinator, split: int):
        self._coord = coordinator
        self._split = split
        self._epoch = -1

    def _bundle_stream(self, epoch: int) -> Iterator[RefBundle]:
        ray_get(self._coord.start_epoch.remote(epoch))
        backoff = 0.002
        while True:
            blocks, done = ray_get(
                self._coord.next_blocks.remote(self._split, epoch))
            if blocks:
                backoff = 0.002
                yield RefBundle([tuple(b) for b in blocks])
            elif done:
                return
            else:
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.1)

    def iter_batches(self, *, batch_size: Optional[int] = 256,
                     batch_format: str = "default", prefetch_batches: int = 1,
                     drop_last: bool = False,
                     local_shuffle_buffer_size: Optional[int] = None,
                     local_shuffle_seed: Optional[int] = None) -> Iterator[Any]:
        self._epoch += 1
        yield from iter_batches_over_bundles(
            self._bundle_stream(self._epoch), batch_size=batch_size,
            batch_format=batch_format, prefetch_batches=prefetch_batches,
            drop_last=drop_last,
            local_shuffle_buffer_size=local_shuffle_buffer_size,
            local_shuffle_seed=local_shuffle_seed)

    def iter_torch_batches(self, *, batch_size: Optional[int] = 256,
                           device=None, **kwargs):
        import torch
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            yield {k: (torch.as_tensor(v).to(device) if device else
                       torch.as_tensor(v)) for k, v in batch.items()}

    def iter_jax_batches(self, *, batch_size: Optional[int] = 256,
                         sharding=None, **kwargs):
        import jax
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy", **kwargs):
            if sharding is not None:
                yield {k: jax.device_put(v, sharding) for k, v in batch.items()}
            else:
                yield {k: jax.numpy.asarray(v) for k, v in batch.items()}


def build_streaming_split(ds, n: int, *, equal: bool = False
                          ) -> List[DataIterator]:
    from ..core.actor import ActorClass
    coord = ActorClass(_SplitCoordinator).remote(ds, n, equal)
    return [DataIterator(coord, i) for i in range(n)]
