"""Logical → physical lowering with map fusion.

Reference: ``python/ray/data/_internal/planner/planner.py`` plus the fusion
rule in ``_internal/logical/rules/operator_fusion.py``: consecutive map-type
operators with compatible compute strategies collapse into a single physical
operator so each block makes one task round-trip.
"""

from __future__ import annotations

from typing import List, Optional

from . import exchange, logical as L
from .context import DataContext
from .operators import (ActorPoolMapOperator, AllToAllOperator, InputDataBuffer,
                        LimitOperator, MapStage, PhysicalOperator, ReadOperator,
                        TaskPoolMapOperator, UnionOperator, WriteOperator)


def _stage_for(op: L.AbstractMap) -> MapStage:
    ctx = DataContext.get_current()
    is_class = isinstance(op.fn, type)
    if isinstance(op, L.MapBatches):
        fmt = op.batch_format
        if fmt in ("default", None):
            fmt = ctx.default_batch_format
        return MapStage("batches", op.fn, batch_size=op.batch_size,
                        batch_format=fmt, fn_args=op.fn_args,
                        fn_kwargs=op.fn_kwargs, is_class=is_class,
                        fn_constructor_args=op.fn_constructor_args)
    kind = {"MapRows": "rows", "Filter": "filter", "FlatMap": "flat_map"}[
        type(op).__name__]
    return MapStage(kind, op.fn, fn_args=op.fn_args, fn_kwargs=op.fn_kwargs,
                    is_class=is_class, fn_constructor_args=op.fn_constructor_args)


def _compute_of(op: L.AbstractMap):
    return op.compute


def plan(logical_tail: L.LogicalOp) -> PhysicalOperator:
    """Lower the logical chain ending at ``logical_tail`` to a physical DAG."""
    ctx = DataContext.get_current()
    chain = logical_tail.chain()
    phys: Optional[PhysicalOperator] = None
    i = 0
    while i < len(chain):
        op = chain[i]
        if isinstance(op, L.Read):
            parallelism = op.parallelism
            if parallelism in (-1, None):
                est = op.datasource.estimate_inmemory_data_size()
                if est:
                    parallelism = max(ctx.read_op_min_num_blocks,
                                      est // ctx.target_max_block_size)
                else:
                    parallelism = ctx.read_op_min_num_blocks
            tasks = op.datasource.get_read_tasks(int(parallelism))
            phys = ReadOperator(op.name(), tasks)
        elif isinstance(op, L.InputData):
            phys = InputDataBuffer(op.bundles)
        elif isinstance(op, L.AbstractMap):
            # Fuse the longest run of same-compute map ops.
            stages: List[MapStage] = []
            compute = _compute_of(op)
            names = []
            j = i
            while j < len(chain) and isinstance(chain[j], L.AbstractMap) \
                    and _compute_of(chain[j]) == compute:
                stages.append(_stage_for(chain[j]))
                names.append(chain[j].name())
                j += 1
            name = "->".join(names)
            if compute == "tasks":
                phys = TaskPoolMapOperator(name, phys, stages,
                                           op.ray_remote_args)
            else:
                _, mn, mx = compute
                phys = ActorPoolMapOperator(name, phys, stages, mn, mx,
                                            op.ray_remote_args)
            i = j
            continue
        elif isinstance(op, L.Limit):
            phys = LimitOperator(phys, op.n)
        elif isinstance(op, L.RandomShuffle):
            phys = AllToAllOperator(
                "RandomShuffle", phys,
                exchange.random_shuffle_fn(op.seed, op.num_outputs))
        elif isinstance(op, L.RandomizeBlockOrder):
            phys = AllToAllOperator(
                "RandomizeBlockOrder", phys,
                exchange.randomize_block_order_fn(op.seed))
        elif isinstance(op, L.Repartition):
            phys = AllToAllOperator(
                f"Repartition({op.num_outputs})", phys,
                exchange.repartition_fn(op.num_outputs, op.shuffle))
        elif isinstance(op, L.Sort):
            phys = AllToAllOperator(
                f"Sort({op.key})", phys, exchange.sort_fn(op.key, op.descending))
        elif isinstance(op, L.Aggregate):
            phys = AllToAllOperator(
                "Aggregate", phys, exchange.aggregate_fn(op.key, op.aggs))
        elif isinstance(op, L.Union):
            others = [plan(x) for x in op.extra_inputs]
            phys = UnionOperator([phys] + others)
        elif isinstance(op, L.Zip):
            other_tail = op.extra_inputs[0]

            def right_getter(other_tail=other_tail):
                from .executor import execute_to_bundles
                return execute_to_bundles(plan(other_tail), "zip-right")

            phys = AllToAllOperator("Zip", phys, exchange.zip_fn(right_getter))
        elif isinstance(op, L.Write):
            phys = WriteOperator(phys, op.path, op.file_format, op.writer_args)
        else:
            raise ValueError(f"cannot plan logical op {op}")
        i += 1
    assert phys is not None
    return phys
