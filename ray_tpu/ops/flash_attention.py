"""Pallas TPU flash attention (forward kernel + memory-bounded backward).

The reference has no TPU kernels at all (its attention lives in external torch
models); this is greenfield TPU-first code (SURVEY §5.7, §7 stance).

Design:
* **Forward** is a Pallas kernel. Grid = (batch, q_heads, S/block_q); each
  program streams K/V blocks for its (batch, kv_head) out of VMEM with a
  `fori_loop`, folding them into the flash online-softmax accumulator
  (running max `m`, denominator `l`, numerator `acc`) so the S×S score matrix
  never exists — only a [block_q, block_kv] tile lives at a time.  Causal
  programs stop the loop at their diagonal block: the lower-triangle work that
  plain attention burns on masked logits is never issued to the MXU.
* **GQA without materialization**: the kv-head index map is
  ``h // (num_q_heads / num_kv_heads)`` so grouped-query K/V blocks are read
  in place; the `repeat_kv` copy the plain path makes is skipped.
* **Backward** recomputes attention blockwise from the saved (out, lse)
  residuals — standard flash-attention recurrence — as a `lax.scan` over KV
  blocks in plain JAX.  Peak memory O(S·block) like the forward; XLA fuses the
  per-block matmuls onto the MXU.  (A Pallas backward kernel is a further
  speedup, not a correctness need: training-step wall time is dominated by
  the big MLP matmuls.)

Numerics: logits and softmax statistics in f32 (MXU accumulates f32 via
``preferred_element_type``); probabilities cast back to the input dtype for
the PV matmul, matching ``attention.attend``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_kv: int,
                seq_kv: int, causal: bool, scale: float):
    """One (batch, head, q-block) program: stream KV blocks, online softmax."""
    qi = pl.program_id(2)
    block_q = q_ref.shape[2]
    d = q_ref.shape[3]
    q = q_ref[0, 0]                                   # [block_q, d]

    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    if causal:
        # KV blocks strictly after this q-block's diagonal are fully masked:
        # don't even loop over them.
        num_kv = (qi * block_q + block_q + block_kv - 1) // block_kv
    else:
        num_kv = seq_kv // block_kv

    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_kv), 0)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.ds(j * block_kv, block_kv), :]     # [block_kv, d]
        v = v_ref[0, 0, pl.ds(j * block_kv, block_kv), :]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bq, bkv]
        if causal:
            k_pos = j * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_kv), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + p.sum(axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m, l, acc = jax.lax.fori_loop(0, num_kv, body, (m0, l0, acc0))
    l = jnp.maximum(l, 1e-30)
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    # TPU tiling wants the last two block dims (8, 128)-aligned; a [block_q]
    # row vector is not.  Replicate the row stats across 8 sublanes and let
    # the caller read lane 0.
    lse_ref[0, 0] = jnp.broadcast_to((m + jnp.log(l))[:, 0][None, :],
                                     (8, block_q))


def _flash_fwd(q, k, v, causal: bool, block_q: int, block_kv: int,
               interpret: bool):
    """q: [B, H, S, D], k/v: [B, KV, S, D] -> (out [B, H, S, D], lse [B, H, S])."""
    b, h, s, d = q.shape
    kv_heads = k.shape[1]
    reps = h // kv_heads
    scale = d ** -0.5
    block_q = min(block_q, s)
    block_kv = min(block_kv, s)

    grid = (b, h, s // block_q)
    kernel = functools.partial(_fwd_kernel, block_kv=block_kv, seq_kv=s,
                               causal=causal, scale=scale)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // reps, 0, 0)),
            pl.BlockSpec((1, 1, s, d), lambda bi, hi, qi: (bi, hi // reps, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 8, block_q), lambda bi, hi, qi: (bi, hi, 0, qi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
            jax.ShapeDtypeStruct((b, h, 8, s), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return out, lse[:, :, 0, :]


def _bwd_blockwise(q, k, v, out, lse, g, causal: bool, block_kv: int):
    """Flash backward, recompute-based, as a scan over KV blocks.

    q/out/g: [B, H, S, D]; k/v: [B, KV, S, D]; lse: [B, H, S].
    Returns (dq, dk, dv) with dk/dv in kv-head layout.
    """
    b, h, s, d = q.shape
    kv_heads = k.shape[1]
    reps = h // kv_heads
    scale = d ** -0.5
    block_kv = min(block_kv, s)
    n_blocks = s // block_kv

    qf = q.astype(jnp.float32)
    gf = g.astype(jnp.float32)
    # D_i = rowsum(dO * O): the softmax-jacobian diagonal term.
    delta = (gf * out.astype(jnp.float32)).sum(-1)              # [B, H, S]
    q_pos = jnp.arange(s)

    kb = jnp.moveaxis(k.reshape(b, kv_heads, n_blocks, block_kv, d), 2, 0)
    vb = jnp.moveaxis(v.reshape(b, kv_heads, n_blocks, block_kv, d), 2, 0)

    def per_block(j, kj, vj):
        # kj/vj: [B, KV, block_kv, D] -> repeat to q heads.
        kjh = jnp.repeat(kj, reps, axis=1) if reps > 1 else kj
        vjh = jnp.repeat(vj, reps, axis=1) if reps > 1 else vj
        sj = jnp.einsum("bhqd,bhkd->bhqk", qf, kjh.astype(jnp.float32)) * scale
        if causal:
            k_pos = j * block_kv + jnp.arange(block_kv)
            sj = jnp.where((q_pos[:, None] >= k_pos[None, :])[None, None],
                           sj, NEG_INF)
        p = jnp.exp(sj - lse[..., None])                        # [B,H,S,bkv]
        dv_h = jnp.einsum("bhqk,bhqd->bhkd", p, gf)
        dp = jnp.einsum("bhqd,bhkd->bhqk", gf, vjh.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * scale
        dq_j = jnp.einsum("bhqk,bhkd->bhqd", ds, kjh.astype(jnp.float32))
        # fold q-head grads back to kv heads (GQA)
        dk_h = jnp.einsum("bhqk,bhqd->bhkd", ds, qf)
        if reps > 1:
            dv_h = dv_h.reshape(b, kv_heads, reps, block_kv, d).sum(2)
            dk_h = dk_h.reshape(b, kv_heads, reps, block_kv, d).sum(2)
        return dq_j, dk_h, dv_h

    def scan_body(dq, xs):
        j, kj, vj = xs
        dq_j, dk_j, dv_j = per_block(j, kj, vj)
        return dq + dq_j, (dk_j, dv_j)

    scan_fn = jax.checkpoint(scan_body,
                             policy=jax.checkpoint_policies.nothing_saveable)
    dq, (dkb, dvb) = jax.lax.scan(
        scan_fn, jnp.zeros_like(qf), (jnp.arange(n_blocks), kb, vb))
    dk = jnp.moveaxis(dkb, 0, 2).reshape(b, kv_heads, s, d)
    dv = jnp.moveaxis(dvb, 0, 2).reshape(b, kv_heads, s, d)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# Pallas backward kernels
# ---------------------------------------------------------------------------
#
# Both kernels compute the score tile TRANSPOSED — s_t = [block_kv(sublanes),
# block_q(lanes)] — so the per-q-row statistics (lse, delta) enter as natural
# [1, block_q] rows and broadcast over sublanes, which Mosaic supports
# directly; no lane-replicated stat arrays and no [1,N]->[N,1] relayout.
# Every matmul contracts either d or a block dim, all MXU-shaped.
#
# Grids iterate over BOTH block axes (q and kv) with an f32 VMEM scratch
# accumulator initialised on the first visit of an output tile and flushed on
# the last, so per-program VMEM is O(block) at any sequence length (the
# first version loaded full-sequence K/V per program and died at S>=4096).


def _bwd_dq_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, dlt_ref, dq_ref,
                   acc_ref, *, causal: bool, scale: float):
    """Grid (b, h, n_q, n_kv): accumulate one q-block's dq over KV blocks."""
    qi, kj = pl.program_id(2), pl.program_id(3)
    n_kv = pl.num_programs(3)
    block_q, d = q_ref.shape[2], q_ref.shape[3]
    block_kv = k_ref.shape[2]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Under causal masking, KV blocks strictly past this q-block's diagonal
    # contribute nothing: skip their compute (loads are pipelined anyway).
    live = (kj * block_kv < (qi + 1) * block_q) if causal else (kj >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]                               # [bq, d]
        g = g_ref[0, 0]                               # [bq, d]
        k = k_ref[0, 0]                               # [bkv, d]
        v = v_ref[0, 0]
        lse = lse_ref[0, 0]                           # [1, bq] f32
        dlt = dlt_ref[0, 0]
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bkv, bq]
        if causal:
            k_pos = kj * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 0)
            q_pos = qi * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 1)
            s_t = jnp.where(q_pos >= k_pos, s_t, NEG_INF)
        p_t = jnp.exp(s_t - lse)                              # [bkv, bq]
        dp_t = jax.lax.dot_general(
            v, g, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bkv, bq]
        ds_t = p_t * (dp_t - dlt) * scale
        acc_ref[...] += jax.lax.dot_general(
            ds_t.astype(k.dtype), k, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bq, d]

    @pl.when(kj == n_kv - 1)
    def _flush():
        dq_ref[0, 0] = acc_ref[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, g_ref, lse_ref, dlt_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, causal: bool,
                    scale: float):
    """Grid (b, kv_heads, n_kv, reps, n_q): accumulate one kv-block's dk/dv
    over q blocks and over the `reps` query heads sharing it (GQA fold-back).
    The two innermost grid dims revisit the same output tile consecutively,
    which is what makes the scratch init/flush pattern valid."""
    ki, r, qj = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    n_rep, n_q = pl.num_programs(3), pl.num_programs(4)
    block_kv, d = k_ref.shape[2], k_ref.shape[3]
    block_q = q_ref.shape[2]

    @pl.when((r == 0) & (qj == 0))
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    # q blocks strictly before this kv-block's diagonal see none of it.
    live = ((qj + 1) * block_q > ki * block_kv) if causal else (qj >= 0)

    @pl.when(live)
    def _compute():
        q = q_ref[0, 0]                               # [bq, d]
        g = g_ref[0, 0]
        k = k_ref[0, 0]                               # [bkv, d]
        v = v_ref[0, 0]
        lse = lse_ref[0, 0]                           # [1, bq] f32
        dlt = dlt_ref[0, 0]
        s_t = jax.lax.dot_general(
            k, q, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [bkv, bq]
        if causal:
            k_pos = ki * block_kv + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 0)
            q_pos = qj * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_kv, block_q), 1)
            s_t = jnp.where(q_pos >= k_pos, s_t, NEG_INF)
        p_t = jnp.exp(s_t - lse)
        dv_acc[...] += jax.lax.dot_general(
            p_t.astype(g.dtype), g, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bkv, d]
        dp_t = jax.lax.dot_general(
            v, g, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds_t = p_t * (dp_t - dlt) * scale
        dk_acc[...] += jax.lax.dot_general(
            ds_t.astype(q.dtype), q, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bkv, d]

    @pl.when((r == n_rep - 1) & (qj == n_q - 1))
    def _flush():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_bwd_pallas(q, k, v, out, lse, g, causal: bool, block_q: int,
                      block_kv: int, interpret: bool):
    """Pallas flash backward: (dq, dk, dv), dk/dv in kv-head layout."""
    b, h, s, d = q.shape
    kv_heads = k.shape[1]
    reps = h // kv_heads
    scale = d ** -0.5
    bq = min(block_q, s)
    bkv = min(block_kv, s)

    gf = g.astype(q.dtype)
    # D_i = rowsum(dO * O), the softmax-jacobian diagonal term.
    delta = (g.astype(jnp.float32) * out.astype(jnp.float32)).sum(-1)
    # Stats ride as [B, H, 1, S] so the (1, 1, 1, bq) block satisfies the
    # Mosaic tiling rule (second-to-last block dim == full array dim).
    lse4 = lse[:, :, None, :]
    dlt4 = delta[:, :, None, :]

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, scale=scale),
        grid=(b, h, s // bq, s // bkv),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda bi, hi, qi, kj: (bi, hi // reps, kj, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda bi, hi, qi, kj: (bi, hi // reps, kj, 0)),
            pl.BlockSpec((1, 1, bq, d), lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda bi, hi, qi, kj: (bi, hi, 0, qi)),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda bi, hi, qi, kj: (bi, hi, 0, qi)),
        ],
        out_specs=pl.BlockSpec((1, 1, bq, d),
                               lambda bi, hi, qi, kj: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, s, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, gf, lse4, dlt4)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, causal=causal, scale=scale),
        grid=(b, kv_heads, s // bkv, reps, s // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, d),
                         lambda bi, gi, ki, r, qj: (bi, gi * reps + r, qj, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda bi, gi, ki, r, qj: (bi, gi, ki, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda bi, gi, ki, r, qj: (bi, gi, ki, 0)),
            pl.BlockSpec((1, 1, bq, d),
                         lambda bi, gi, ki, r, qj: (bi, gi * reps + r, qj, 0)),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda bi, gi, ki, r, qj: (bi, gi * reps + r, 0, qj)),
            pl.BlockSpec((1, 1, 1, bq),
                         lambda bi, gi, ki, r, qj: (bi, gi * reps + r, 0, qj)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bkv, d),
                         lambda bi, gi, ki, r, qj: (bi, gi, ki, 0)),
            pl.BlockSpec((1, 1, bkv, d),
                         lambda bi, gi, ki, r, qj: (bi, gi, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, kv_heads, s, d), k.dtype),
            jax.ShapeDtypeStruct((b, kv_heads, s, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((bkv, d), jnp.float32),
                        pltpu.VMEM((bkv, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, gf, lse4, dlt4)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash(q, k, v, causal, block_q, block_kv, interpret):
    out, _ = _flash_fwd(q, k, v, causal, block_q, block_kv, interpret)
    return out


def _flash_vjp_fwd(q, k, v, causal, block_q, block_kv, interpret):
    from jax.ad_checkpoint import checkpoint_name
    out, lse = _flash_fwd(q, k, v, causal, block_q, block_kv, interpret)
    # Under `jax.checkpoint(policy=save_only_these_names(...))` these names let
    # the remat replay keep the flash residuals instead of re-running the
    # forward kernel (models/transformer.py REMAT_SAVE_NAMES).
    out = checkpoint_name(out, "attn_out")
    lse = checkpoint_name(lse, "attn_lse")
    return out, (q, k, v, out, lse)


def _flash_vjp_bwd(causal, block_q, block_kv, interpret, res, g):
    q, k, v, out, lse = res
    s = q.shape[2]
    bq, bkv = min(block_q, s), min(block_kv, s)
    # bq rides the lane dim of the stat rows (must be 128-aligned); bkv the
    # sublane dim of the transposed score tile.
    if bq % 128 == 0 and bkv % 128 == 0 and s % bq == 0 and s % bkv == 0:
        return _flash_bwd_pallas(q, k, v, out, lse, g, causal, block_q,
                                 block_kv, interpret)
    return _bwd_blockwise(q, k, v, out, lse, g, causal, block_kv)


_flash.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, causal: bool = True, block_q: int = 512,
                    block_kv: int = 512,
                    interpret: Optional[bool] = None) -> jnp.ndarray:
    """Flash attention. q: [B, Sq, H, D], k/v: [B, Skv, KV, D] -> [B, Sq, H, D].

    Layout matches ``attention.attend``; internally transposed to [B, H, S, D]
    (the kernel wants the sequence on the sublane dim and D=64/128 on lanes).
    Sequence lengths must be multiples of the block sizes (the model layer
    guarantees power-of-two seq; dispatch falls back to plain otherwise).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    b, sq, h, d = q.shape
    skv = k.shape[1]
    block_q = min(block_q, sq)
    block_kv = min(block_kv, skv)
    if sq % block_q or skv % block_kv or h % k.shape[2]:
        from .attention import attend
        return attend(q, k, v, causal=causal)
    qt = q.swapaxes(1, 2)
    kt = k.swapaxes(1, 2)
    vt = v.swapaxes(1, 2)
    out = _flash(qt, kt, vt, causal, block_q, block_kv, interpret)
    return out.swapaxes(1, 2)
