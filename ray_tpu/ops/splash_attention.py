"""Splash attention: the pallas TPU kernel with explicit backward blocks.

Third attention impl beside ``ops/attention.mha`` (plain) and
``ops/flash_attention`` (in-repo pallas flash).  What splash adds over the
in-repo flash kernel:

* **native GQA** — k/v stay at ``num_kv_heads``; no ``repeat`` materialising
  the full head count into HBM before the kernel,
* **separate backward block sizes** — ``block_q_dkv``/``block_kv_dkv`` and
  ``block_q_dq``/``block_kv_dq`` tune the dkv and dq backward passes
  independently of the forward (the forward-optimal tile is usually wrong
  for the backward at long sequence),
* **sparse mask skipping** — fully-masked causal tiles are never launched.

Layout matches the rest of ``ops/``: q ``[B, S, H, D]``, k/v
``[B, S, KV, D]``, output ``[B, S, H, D]``.  The kernel itself wants
per-batch ``[H, S, D]`` with a pre-scaled q, so the wrapper transposes and
vmaps over batch.

Dispatch contract (`splash_mha`): returns the attention output, or **None**
when splash cannot run here (pallas ops missing, shape doesn't tile, kernel
construction failed) after emitting one RuntimeWarning per process — the
caller then falls back to the ``mha`` dispatcher.  Never raises ImportError.

Off TPU the kernel runs in pallas interpret mode, which is numerically
faithful (tier-1 pins parity against ``ops/flash_attention`` on GQA+causal
shapes) but slow — interpret mode is for correctness gates, not benchmarks.
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..util import jax_compat

__all__ = ["splash_mha", "splash_supported", "DEFAULT_BLOCK"]

#: Forward/backward tile edge used when the sequence allows it.  512 is the
#: sweet spot measured for the in-repo flash kernel on v5e (PROFILE_CORE.md);
#: splash shrinks it to the largest 128-multiple that divides the sequence.
DEFAULT_BLOCK = 512

_warned = False


def _warn_once(reason: str) -> None:
    global _warned
    if not _warned:
        _warned = True
        warnings.warn(
            "splash attention unavailable (%s); falling back to the "
            "flash/plain attention path" % reason,
            RuntimeWarning, stacklevel=3)


def _pick_block(seq: int, cap: int) -> int:
    """Largest multiple of 128 that is <= cap and divides seq."""
    best = 128
    b = 128
    while b <= min(cap, seq):
        if seq % b == 0:
            best = b
        b += 128
    return best


def splash_supported(seq_q: int, seq_kv: int, num_heads: int,
                     num_kv_heads: int, head_dim: int) -> Optional[str]:
    """None when the shape tiles for the splash kernel, else the reason."""
    if not jax_compat.has_splash_attention():
        return "pallas splash ops not importable in this jax"
    if head_dim % 128 != 0:
        return f"head_dim={head_dim} not a multiple of 128"
    if seq_q % 128 != 0 or seq_kv % 128 != 0:
        return f"seq ({seq_q}, {seq_kv}) not a multiple of 128"
    if num_kv_heads < 1 or num_heads % num_kv_heads != 0:
        return f"heads {num_heads} not a multiple of kv heads {num_kv_heads}"
    return None


@functools.lru_cache(maxsize=32)
def _get_kernel(num_q_heads: int, seq_q: int, seq_kv: int, causal: bool,
                softcap: float, block_q: int, block_kv: int,
                block_q_bwd: int, block_kv_bwd: int, interpret: bool):
    """Build (and cache) a SplashAttentionKernel for one static shape.

    The mask-info preprocessing inside make_splash_mha is numpy work
    proportional to (seq/block)^2 per head — caching keys on everything
    that changes the compiled kernel.
    """
    from jax.experimental.pallas.ops.tpu.splash_attention import (
        splash_attention_kernel as sak, splash_attention_mask as sam)
    shape = (seq_q, seq_kv)
    if causal:
        heads = [sam.CausalMask(shape=shape) for _ in range(num_q_heads)]
    else:
        heads = [sam.FullMask(shape) for _ in range(num_q_heads)]
    mask = sam.MultiHeadMask(heads)
    block_sizes = sak.BlockSizes(
        block_q=block_q, block_kv=block_kv, block_kv_compute=block_kv,
        block_q_dkv=block_q_bwd, block_kv_dkv=block_kv_bwd,
        block_kv_dkv_compute=block_kv_bwd,
        block_q_dq=block_q_bwd, block_kv_dq=block_kv_bwd)
    return sak.make_splash_mha(
        mask, block_sizes=block_sizes, head_shards=1, q_seq_shards=1,
        attn_logits_soft_cap=(float(softcap) if softcap else None),
        interpret=interpret)


def _shard_map_call(kernel, qs, ks, vs, mesh, batch_axes):
    """TPU multi-device path: batch-shard the kernel call via shard_map.

    Under plain jit XLA treats the pallas call as an opaque custom call and
    would gather the batch onto every device; shard_map keeps each device on
    its local batch shard (the SNIPPETS.md maxtext recipe).  head_shards and
    q_seq_shards stay 1 — batch is the only sharded dim here, so the
    kernel's manual_sharding_spec is the replicated spec.
    """
    from jax.sharding import NamedSharding, PartitionSpec as P
    axes = tuple(a for a in batch_axes if a in mesh.axis_names)
    spec = P(axes if axes else None, None, None, None)
    kernel_spec = kernel.manual_sharding_spec(
        NamedSharding(mesh, P(None, None)))
    fn = jax_compat.shard_map(
        lambda kern, q, k, v: jax.vmap(kern)(q, k, v),
        mesh=mesh, in_specs=(kernel_spec, spec, spec, spec),
        out_specs=spec, check_vma=False)
    return fn(kernel, qs, ks, vs)


def splash_mha(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
               causal: bool = True, logit_softcap: float = 0.0,
               mesh=None, batch_axes: Tuple[str, ...] = ("dp", "fsdp"),
               manual: bool = False, interpret: Optional[bool] = None,
               block_q: int = DEFAULT_BLOCK, block_kv: int = DEFAULT_BLOCK,
               block_q_bwd: Optional[int] = None,
               block_kv_bwd: Optional[int] = None) -> Optional[jnp.ndarray]:
    """Splash attention over [B, S, H, D] q and [B, S, KV, D] k/v.

    Returns None (after one RuntimeWarning per process) when splash cannot
    serve this call — the caller is expected to fall back to ``mha``.

    ``manual=True`` means we are already inside a manually-partitioned
    region (shard_map body) and operands are per-device local: call the
    kernel directly.  Otherwise, with a multi-device ``mesh`` on TPU the
    call is batch-sharded via shard_map; on CPU (interpret mode) the direct
    call stays auto-partitionable because interpret lowers to plain HLO.
    """
    b, seq_q, num_heads, head_dim = q.shape
    seq_kv, num_kv = k.shape[1], k.shape[2]
    reason = splash_supported(seq_q, seq_kv, num_heads, num_kv, head_dim)
    if reason is not None:
        _warn_once(reason)
        return None
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bq = _pick_block(seq_q, block_q)
    bkv = _pick_block(seq_kv, block_kv)
    bq_bwd = _pick_block(seq_q, block_q_bwd or block_q)
    bkv_bwd = _pick_block(seq_kv, block_kv_bwd or block_kv)
    try:
        kernel = _get_kernel(num_heads, seq_q, seq_kv, bool(causal),
                             float(logit_softcap), bq, bkv, bq_bwd, bkv_bwd,
                             bool(interpret))
    except Exception as exc:  # mask/kernel construction failed
        _warn_once(f"kernel construction failed: {exc!r}")
        return None
    # kernel applies no softmax scale itself; fold 1/sqrt(D) into q
    qs = (q * (head_dim ** -0.5)).swapaxes(1, 2)   # [B, H, Sq, D]
    ks = k.swapaxes(1, 2)                          # [B, KV, Skv, D]
    vs = v.swapaxes(1, 2)
    use_shard_map = (mesh is not None and not manual and not interpret
                     and any(mesh.shape.get(a, 1) > 1 for a in batch_axes))
    if use_shard_map:
        out = _shard_map_call(kernel, qs, ks, vs, mesh, batch_axes)
    else:
        out = jax.vmap(kernel)(qs, ks, vs)
    return out.swapaxes(1, 2).astype(q.dtype)
