"""Mixture-of-Experts layer: top-k routing with capacity-based dense dispatch.

TPU-first: dispatch/combine are einsums against one-hot routing tensors so everything
stays on the MXU with static shapes (the standard TPU MoE formulation; dynamic gather/
scatter routing is hostile to XLA).  With the expert dimension sharded over the ``ep``
mesh axis, XLA lowers the dispatch einsum into the expert all-to-all over ICI
(SURVEY §2.3 EP row: the reference has no MoE support in core — this is first-class).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp


def top_k_routing(router_logits: jnp.ndarray, k: int,
                  capacity: int) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """router_logits: [T, E] -> (dispatch [T, E, C] bool, combine [T, E, C], aux_loss).

    Capacity-based: each expert accepts at most C tokens (overflow dropped),
    keeping shapes static for XLA.
    """
    t, e = router_logits.shape
    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    top_p, top_idx = jax.lax.top_k(probs, k)          # [T, k]
    top_p = top_p / jnp.maximum(top_p.sum(-1, keepdims=True), 1e-9)  # renorm (Mixtral)

    # Position of each (token, choice) in its expert's capacity buffer:
    # earlier tokens with the same choice + tokens admitted by earlier choices.
    dispatch = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    combine = jnp.zeros((t, e, capacity), dtype=jnp.float32)
    counts = jnp.zeros((e,), dtype=jnp.int32)
    for choice in range(k):
        idx = top_idx[:, choice]                                  # [T]
        onehot = jax.nn.one_hot(idx, e, dtype=jnp.int32)          # [T, E]
        prior = jnp.cumsum(onehot, axis=0) - onehot
        pos = (onehot * (prior + counts[None, :])).sum(-1)        # [T]
        ok = pos < capacity
        disp = (jax.nn.one_hot(idx, e)[:, :, None]
                * jax.nn.one_hot(pos, capacity)[:, None, :]
                * ok[:, None, None].astype(jnp.float32))
        dispatch = dispatch + disp
        combine = combine + disp * top_p[:, choice][:, None, None]
        counts = counts + (onehot * ok[:, None].astype(jnp.int32)).sum(0)

    # Load-balancing auxiliary loss (Switch Transformer style).
    me = probs.mean(axis=0)                            # [E] mean router prob
    ce = jax.nn.one_hot(top_idx[:, 0], e).mean(axis=0)  # [E] fraction routed
    aux_loss = e * jnp.sum(me * ce)
    return dispatch, combine, aux_loss


def moe_mlp(x: jnp.ndarray, router_w: jnp.ndarray, w_gate: jnp.ndarray,
            w_in: jnp.ndarray, w_out: jnp.ndarray, experts_per_token: int,
            capacity_factor: float) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Sparse SwiGLU MLP. x: [B, S, H]; router_w: [H, E];
    w_gate/w_in: [E, H, M]; w_out: [E, M, H]. Returns (out [B,S,H], aux_loss)."""
    b, s, h = x.shape
    e = router_w.shape[-1]
    tokens = x.reshape(b * s, h)
    capacity = max(1, int(capacity_factor * experts_per_token * b * s / e))
    logits = tokens @ router_w.astype(tokens.dtype)
    dispatch, combine, aux = top_k_routing(logits, experts_per_token, capacity)
    # Dispatch to expert buffers: [E, C, H]
    xs = jnp.einsum("tec,th->ech", dispatch.astype(tokens.dtype), tokens)
    gate = jnp.einsum("ech,ehm->ecm", xs, w_gate.astype(xs.dtype))
    up = jnp.einsum("ech,ehm->ecm", xs, w_in.astype(xs.dtype))
    act = jax.nn.silu(gate) * up
    out_e = jnp.einsum("ecm,emh->ech", act, w_out.astype(act.dtype))
    out = jnp.einsum("tec,ech->th", combine.astype(out_e.dtype), out_e)
    return out.reshape(b, s, h), aux
