"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

SURVEY §5.7: the reference has **no** sequence/context parallelism (verified negative)
— this is greenfield, first-class here.  Design: each device on the ``sp`` mesh axis
holds a contiguous sequence shard of Q/K/V; K/V shards rotate around the ICI ring with
``jax.lax.ppermute`` while each hop folds one KV block into the flash-attention
online-softmax accumulator (``ops.attention.attend_blockwise``).  Communication
overlaps compute hop-by-hop, HBM never materializes the S×S score matrix, and the
collective rides ICI neighbor links (the ppermute pattern XLA maps to an ICI ring).

Papers: Ring Attention (blockwise transformers), Ulysses all-to-all alternative
(``ulysses_attention`` below).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..util import jax_compat

from .attention import attend_blockwise, finalize_blockwise


def _ring_attn_shard(q, k, v, axis_name: str, causal: bool = True,
                     logit_softcap: float = 0.0):
    """Per-shard body (runs under shard_map): q/k/v [B, S_local, H|KV, D]."""
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    b, _, h, d = q.shape

    m = jnp.full((b, h, s_local), -jnp.inf, dtype=jnp.float32)
    l = jnp.zeros((b, h, s_local), dtype=jnp.float32)
    o = jnp.zeros((b, s_local, h, d), dtype=jnp.float32)

    q_offset = my_idx * s_local

    def hop(carry, i):
        m, l, o, k_cur, v_cur = carry
        # The KV block currently held came from shard (my_idx - i) mod n.
        src = (my_idx - i) % axis_size
        kv_offset = src * s_local
        m, l, o = attend_blockwise(q, k_cur, v_cur, m, l, o,
                                   causal=causal, q_offset=q_offset,
                                   kv_offset=kv_offset,
                                   logit_softcap=logit_softcap)
        # Rotate KV to the next device (ring: i -> i+1).
        perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (m, l, o, k_nxt, v_nxt), None

    (m, l, o, _, _), _ = jax.lax.scan(hop, (m, l, o, k, v),
                                      jnp.arange(axis_size))
    return finalize_blockwise(m, l, o).astype(q.dtype)


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                   causal: bool = True, batch_axes: tuple = ("dp",),
                   logit_softcap: float = 0.0):
    """Ring attention over `axis_name` of `mesh`.

    q: [B, S, H, D], k/v: [B, S, KV, D] with S sharded over `axis_name` and B
    over `batch_axes`. Returns [B, S, H, D] with the same sharding.
    """
    batch_spec = P(batch_axes if len(batch_axes) > 1 else batch_axes[0],
                   axis_name, None, None)
    fn = jax_compat.shard_map(
        functools.partial(_ring_attn_shard, axis_name=axis_name, causal=causal,
                          logit_softcap=logit_softcap),
        mesh=mesh,
        in_specs=(batch_spec, batch_spec, batch_spec),
        out_specs=batch_spec,
        check_vma=False)
    return fn(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sp",
                      causal: bool = True, batch_axes: tuple = ("dp",)):
    """DeepSpeed-Ulysses-style sequence parallelism: all-to-all so each device
    gets the full sequence for H/n heads, attends locally, all-to-all back.

    Cheaper than ring for moderate S (two all-to-alls vs n-1 ppermutes) but
    caps the sp degree at num_heads; ring has no such cap (SURVEY §2.3 SP row).
    """
    bspec = batch_axes if len(batch_axes) > 1 else batch_axes[0]

    def body(q, k, v):
        n = jax.lax.psum(1, axis_name)
        # [B, S/n, H, D] -> all-to-all -> [B, S, H/n, D]
        def a2a_fwd(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                      tiled=True)

        def a2a_bwd(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                      tiled=True)

        qf, kf, vf = a2a_fwd(q), a2a_fwd(k), a2a_fwd(v)
        from .attention import attend
        out = attend(qf, kf, vf, causal=causal)
        return a2a_bwd(out)

    spec = P(bspec, axis_name, None, None)
    kv_heads = k.shape[2]
    sp = mesh.shape[axis_name]
    if kv_heads % sp != 0:
        # GQA with fewer KV heads than the sp degree: fall back to ring.
        return ring_attention(q, k, v, mesh, axis_name, causal, batch_axes)
    return jax_compat.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)(q, k, v)
