"""Attention ops: causal multi-head attention with GQA, plain XLA path +
Pallas flash kernel on TPU.

TPU-first notes: the plain path is two einsums XLA maps straight onto the MXU and is
the right choice for short sequences; the Pallas flash kernel (``flash_attention.py``)
wins once S is large enough that the S×S score matrix stops fitting VMEM-friendly
tiles.  ``attend_blockwise`` exposes the online-softmax accumulator used by ring
attention (``ring_attention.py``).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def repeat_kv(k: jnp.ndarray, num_heads: int) -> jnp.ndarray:
    """[B, S, KV, D] -> [B, S, H, D] by repeating kv heads (GQA)."""
    num_kv = k.shape[2]
    if num_kv == num_heads:
        return k
    reps = num_heads // num_kv
    return jnp.repeat(k, reps, axis=2)


def attend(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray,
           causal: bool = True,
           q_offset: int | jnp.ndarray = 0,
           kv_offset: int | jnp.ndarray = 0,
           logit_softcap: float = 0.0) -> jnp.ndarray:
    """Plain attention. q: [B, Sq, H, D], k/v: [B, Skv, KV, D] -> [B, Sq, H, D].

    ``q_offset``/``kv_offset`` are the global positions of the first query/key —
    used by ring attention where each device holds a sequence shard.
    """
    num_heads = q.shape[2]
    k = repeat_kv(k, num_heads)
    v = repeat_kv(v, num_heads)
    scale = q.shape[-1] ** -0.5
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k.astype(q.dtype)) * scale
    if logit_softcap > 0:
        logits = jnp.tanh(logits / logit_softcap) * logit_softcap
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attend_blockwise(q, k, v, m, l, o, causal, q_offset, kv_offset,
                     logit_softcap: float = 0.0):
    """One online-softmax accumulation step over a KV block.

    State: m [B,H,Sq] running max (f32), l [B,H,Sq] running denom (f32),
    o [B,Sq,H,D] running numerator (f32).  Returns updated (m, l, o).
    This is the flash-attention recurrence; ring attention calls it once per
    rotated KV shard (PAPERS.md: blockwise/ring attention).
    """
    num_heads = q.shape[2]
    k = repeat_kv(k, num_heads)
    v = repeat_kv(v, num_heads)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k.astype(q.dtype)) * scale
    s = s.astype(jnp.float32)
    if logit_softcap > 0:
        s = jnp.tanh(s / logit_softcap) * logit_softcap
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        k_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask[None, None], s, NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(jnp.float32)
    return m_new, l_new, o_new


def finalize_blockwise(m, l, o):
    """Normalize the online-softmax accumulator into the attention output."""
    denom = jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return o / denom


def mha(q, k, v, causal: bool = True, logit_softcap: float = 0.0,
        use_flash: Optional[bool] = None):
    """Dispatch between the Pallas flash kernel (TPU, long seq) and plain XLA."""
    if use_flash is None:
        # The flash kernel does not implement logit softcap; fall back when set.
        use_flash = (jax.default_backend() == "tpu" and q.shape[1] >= 1024
                     and q.shape[-1] in (64, 128, 256) and logit_softcap == 0.0)
    if use_flash:
        if logit_softcap > 0.0:
            raise ValueError("flash_attention does not implement logit_softcap;"
                             " use use_flash=False (or leave it None to"
                             " auto-fall-back)")
        from .flash_attention import flash_attention
        return flash_attention(q, k, v, causal=causal)
    return attend(q, k, v, causal=causal, logit_softcap=logit_softcap)
