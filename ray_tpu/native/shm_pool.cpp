// Shared-memory arena allocator for the object store.
//
// Reference analogue: the plasma store's single-mmap + dlmalloc design
// (src/ray/object_manager/plasma/{store.h,dlmalloc}) — one large mapping per
// node, objects are offsets into it.  The round-1 Python store paid a file
// create + ftruncate + mmap + page-zero per object; this arena pays them
// once per node.
//
// The allocator is a first-fit free list with boundary-tag coalescing.
// Allocator METADATA lives in process-local heap (only the node agent
// allocates/frees); the shm file carries pure object bytes, so attaching
// processes just mmap + offset.  All sizes are 64-byte aligned (cache line).
//
// C ABI (consumed via ctypes from ray_tpu/native/__init__.py):
//   rt_pool_create(path, capacity) -> handle | NULL
//   rt_pool_alloc(handle, size)    -> offset | -1
//   rt_pool_free(handle, offset)
//   rt_pool_used(handle)           -> bytes allocated
//   rt_pool_capacity(handle)       -> bytes total
//   rt_pool_destroy(handle, unlink_file)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <map>
#include <string>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint64_t ALIGN = 64;

struct Block {
    uint64_t size;   // bytes of the block (aligned)
    bool free;
};

struct Pool {
    std::string path;
    int fd = -1;
    uint64_t capacity = 0;
    uint64_t used = 0;
    // offset -> block; adjacency by offset drives coalescing
    std::map<uint64_t, Block> blocks;
};

uint64_t align_up(uint64_t n) { return (n + ALIGN - 1) & ~(ALIGN - 1); }

}  // namespace

extern "C" {

void* rt_pool_create(const char* path, uint64_t capacity) {
    int fd = ::open(path, O_RDWR | O_CREAT, 0600);
    if (fd < 0) return nullptr;
    if (::ftruncate(fd, static_cast<off_t>(capacity)) != 0) {
        ::close(fd);
        return nullptr;
    }
    auto* p = new Pool();
    p->path = path;
    p->fd = fd;
    p->capacity = capacity;
    p->blocks[0] = Block{capacity, true};
    return p;
}

int64_t rt_pool_alloc(void* handle, uint64_t size) {
    auto* p = static_cast<Pool*>(handle);
    if (p == nullptr || size == 0) return -1;
    uint64_t need = align_up(size);
    for (auto it = p->blocks.begin(); it != p->blocks.end(); ++it) {
        if (!it->second.free || it->second.size < need) continue;
        uint64_t off = it->first;
        uint64_t remainder = it->second.size - need;
        it->second.free = false;
        it->second.size = need;
        if (remainder >= ALIGN) {
            p->blocks[off + need] = Block{remainder, true};
        } else {
            it->second.size += remainder;  // absorb the sliver
        }
        p->used += it->second.size;
        return static_cast<int64_t>(off);
    }
    return -1;  // caller evicts and retries
}

void rt_pool_free(void* handle, uint64_t offset) {
    auto* p = static_cast<Pool*>(handle);
    if (p == nullptr) return;
    auto it = p->blocks.find(offset);
    if (it == p->blocks.end() || it->second.free) return;
    it->second.free = true;
    p->used -= it->second.size;
    // coalesce with the next block
    auto next = std::next(it);
    if (next != p->blocks.end() && next->second.free &&
        it->first + it->second.size == next->first) {
        it->second.size += next->second.size;
        p->blocks.erase(next);
    }
    // coalesce with the previous block
    if (it != p->blocks.begin()) {
        auto prev = std::prev(it);
        if (prev->second.free &&
            prev->first + prev->second.size == it->first) {
            prev->second.size += it->second.size;
            p->blocks.erase(it);
        }
    }
}

uint64_t rt_pool_used(void* handle) {
    auto* p = static_cast<Pool*>(handle);
    return p ? p->used : 0;
}

uint64_t rt_pool_capacity(void* handle) {
    auto* p = static_cast<Pool*>(handle);
    return p ? p->capacity : 0;
}

uint64_t rt_pool_num_blocks(void* handle) {
    auto* p = static_cast<Pool*>(handle);
    return p ? p->blocks.size() : 0;
}

// Size of the allocated block at `offset`, or 0 when offset is not the
// start of a live allocation.  Lets the Python layer sanity-check a
// deferred (pin-held) free target before completing it.
uint64_t rt_pool_block_size(void* handle, uint64_t offset) {
    auto* p = static_cast<Pool*>(handle);
    if (p == nullptr) return 0;
    auto it = p->blocks.find(offset);
    if (it == p->blocks.end() || it->second.free) return 0;
    return it->second.size;
}

// Largest free block — the fragmentation signal surfaced by store stats
// and the `raytpu memory` report (a full-looking arena whose largest free
// block is tiny is fragmented, not out of capacity).
uint64_t rt_pool_largest_free(void* handle) {
    auto* p = static_cast<Pool*>(handle);
    if (p == nullptr) return 0;
    uint64_t best = 0;
    for (const auto& kv : p->blocks) {
        if (kv.second.free && kv.second.size > best) best = kv.second.size;
    }
    return best;
}

// Free-block sizes, written into the caller's buffer (up to max_n).
// Returns the TOTAL number of free blocks, which may exceed max_n — the
// caller then knows its histogram is a sample.  Feeds the arena
// fragmentation report (`raytpu memory`, raytpu_mem_arena_frag_fraction).
uint64_t rt_pool_free_blocks(void* handle, uint64_t* out, uint64_t max_n) {
    auto* p = static_cast<Pool*>(handle);
    if (p == nullptr) return 0;
    uint64_t n = 0;
    for (const auto& kv : p->blocks) {
        if (!kv.second.free) continue;
        if (out != nullptr && n < max_n) out[n] = kv.second.size;
        ++n;
    }
    return n;
}

void rt_pool_destroy(void* handle, int unlink_file) {
    auto* p = static_cast<Pool*>(handle);
    if (p == nullptr) return;
    if (p->fd >= 0) ::close(p->fd);
    if (unlink_file) ::unlink(p->path.c_str());
    delete p;
}

}  // extern "C"
