"""ray_tpu.native — C++ runtime components.

The compute path is JAX/XLA/Pallas; the runtime around it goes native where
the reference's does (SURVEY §2.1: the store/allocator layer is C++ plasma).
Components build on first use with g++ (baked into the image; pybind11 is
not, so the ABI is plain C consumed via ctypes).

``shm_pool``: single-mmap arena allocator backing the object store — the
plasma design (one mapping per node, objects are offsets) instead of the
round-1 file-per-object layout (which paid open+ftruncate+mmap+page-zero on
every put).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_BUILD_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LIB_FAILED = False

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "shm_pool.cpp")


def _build_lib(src: str = _SRC, name: str = "libshmpool.so"
               ) -> Optional[str]:
    """Compile a .so next to its source (cached by mtime)."""
    out = os.path.join(os.path.dirname(src), name)
    try:
        if (os.path.exists(out)
                and os.path.getmtime(out) >= os.path.getmtime(src)):
            return out
        tmp = out + f".tmp{os.getpid()}"
        subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-std=c++17", src, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)
        return out
    except Exception:
        return None


def load_shm_pool() -> Optional[ctypes.CDLL]:
    """The compiled allocator, or None (callers fall back to pure Python)."""
    global _LIB, _LIB_FAILED
    if _LIB is not None or _LIB_FAILED:
        return _LIB
    with _BUILD_LOCK:
        if _LIB is not None or _LIB_FAILED:
            return _LIB
        path = _build_lib()
        if path is None:
            _LIB_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            _LIB_FAILED = True
            return None
        lib.rt_pool_create.restype = ctypes.c_void_p
        lib.rt_pool_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.rt_pool_alloc.restype = ctypes.c_int64
        lib.rt_pool_alloc.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rt_pool_free.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.rt_pool_used.restype = ctypes.c_uint64
        lib.rt_pool_used.argtypes = [ctypes.c_void_p]
        lib.rt_pool_capacity.restype = ctypes.c_uint64
        lib.rt_pool_capacity.argtypes = [ctypes.c_void_p]
        lib.rt_pool_num_blocks.restype = ctypes.c_uint64
        lib.rt_pool_num_blocks.argtypes = [ctypes.c_void_p]
        lib.rt_pool_destroy.argtypes = [ctypes.c_void_p, ctypes.c_int]
        # Introspection (older cached .so builds may predate these)
        for sym, res, args in (
                ("rt_pool_block_size", ctypes.c_uint64,
                 [ctypes.c_void_p, ctypes.c_uint64]),
                ("rt_pool_largest_free", ctypes.c_uint64,
                 [ctypes.c_void_p]),
                ("rt_pool_free_blocks", ctypes.c_uint64,
                 [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64),
                  ctypes.c_uint64])):
            fn = getattr(lib, sym, None)
            if fn is not None:
                fn.restype = res
                fn.argtypes = args
        _LIB = lib
        return _LIB


_SP_LIB: Optional[ctypes.CDLL] = None
_SP_FAILED = False


def load_submit_plane() -> Optional[ctypes.CDLL]:
    """The packed spec-frame packer/scanner (``sp_pack``/``sp_scan``), or
    None — callers use the byte-identical pure-Python struct path.  A
    missing compiler, a wedged cached .so, or a stale build lacking the
    symbols degrades to the fallback with ONE warning; importing this
    module never fails on native-build problems."""
    global _SP_LIB, _SP_FAILED
    if _SP_LIB is not None or _SP_FAILED:
        return _SP_LIB
    with _BUILD_LOCK:
        if _SP_LIB is not None or _SP_FAILED:
            return _SP_LIB
        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "submit_plane.cpp")
        path = _build_lib(src, "libsubmitplane.so")
        lib = None
        if path is not None:
            try:
                lib = ctypes.CDLL(path)
                lib.sp_pack.restype = ctypes.c_int64
                lib.sp_pack.argtypes = [
                    ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
                    ctypes.c_char_p, ctypes.c_char_p,
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint64),
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_char_p),
                    ctypes.POINTER(ctypes.c_uint32)]
                lib.sp_scan.restype = ctypes.c_int32
                lib.sp_scan.argtypes = [
                    ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint32,
                    ctypes.POINTER(ctypes.c_uint64),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint64),
                    ctypes.POINTER(ctypes.c_uint32),
                    ctypes.POINTER(ctypes.c_uint32)]
            except (OSError, AttributeError):
                lib = None
        if lib is None:
            _SP_FAILED = True
            import warnings
            warnings.warn(
                "native submit-plane encoder unavailable (build or load "
                "failed); using the pure-Python packed-frame fallback",
                RuntimeWarning, stacklevel=2)
            return None
        _SP_LIB = lib
        return _SP_LIB


def submit_plane_loaded() -> bool:
    """Whether the native packer is currently live — pure introspection,
    never triggers a build (False before first use AND after a failed
    build; the counters plane reports actual state, not intent)."""
    return _SP_LIB is not None


_CRC_LIB: Optional[ctypes.CDLL] = None
_CRC_FAILED = False


def load_crc32c():
    """Native CRC-32C ``fn(data: bytes) -> int``, or None (callers fall
    back to the pure-Python table loop). SSE4.2 hardware CRC when the
    CPU has it — the TFRecord/TensorBoard write paths checksum every
    payload, where ~10 MB/s pure Python is the bottleneck."""
    global _CRC_LIB, _CRC_FAILED
    if _CRC_LIB is not None or _CRC_FAILED:
        return _crc_fn if _CRC_LIB is not None else None
    with _BUILD_LOCK:
        if _CRC_LIB is not None or _CRC_FAILED:
            return _crc_fn if _CRC_LIB is not None else None
        src = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "crc32c.cpp")
        path = _build_lib(src, "libcrc32c.so")
        if path is None:
            _CRC_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(path)
            lib.rt_crc32c.restype = ctypes.c_uint32
            lib.rt_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_uint32]
        except (OSError, AttributeError):
            _CRC_FAILED = True
            return None
        _CRC_LIB = lib
        return _crc_fn


def _crc_fn(data, seed: int = 0) -> int:
    if isinstance(data, bytes):
        return _CRC_LIB.rt_crc32c(data, len(data), seed)
    # Buffer-protocol payloads (the transfer plane checksums chunks that
    # landed directly in a shm segment view): hand ctypes the buffer
    # in place — round-tripping through bytes() would copy the chunk.
    mv = memoryview(data)
    if mv.readonly:
        arr = (ctypes.c_char * mv.nbytes).from_buffer_copy(mv)
    else:
        arr = (ctypes.c_char * mv.nbytes).from_buffer(mv)
    return _CRC_LIB.rt_crc32c(arr, mv.nbytes, seed)


class ShmPool:
    """Owner-side arena: allocate/free offsets in one shm mapping."""

    def __init__(self, path: str, capacity: int):
        lib = load_shm_pool()
        if lib is None:
            raise RuntimeError("native shm pool unavailable (no g++?)")
        self._lib = lib
        self.path = path
        self._handle = lib.rt_pool_create(path.encode(), capacity)
        if not self._handle:
            raise OSError(f"failed to create shm pool at {path}")
        import mmap as _mmap
        fd = os.open(path, os.O_RDWR)
        try:
            self._mm = _mmap.mmap(fd, capacity)
        finally:
            os.close(fd)

    def alloc(self, size: int) -> int:
        """-> offset, or -1 when the arena is full (caller evicts)."""
        return self._lib.rt_pool_alloc(self._handle, size)

    def free(self, offset: int):
        self._lib.rt_pool_free(self._handle, offset)

    def view(self, offset: int, size: int) -> memoryview:
        return memoryview(self._mm)[offset:offset + size]

    @property
    def used(self) -> int:
        return self._lib.rt_pool_used(self._handle)

    @property
    def capacity(self) -> int:
        return self._lib.rt_pool_capacity(self._handle)

    @property
    def num_blocks(self) -> int:
        return self._lib.rt_pool_num_blocks(self._handle)

    def block_size(self, offset: int) -> int:
        """Size of the live allocation at ``offset`` (0 = not allocated)."""
        fn = getattr(self._lib, "rt_pool_block_size", None)
        return int(fn(self._handle, offset)) if fn is not None else 0

    @property
    def largest_free(self) -> int:
        """Largest free block — the arena's fragmentation signal."""
        fn = getattr(self._lib, "rt_pool_largest_free", None)
        return int(fn(self._handle)) if fn is not None else 0

    def free_blocks(self, max_n: int = 4096) -> list:
        """Sizes of up to ``max_n`` free blocks (the fragmentation
        histogram's raw data); [] when the cached .so predates the
        introspection symbol."""
        fn = getattr(self._lib, "rt_pool_free_blocks", None)
        if fn is None or not self._handle:
            return []
        buf = (ctypes.c_uint64 * max_n)()
        n = int(fn(self._handle, buf, max_n))
        return [int(buf[i]) for i in range(min(n, max_n))]

    def close(self, unlink: bool = True):
        if self._handle:
            try:
                self._mm.close()
            except Exception:
                pass
            self._lib.rt_pool_destroy(self._handle, 1 if unlink else 0)
            self._handle = None
