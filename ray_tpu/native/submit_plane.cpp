// Native submission plane: packed spec-batch frame pack/scan.
//
// A warm push batch wire-encodes into ONE flat frame instead of N pickled
// tuples (see core/spec_cache.py for the layout contract):
//
//   "SP01" | u32 count
//   per record:
//     thash(16) | task_id(16) | retry u32 | seq u64 | args_len u32
//     | trace_len u32 | args bytes | trace bytes
//
// All integers little-endian, headers packed (no padding) — the layout
// MUST stay byte-identical to the pure-Python struct packer/scanner
// (spec_cache._py_pack / unpack_specs), which is the fallback when this
// .so is absent.  Plain C ABI, consumed via ctypes (no pybind11 in the
// image — same toolchain as shm_pool.cpp / crc32c.cpp).

#include <cstdint>
#include <cstring>

namespace {
constexpr uint64_t kRecFixed = 52;  // 16 + 16 + 4 + 8 + 4 + 4
}

extern "C" {

// Pack n records into `out` (caller sized it exactly); returns bytes
// written, or -1 when the buffer cannot hold the frame.
int64_t sp_pack(uint8_t* out, uint64_t cap, uint32_t n,
                const uint8_t* thash, const uint8_t* task_ids,
                const uint32_t* retries, const uint64_t* seqs,
                const uint8_t* const* args_ptrs, const uint32_t* args_lens,
                const uint8_t* const* trace_ptrs,
                const uint32_t* trace_lens) {
    if (cap < 8) return -1;
    out[0] = 'S'; out[1] = 'P'; out[2] = '0'; out[3] = '1';
    std::memcpy(out + 4, &n, 4);
    uint64_t off = 8;
    for (uint32_t i = 0; i < n; i++) {
        const uint32_t alen = args_lens[i], tlen = trace_lens[i];
        if (off + kRecFixed + (uint64_t)alen + tlen > cap) return -1;
        std::memcpy(out + off, thash + (uint64_t)i * 16, 16);
        std::memcpy(out + off + 16, task_ids + (uint64_t)i * 16, 16);
        std::memcpy(out + off + 32, &retries[i], 4);
        std::memcpy(out + off + 36, &seqs[i], 8);
        std::memcpy(out + off + 44, &alen, 4);
        std::memcpy(out + off + 48, &tlen, 4);
        off += kRecFixed;
        if (alen) { std::memcpy(out + off, args_ptrs[i], alen); off += alen; }
        if (tlen) { std::memcpy(out + off, trace_ptrs[i], tlen); off += tlen; }
    }
    return (int64_t)off;
}

// Scan a frame: fill per-record offsets + header fields so Python only
// slices payload views.  Returns the record count, or -1 on a malformed/
// truncated frame (the receiver raises before dispatching anything).
int32_t sp_scan(const uint8_t* blob, uint64_t len, uint32_t max_n,
                uint64_t* rec_offs, uint32_t* retries, uint64_t* seqs,
                uint32_t* args_lens, uint32_t* trace_lens) {
    if (len < 8 || blob[0] != 'S' || blob[1] != 'P' || blob[2] != '0' ||
        blob[3] != '1')
        return -1;
    uint32_t n;
    std::memcpy(&n, blob + 4, 4);
    if (n > max_n) return -1;
    uint64_t off = 8;
    for (uint32_t i = 0; i < n; i++) {
        if (off + kRecFixed > len) return -1;
        rec_offs[i] = off;
        uint32_t alen, tlen;
        std::memcpy(&retries[i], blob + off + 32, 4);
        std::memcpy(&seqs[i], blob + off + 36, 8);
        std::memcpy(&alen, blob + off + 44, 4);
        std::memcpy(&tlen, blob + off + 48, 4);
        args_lens[i] = alen;
        trace_lens[i] = tlen;
        off += kRecFixed + (uint64_t)alen + tlen;
        if (off > len) return -1;
    }
    return (int32_t)n;
}

}  // extern "C"
