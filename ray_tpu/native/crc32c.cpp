// CRC-32C (Castagnoli) for the data path: TFRecord framing and the
// TensorBoard event writer checksum every payload (reference: the C++
// crc32c library TensorFlow links; SURVEY §2.1 — data loaders are
// native where they are hot).  Python's stdlib only ships CRC-32
// (0x04C11DB7); the pure-Python Castagnoli loop runs ~10 MB/s, which
// makes the checksum — not the disk — the bottleneck when writing
// datasets.  Here: SSE4.2 hardware CRC when the CPU has it (~GB/s),
// slice-by-8 tables otherwise.  Plain-C ABI, consumed via ctypes.

#include <cstddef>
#include <cstdint>

static uint32_t T[8][256];

static struct TableInit {
    TableInit() {
        for (int i = 0; i < 256; i++) {
            uint32_t c = (uint32_t)i;
            for (int k = 0; k < 8; k++)
                c = (c & 1u) ? (c >> 1) ^ 0x82F63B78u : c >> 1;
            T[0][i] = c;
        }
        for (int i = 0; i < 256; i++) {
            uint32_t c = T[0][i];
            for (int j = 1; j < 8; j++) {
                c = T[0][c & 0xFFu] ^ (c >> 8);
                T[j][i] = c;
            }
        }
    }
} table_init;

static uint32_t crc_sw(const uint8_t *p, size_t n, uint32_t crc) {
    while (n >= 8) {
        uint32_t lo = (uint32_t)p[0] | ((uint32_t)p[1] << 8)
                      | ((uint32_t)p[2] << 16) | ((uint32_t)p[3] << 24);
        uint32_t hi = (uint32_t)p[4] | ((uint32_t)p[5] << 8)
                      | ((uint32_t)p[6] << 16) | ((uint32_t)p[7] << 24);
        lo ^= crc;
        crc = T[7][lo & 0xFFu] ^ T[6][(lo >> 8) & 0xFFu]
              ^ T[5][(lo >> 16) & 0xFFu] ^ T[4][lo >> 24]
              ^ T[3][hi & 0xFFu] ^ T[2][(hi >> 8) & 0xFFu]
              ^ T[1][(hi >> 16) & 0xFFu] ^ T[0][hi >> 24];
        p += 8;
        n -= 8;
    }
    while (n--)
        crc = T[0][(crc ^ *p++) & 0xFFu] ^ (crc >> 8);
    return crc;
}

// x86-64 only: the crc32q builtin does not exist in 32-bit mode and
// would fail the whole compile, losing the software path too
#if defined(__x86_64__)
__attribute__((target("sse4.2")))
static uint32_t crc_hw(const uint8_t *p, size_t n, uint32_t crc) {
    uint64_t c64 = crc;
    while (n >= 8) {
        uint64_t v;
        __builtin_memcpy(&v, p, 8);
        c64 = __builtin_ia32_crc32di(c64, v);
        p += 8;
        n -= 8;
    }
    uint32_t c = (uint32_t)c64;
    while (n--)
        c = __builtin_ia32_crc32qi(c, *p++);
    return c;
}
static const bool has_sse42 = __builtin_cpu_supports("sse4.2");
#else
static const bool has_sse42 = false;
static uint32_t crc_hw(const uint8_t *p, size_t n, uint32_t crc) {
    return crc_sw(p, n, crc);
}
#endif

extern "C" uint32_t rt_crc32c(const uint8_t *data, size_t len,
                              uint32_t seed) {
    uint32_t crc = ~seed;
    crc = has_sse42 ? crc_hw(data, len, crc) : crc_sw(data, len, crc);
    return ~crc;
}
