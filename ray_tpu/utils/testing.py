"""Test helpers: virtual CPU device meshes (SURVEY §4 takeaway — a fake mesh/ICI
backend so multi-host pjit code paths run in CI without TPUs)."""

from __future__ import annotations

import os


def force_cpu_devices(n: int = 8) -> None:
    """Force jax onto `n` virtual CPU devices for this process.

    Must run before the first jax backend use.  Overrides both the env and
    jax.config because TPU-terminal environments (axon) force
    ``jax_platforms`` from sitecustomize at interpreter start.
    """
    flag = f"--xla_force_host_platform_device_count={n}"
    xf = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in xf:
        os.environ["XLA_FLAGS"] = (xf + " " + flag).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass


#: Environment for subprocess workers that should see the virtual CPU mesh.
#: PALLAS_AXON_POOL_IPS="" disables the axon sitecustomize registration hook
#: so JAX_PLATFORMS from the env is honored in the child.
CPU_WORKER_ENV = {
    "JAX_PLATFORMS": "cpu",
    "PALLAS_AXON_POOL_IPS": "",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}
