"""ray_tpu.utils — shared helpers (testing, logging, metrics)."""
