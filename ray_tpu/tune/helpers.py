"""Trainable wrappers (reference: ``python/ray/tune/trainable/util.py`` —
``tune.with_resources`` and ``tune.with_parameters``)."""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict


def with_resources(trainable: Callable, resources: Dict[str, float]):
    """Attach a per-trial resource request to a trainable (reference:
    ``tune.with_resources``).  The Tuner reads the annotation instead of
    needing ``resources_per_trial`` threaded through.  Always returns a
    FRESH wrapper — annotating the argument in place would alias every
    earlier wrapping of the same trainable."""

    @functools.wraps(trainable)
    def wrapped(config):
        return trainable(config)

    if hasattr(trainable, "_raytpu_params"):
        wrapped._raytpu_params = trainable._raytpu_params
    wrapped._raytpu_resources = dict(resources)
    return wrapped


def with_parameters(trainable: Callable, **parameters: Any):
    """Partially apply large/constant objects OUTSIDE the config dict
    (reference: ``tune.with_parameters`` — the reference stores them in
    the object store once; here the wrapper ships by value with the
    function, which the function registry already stores once per
    cluster)."""

    @functools.wraps(trainable)
    def wrapped(config):
        return trainable(config, **parameters)

    wrapped._raytpu_params = dict(parameters)
    if hasattr(trainable, "_raytpu_resources"):
        wrapped._raytpu_resources = trainable._raytpu_resources
    return wrapped
