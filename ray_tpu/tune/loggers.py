"""Per-trial result loggers: progress.csv, result.json, TensorBoard events.

Reference: ``python/ray/tune/logger/`` (CSVLoggerCallback, JsonLoggerCallback,
TBXLoggerCallback).  Files land inside each trial's directory so a user can
``tail -f`` a live trial or point TensorBoard at the experiment dir — the two
artifacts VERDICT r3 called out as missing (only experiment_state.json
existed).

The TensorBoard writer is offline-safe and dependency-free: tfevents files
are length-delimited records with TFRecord masked CRCs (the framing already
implemented for the TFRecord datasource) around hand-encoded ``Event``
protobufs — only scalar summaries are written, which is what tune metrics
are.
"""

from __future__ import annotations

import csv
import json
import numbers
import os
import struct
import time
from typing import Any, Dict, List, Optional

from ..data.datasource import _masked_crc32c


class TrialLoggers:
    """All three per-trial writers behind one open/log/close surface."""

    def __init__(self, trial_dir: str, trial_id: str):
        os.makedirs(trial_dir, exist_ok=True)
        self.trial_dir = trial_dir
        self._csv_path = os.path.join(trial_dir, "progress.csv")
        self._json_path = os.path.join(trial_dir, "result.json")
        self._csv_fields: Optional[List[str]] = None
        self._csv_f = None
        self._csv_writer = None
        self._json_f = None
        self._tb = _TBEventWriter(trial_dir, trial_id)
        self._step = 0

    def log(self, result: Dict[str, Any]):
        flat = _flatten(result)
        self._step = int(flat.get("training_iteration", self._step + 1))
        # result.json: one JSON object per line (jsonl), full fidelity.
        if self._json_f is None:
            self._json_f = open(self._json_path, "a", buffering=1)
        self._json_f.write(json.dumps(flat, default=str) + "\n")
        # progress.csv: columns fixed by the first result (reference CSV
        # logger semantics); later keys outside the set are dropped.
        if self._csv_writer is None:
            self._csv_fields = sorted(flat.keys())
            new = not os.path.exists(self._csv_path) \
                or os.path.getsize(self._csv_path) == 0
            self._csv_f = open(self._csv_path, "a", buffering=1, newline="")
            self._csv_writer = csv.DictWriter(self._csv_f, self._csv_fields,
                                              extrasaction="ignore")
            if new:
                self._csv_writer.writeheader()
        self._csv_writer.writerow({k: flat.get(k, "") for k in self._csv_fields})
        # tfevents: numeric scalars only.
        scalars = {k: float(v) for k, v in flat.items()
                   if isinstance(v, numbers.Real) and not isinstance(v, bool)}
        self._tb.write_scalars(self._step, scalars)

    def close(self):
        for f in (self._csv_f, self._json_f):
            if f is not None:
                try:
                    f.close()
                except OSError:
                    pass
        self._tb.close()


def _flatten(d: Dict[str, Any], prefix: str = "") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in d.items():
        key = f"{prefix}{k}"
        if isinstance(v, dict):
            out.update(_flatten(v, key + "/"))
        else:
            out[key] = v
    return out


# ---------------------------------------------------------------------------
# Hand-rolled tfevents writer
# ---------------------------------------------------------------------------

def _varint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _ld(num: int, payload: bytes) -> bytes:   # length-delimited
    return _field(num, 2) + _varint(len(payload)) + payload


def _scalar_summary(tag: str, value: float) -> bytes:
    # Summary.Value { tag = 1 (string), simple_value = 2 (float) }
    val = _ld(1, tag.encode()) + _field(2, 5) + struct.pack("<f", value)
    return _ld(1, val)  # Summary { value = 1 (repeated) }


def _event(wall_time: float, step: int, summary: Optional[bytes] = None,
           file_version: Optional[str] = None) -> bytes:
    # Event { wall_time = 1 (double), step = 2 (int64),
    #         file_version = 3 (string), summary = 5 (message) }
    msg = _field(1, 1) + struct.pack("<d", wall_time)
    msg += _field(2, 0) + _varint(step & 0xFFFFFFFFFFFFFFFF)
    if file_version is not None:
        msg += _ld(3, file_version.encode())
    if summary is not None:
        msg += _ld(5, summary)
    return msg


class _TBEventWriter:
    """events.out.tfevents.* writer (TFRecord framing, Event protos)."""

    def __init__(self, logdir: str, suffix: str):
        self._path = os.path.join(
            logdir, f"events.out.tfevents.{int(time.time())}.{suffix}")
        self._f = None

    def _record(self, payload: bytes) -> bytes:
        header = struct.pack("<Q", len(payload))
        return (header + struct.pack("<I", _masked_crc32c(header))
                + payload + struct.pack("<I", _masked_crc32c(payload)))

    def _ensure_open(self):
        if self._f is None:
            self._f = open(self._path, "ab")
            self._f.write(self._record(
                _event(time.time(), 0, file_version="brain.Event:2")))

    def write_scalars(self, step: int, scalars: Dict[str, float]):
        if not scalars:
            return
        self._ensure_open()
        summary = b"".join(_scalar_summary(k, v) for k, v in scalars.items())
        self._f.write(self._record(_event(time.time(), step, summary=summary)))
        self._f.flush()

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
