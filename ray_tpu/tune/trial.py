"""Trial state + the trial-runner actor.

Reference: ``python/ray/tune/experiment/trial.py`` (Trial state machine) and
the trainable actor the TuneController drives.  The runner actor uses the same
thread + result-queue protocol as the Train worker (worker_group.py) — the
controller pulls one result at a time and releases the barrier, so scheduler
decisions (stop/perturb) apply at report boundaries exactly like the
reference's ``Trainable.train()`` stepping.
"""

from __future__ import annotations

import dataclasses
import os
import threading
import uuid
from typing import Any, Dict, Optional, Set

PENDING = "PENDING"
RUNNING = "RUNNING"
TERMINATED = "TERMINATED"
ERROR = "ERROR"


@dataclasses.dataclass
class Trial:
    trial_id: str
    config: Dict[str, Any]
    experiment_dir: str
    status: str = PENDING
    last_result: Optional[Dict[str, Any]] = None
    metrics_history: list = dataclasses.field(default_factory=list)
    error: Optional[str] = None
    latest_checkpoint: Optional[str] = None
    runner: Any = None  # actor handle
    iteration: int = 0
    rungs_passed: Set[int] = dataclasses.field(default_factory=set)
    restarts: int = 0
    # Monotonic checkpoint counter: checkpoint dirs must not be keyed on
    # training_iteration, which resets to 1 after a PBT perturb / failure
    # restart and would merge fresh files into a stale directory.
    ckpt_seq: int = 0
    # Per-trial resource override (ResourceChangingScheduler); None = the
    # experiment-wide resources_per_trial.
    resources: Optional[Dict[str, float]] = None
    _pending_ref: Any = None  # outstanding next_result ref (controller-owned)

    @property
    def trial_dir(self) -> str:
        d = os.path.join(self.experiment_dir, self.trial_id)
        os.makedirs(d, exist_ok=True)
        return d

    @staticmethod
    def new(config: Dict[str, Any], experiment_dir: str,
            name: Optional[str] = None) -> "Trial":
        tid = name or f"trial_{uuid.uuid4().hex[:8]}"
        return Trial(trial_id=tid, config=config,
                     experiment_dir=experiment_dir)


class TrialRunner:
    """Actor: runs the trainable function, reports via the tune session."""

    def __init__(self, env: Optional[Dict[str, str]] = None):
        for k, v in (env or {}).items():
            os.environ[k] = v
        self._session = None
        self._thread: Optional[threading.Thread] = None

    def run(self, trainable, config: Dict[str, Any], trial_id: str,
            trial_dir: str, checkpoint_path: Optional[str],
            resources: Optional[Dict[str, float]] = None) -> None:
        from . import session as tune_session
        from ..train.checkpoint import Checkpoint
        from ..train.context import SessionFinished

        sess = tune_session._TuneSession(
            trial_id=trial_id, trial_dir=trial_dir,
            checkpoint=Checkpoint(checkpoint_path) if checkpoint_path else None,
            resources=resources)
        self._session = sess
        tune_session._set_session(sess)

        def target():
            try:
                out = trainable(config)
                sess._finish(out)
            except SessionFinished:
                sess._finish(None)
            except BaseException as e:  # noqa: BLE001 — forwarded to driver
                sess._fail(e)

        self._thread = threading.Thread(target=target, daemon=True,
                                        name=f"tune-{trial_id}")
        self._thread.start()

    def next_result(self, timeout: float = 3600.0):
        kind, payload, ckpt = self._session._next(timeout)
        if kind == "error":
            raise payload
        return kind, payload, ckpt

    def resume(self) -> None:
        self._session._resume()

    def abort(self) -> None:
        if self._session is not None:
            self._session._abort()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
