"""Trial schedulers — reference ``python/ray/tune/schedulers/``:
FIFO (default), ASHA (``async_hyperband.py``), median stopping
(``median_stopping_rule.py``), PBT (``pbt.py``).

Decisions are made per reported result: CONTINUE, STOP (early termination) or
a PBT exploit/explore restart (returned as (PERTURB, new_config,
clone_from_trial_id) — the controller handles the checkpoint transplant).
"""

from __future__ import annotations

import math
import random
from typing import Any, Dict, List, Optional, Tuple

CONTINUE = "CONTINUE"
STOP = "STOP"
PERTURB = "PERTURB"
RESIZE = "RESIZE"


class TrialScheduler:
    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def _score(self, result: Dict[str, Any]) -> Optional[float]:
        v = result.get(self.metric)
        if v is None:
            return None
        v = float(v)
        return v if self.mode == "max" else -v

    def on_result(self, trial, result: Dict[str, Any]):
        return CONTINUE

    def on_complete(self, trial, result: Optional[Dict[str, Any]]) -> None:
        pass


class FIFOScheduler(TrialScheduler):
    """No early stopping."""


class AsyncHyperBandScheduler(TrialScheduler):
    """ASHA: rungs at r*eta^k iterations; a trial stops at a rung if its
    score is below the top-1/eta quantile of completed rung entries
    (asynchronous successive halving — no waiting for full brackets)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 100, grace_period: int = 1,
                 reduction_factor: float = 4.0):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.grace = grace_period
        self.eta = reduction_factor
        # rung iteration -> list of scores recorded at that rung
        self.rungs: Dict[int, List[float]] = {}
        r = grace_period
        while r < max_t:
            self.rungs[int(r)] = []
            r *= reduction_factor

    def on_result(self, trial, result):
        t = result.get(self.time_attr)
        score = self._score(result)
        if t is None or score is None:
            return CONTINUE
        if t >= self.max_t:
            return STOP
        for rung_t in sorted(self.rungs, reverse=True):
            if t >= rung_t and rung_t not in trial.rungs_passed:
                trial.rungs_passed.add(rung_t)
                scores = self.rungs[rung_t]
                scores.append(score)
                k = max(1, int(len(scores) / self.eta))
                cutoff = sorted(scores, reverse=True)[k - 1]
                if score < cutoff:
                    return STOP
                break
        return CONTINUE


class HyperBandScheduler(TrialScheduler):
    """HyperBand: multiple successive-halving brackets with different
    exploration/exploitation trade-offs (Li et al., JMLR 2018; reference
    ``python/ray/tune/schedulers/hyperband.py``).

    Asynchronous variant: incoming trials are assigned round-robin to
    brackets; bracket ``s`` starts its rungs at ``grace * eta^s`` so
    aggressive brackets kill early and conservative ones let everything
    run long.  Within a bracket the rung rule is ASHA's (top-1/eta
    quantile continues) — the synchronous pause/resume machinery of the
    original is deliberately traded for never idling a chip while a rung
    waits to fill.
    """

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 max_t: int = 81, reduction_factor: float = 3.0):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.max_t = max_t
        self.eta = reduction_factor
        s_max = max(1, int(math.log(max_t) / math.log(reduction_factor)))
        self.brackets: List[AsyncHyperBandScheduler] = [
            AsyncHyperBandScheduler(
                metric, mode, time_attr=time_attr, max_t=max_t,
                grace_period=max(1, int(reduction_factor ** s)),
                reduction_factor=reduction_factor)
            for s in range(s_max)
        ]
        self._assignment: Dict[str, int] = {}
        self._next = 0

    def on_result(self, trial, result):
        for bracket in self.brackets:
            # the controller patches metric/mode onto the outer scheduler
            # after construction (controller fix-up for metric=None) —
            # propagate so the brackets actually score
            bracket.metric, bracket.mode = self.metric, self.mode
        b = self._assignment.get(trial.trial_id)
        if b is None:
            b = self._assignment[trial.trial_id] = (
                self._next % len(self.brackets))
            self._next += 1
        return self.brackets[b].on_result(trial, result)


class MedianStoppingRule(TrialScheduler):
    """Stop a trial whose best score so far is below the median of other
    trials' running averages at the same point in time."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 grace_period: int = 1, min_samples_required: int = 3):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.grace = grace_period
        self.min_samples = min_samples_required
        self._avgs: Dict[str, Tuple[float, int]] = {}  # trial -> (sum, n)

    def on_result(self, trial, result):
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is None:
            return CONTINUE
        s, n = self._avgs.get(trial.trial_id, (0.0, 0))
        self._avgs[trial.trial_id] = (s + score, n + 1)
        if t < self.grace or len(self._avgs) < self.min_samples:
            return CONTINUE
        others = [s / n for tid, (s, n) in self._avgs.items()
                  if tid != trial.trial_id and n > 0]
        if not others:
            return CONTINUE
        median = sorted(others)[len(others) // 2]
        mine_s, mine_n = self._avgs[trial.trial_id]
        if mine_s / mine_n < median:
            return STOP
        return CONTINUE


class PopulationBasedTraining(TrialScheduler):
    """PBT: every perturbation_interval, bottom-quantile trials exploit a
    top-quantile trial (clone its checkpoint) and explore (mutate config) —
    reference ``pbt.py`` exploit/explore."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_mutations: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 resample_probability: float = 0.25,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.time_attr = time_attr
        self.interval = perturbation_interval
        self.mutations = hyperparam_mutations or {}
        self.quantile = quantile_fraction
        self.resample_p = resample_probability
        self.rng = random.Random(seed)
        self.latest: Dict[str, float] = {}  # trial_id -> latest score
        # Cumulative per-trial perturb time (reference pbt.py
        # last_perturbation_time): survives trial restarts, so a restarted
        # trial whose time_attr resets cannot re-trigger immediately.
        self._last_perturb: Dict[str, float] = {}

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        from .search import Domain
        new = dict(config)
        for key, mut in self.mutations.items():
            if self.rng.random() < self.resample_p or key not in new:
                if isinstance(mut, Domain):
                    new[key] = mut.sample(self.rng)
                elif isinstance(mut, list):
                    new[key] = self.rng.choice(mut)
                elif callable(mut):
                    new[key] = mut()
            else:
                factor = self.rng.choice([0.8, 1.2])
                if isinstance(new[key], (int, float)):
                    new[key] = new[key] * factor
                    if isinstance(mut, list):  # snap to closest allowed
                        new[key] = min(mut, key=lambda v: abs(v - new[key]))
        return new

    def on_result(self, trial, result):
        t = result.get(self.time_attr, 0)
        score = self._score(result)
        if score is not None:
            self.latest[trial.trial_id] = score
        self._observe(trial, t, score)
        last = self._last_perturb.get(trial.trial_id, 0)
        if t is None or t - last < self.interval:
            return CONTINUE
        if len(self.latest) < 2:
            return CONTINUE
        # Perturb time advances whether or not this trial exploits
        # (reference pbt.py updates last_perturbation_time unconditionally).
        self._last_perturb[trial.trial_id] = t
        ranked = sorted(self.latest.items(), key=lambda kv: kv[1])
        k = max(1, int(len(ranked) * self.quantile))
        bottom = [tid for tid, _ in ranked[:k]]
        top = [tid for tid, _ in ranked[-k:]]
        if trial.trial_id in bottom and trial.trial_id not in top:
            donor = self.rng.choice(top)
            return (PERTURB, self._explore(trial.config), donor)
        return CONTINUE

    def _observe(self, trial, t, score):
        """Hook for PB2's reward-curve bookkeeping (no-op for plain PBT)."""


class PB2(PopulationBasedTraining):
    """Population Based Bandits: PBT whose EXPLORE step selects the new
    hyperparameters with a GP-UCB model over observed (config -> reward
    improvement) data instead of random resample/×0.8/×1.2 perturbation.

    Reference: ``python/ray/tune/schedulers/pb2.py`` (Parker-Holder et al.,
    NeurIPS 2020).  Kept self-contained: the exact-GP fit (RBF kernel +
    Cholesky) follows tune/search.py's BayesOptSearcher, the acquisition is
    UCB maximized over candidate configs sampled from the mutation bounds.
    Only numeric hyperparameters participate in the model (categorical
    mutations fall back to PBT-style resampling — same as the reference,
    which requires continuous bounds)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max",
                 time_attr: str = "training_iteration",
                 perturbation_interval: int = 4,
                 hyperparam_bounds: Optional[Dict[str, Any]] = None,
                 quantile_fraction: float = 0.25,
                 ucb_kappa: float = 2.0,
                 candidates: int = 256,
                 seed: Optional[int] = None):
        super().__init__(metric, mode, time_attr, perturbation_interval,
                         hyperparam_mutations=dict(hyperparam_bounds or {}),
                         quantile_fraction=quantile_fraction, seed=seed)
        self.bounds: Dict[str, tuple] = {
            k: tuple(v) for k, v in (hyperparam_bounds or {}).items()
            if isinstance(v, (list, tuple)) and len(v) == 2
            and all(isinstance(x, (int, float)) for x in v)}
        self.kappa = ucb_kappa
        self.candidates = candidates
        # trial_id -> (t, score) of the previous observation; the GP's y is
        # the per-interval score DELTA (PB2 models reward improvement).
        self._prev: Dict[str, tuple] = {}
        self._data: list = []      # (config_vec, delta)

    def _vec(self, config) -> Optional[list]:
        try:
            return [self._norm01(k, float(config[k])) for k in self.bounds]
        except (KeyError, TypeError, ValueError):
            return None

    def _norm01(self, key, v):
        lo, hi = self.bounds[key]
        return (v - lo) / (hi - lo) if hi > lo else 0.0

    def _observe(self, trial, t, score):
        if score is None or t is None or not self.bounds:
            return
        prev = self._prev.get(trial.trial_id)
        self._prev[trial.trial_id] = (t, score)
        if prev is None or t <= prev[0]:
            return
        vec = self._vec(trial.config)
        if vec is None:
            return
        # `score` arrives via TrialScheduler._score, which already negates
        # for mode="min" — deltas here are maximize-oriented as-is.
        self._data.append((vec, (score - prev[1]) / (t - prev[0])))
        if len(self._data) > 512:
            self._data = self._data[-512:]

    def _explore(self, config: Dict[str, Any]) -> Dict[str, Any]:
        import numpy as np
        if not self.bounds or len(self._data) < 4:
            return self._explore_fallback(config)
        X = np.array([v for v, _ in self._data])
        y = np.array([d for _, d in self._data])
        ystd = y.std() or 1.0
        yn = (y - y.mean()) / ystd

        def kern(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / 0.2 ** 2)

        K = kern(X, X) + 1e-4 * np.eye(len(X))
        try:
            L = np.linalg.cholesky(K)
        except np.linalg.LinAlgError:
            return self._explore_fallback(config)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        # Candidate configs sampled uniformly inside the bounds; pick the
        # UCB argmax.
        cand = np.array([[self.rng.random() for _ in self.bounds]
                         for _ in range(self.candidates)])
        Ks = kern(cand, X)
        mu = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-9, None)
        best = cand[int(np.argmax(mu + self.kappa * np.sqrt(var)))]
        new = dict(config)
        for z, key in zip(best, self.bounds):
            lo, hi = self.bounds[key]
            val = lo + float(z) * (hi - lo)
            if isinstance(config.get(key), int):
                val = int(round(val))
            new[key] = val
        # Non-numeric mutations keep PBT resampling semantics.
        for key, mut in self.mutations.items():
            if key not in self.bounds:
                from .search import Domain
                if isinstance(mut, Domain):
                    new[key] = mut.sample(self.rng)
                elif isinstance(mut, list):
                    new[key] = self.rng.choice(mut)
                elif callable(mut):
                    new[key] = mut()
        return new

    def _explore_fallback(self, config: Dict[str, Any]) -> Dict[str, Any]:
        """Pre-GP (or singular-kernel) exploration.  PBT's mutation semantics
        don't understand continuous bounds — a (lo, hi) tuple matches none of
        its resample cases and its ×0.8/1.2 drift is unclamped — so bounded
        keys resample uniformly inside the bounds and perturbations clamp."""
        new = super()._explore(config)
        for key, (lo, hi) in self.bounds.items():
            v = config.get(key)
            if not isinstance(v, (int, float)):
                continue
            if self.rng.random() < self.resample_p:
                nv = self.rng.uniform(lo, hi)
            else:
                nv = v * self.rng.choice([0.8, 1.2])
            nv = min(max(nv, lo), hi)
            new[key] = int(round(nv)) if isinstance(v, int) else nv
        return new


class ResourceChangingScheduler(TrialScheduler):
    """Reallocate a trial's resources mid-run (reference:
    ``tune/schedulers/resource_changing_scheduler.py``).

    Wraps a base scheduler; after each result the
    ``resources_allocation_function(controller_state, trial, result)`` may
    return a new resources dict — the controller then checkpoints-restarts
    the trial actor with the new allocation, and the trainable reads it via
    ``tune.get_trial_resources()``.  The base scheduler's decision applies
    when no reallocation happens (a RESIZE supersedes CONTINUE but not
    STOP)."""

    def __init__(self, base_scheduler: Optional[TrialScheduler] = None,
                 resources_allocation_function=None):
        self.base = base_scheduler or FIFOScheduler()
        # NOTE: metric/mode are delegating properties over self.base, so it
        # must exist before the base-class __init__ assigns them.
        super().__init__(self.base.metric, self.base.mode)
        self.alloc_fn = resources_allocation_function

    @property
    def metric(self):  # delegate scoring config to the base scheduler
        return self.base.metric

    @metric.setter
    def metric(self, v):
        self.base.metric = v

    @property
    def mode(self):
        return self.base.mode

    @mode.setter
    def mode(self, v):
        self.base.mode = v

    def on_result(self, trial, result):
        decision = self.base.on_result(trial, result)
        if decision != CONTINUE or self.alloc_fn is None:
            # STOP and PERTURB take precedence: a PBT exploit must not be
            # silently swallowed by a same-result resize (the base already
            # updated its perturb bookkeeping).  The resize retries on the
            # next report.
            return decision
        new = self.alloc_fn(None, trial, result)
        if new and dict(new) != dict(trial.resources or {}):
            return (RESIZE, dict(new))
        return decision
