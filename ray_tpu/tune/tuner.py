"""Tuner — the public entrypoint.  Reference: ``python/ray/tune/tuner.py:59``
(``Tuner``, ``fit`` :337), ``tune/tune_config.py`` (``TuneConfig``),
``result_grid.py`` (``ResultGrid``)."""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ..train.checkpoint import Checkpoint
from ..train.config import RunConfig
from ..train.result import Result
from .controller import TuneController
from .search import BasicVariantGenerator, Searcher
from .schedulers import TrialScheduler
from .trial import ERROR, Trial


@dataclasses.dataclass
class TuneConfig:
    metric: Optional[str] = None
    mode: str = "max"
    num_samples: int = 1
    search_alg: Optional[Searcher] = None
    scheduler: Optional[TrialScheduler] = None
    max_concurrent_trials: Optional[int] = None
    seed: Optional[int] = None


class ResultGrid:
    def __init__(self, trials: List[Trial], metric: Optional[str],
                 mode: str):
        self.trials = trials
        self._metric, self._mode = metric, mode
        self.results = [
            Result(metrics=t.last_result,
                   checkpoint=Checkpoint(t.latest_checkpoint)
                   if t.latest_checkpoint else None,
                   path=os.path.join(t.experiment_dir, t.trial_id),
                   error=RuntimeError(t.error) if t.error else None,
                   metrics_history=t.metrics_history,
                   config=t.config)
            for t in trials
        ]

    def __len__(self):
        return len(self.results)

    def __getitem__(self, i):
        return self.results[i]

    @property
    def errors(self):
        return [r.error for r in self.results if r.error is not None]

    def get_best_result(self, metric: Optional[str] = None,
                        mode: Optional[str] = None) -> Result:
        metric = metric or self._metric
        mode = mode or self._mode
        if metric is None:
            raise ValueError("metric required (set TuneConfig.metric)")
        scored = [r for r in self.results
                  if r.metrics and metric in r.metrics]
        if not scored:
            raise RuntimeError("no trial reported metric " + metric)
        return (max if mode == "max" else min)(
            scored, key=lambda r: r.metrics[metric])

    def get_dataframe(self):
        import pandas as pd
        rows = []
        for t in self.trials:
            row = dict(t.last_result or {})
            row["trial_id"] = t.trial_id
            row.update({f"config/{k}": v for k, v in t.config.items()
                        if isinstance(v, (int, float, str, bool))})
            rows.append(row)
        return pd.DataFrame(rows)


class Tuner:
    def __init__(self, trainable: Any,
                 *,
                 param_space: Optional[Dict[str, Any]] = None,
                 tune_config: Optional[TuneConfig] = None,
                 run_config: Optional[RunConfig] = None,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 worker_env: Optional[Dict[str, str]] = None):
        self.param_space = param_space or {}
        self.tune_config = tune_config or TuneConfig()
        self.run_config = run_config or RunConfig()
        self.resources_per_trial = resources_per_trial
        self.worker_env = worker_env
        # A Trainer instance is converted to its function trainable
        # (reference: BaseTrainer.fit routes through Tuner the other way).
        from ..train.trainer import BaseTrainer
        if isinstance(trainable, BaseTrainer):
            trainable = trainable.as_trainable()
        self.trainable = trainable
        if self.resources_per_trial is None:
            # tune.with_resources annotation on the trainable
            self.resources_per_trial = getattr(
                trainable, "_raytpu_resources", None)
        self._restored: Optional[tuple] = None  # (experiment_dir, trials, searcher)

    @classmethod
    def restore(cls, experiment_dir: str, trainable: Any,
                *, tune_config: Optional[TuneConfig] = None,
                run_config: Optional[RunConfig] = None,
                resources_per_trial: Optional[Dict[str, float]] = None,
                worker_env: Optional[Dict[str, str]] = None) -> "Tuner":
        """Resume an interrupted experiment from its state snapshot
        (reference: ``Tuner.restore`` / ``execution/experiment_state.py``).
        Terminated trials keep their results; interrupted ones restart from
        their latest checkpoint; the searcher resumes where it stopped."""
        trials, searcher, max_trials = TuneController.load_state(
            experiment_dir)
        tuner = cls(trainable, tune_config=tune_config,
                    run_config=run_config,
                    resources_per_trial=resources_per_trial,
                    worker_env=worker_env)
        if tuner.tune_config.search_alg is None:
            tuner.tune_config.search_alg = searcher
        tuner._restored = (experiment_dir, trials, max_trials)
        return tuner

    def fit(self) -> ResultGrid:
        cfg = self.tune_config
        restored_max_trials = None
        if self._restored is not None:
            experiment_dir, initial_trials, restored_max_trials = \
                self._restored
        else:
            initial_trials = None
            name = self.run_config.name or \
                f"tune_{getattr(self.trainable, '__name__', 'exp')}_{int(time.time())}"
            experiment_dir = os.path.join(
                self.run_config.resolved_storage_path(), name)
            os.makedirs(experiment_dir, exist_ok=True)
        searcher = cfg.search_alg or BasicVariantGenerator(
            self.param_space, num_samples=cfg.num_samples, seed=cfg.seed)
        if searcher.metric is None:
            searcher.metric, searcher.mode = cfg.metric, cfg.mode
        failure_cfg = self.run_config.failure_config
        controller = TuneController(
            self.trainable, searcher, cfg.scheduler, experiment_dir,
            metric=cfg.metric, mode=cfg.mode,
            max_concurrent=cfg.max_concurrent_trials,
            max_failures_per_trial=(failure_cfg.max_failures
                                    if failure_cfg else 0),
            resources_per_trial=self.resources_per_trial,
            worker_env=self.worker_env,
            initial_trials=initial_trials,
            max_trials=self._resolve_max_trials(searcher,
                                                restored_max_trials))
        trials = controller.run()
        return ResultGrid(trials, cfg.metric, cfg.mode)

    def _resolve_max_trials(self, searcher,
                            restored_max_trials: Optional[int]) -> Optional[int]:
        """Open-ended searchers (TPE etc.) always have a suggestion, so
        num_samples is their total trial budget; BasicVariantGenerator
        self-exhausts and must NOT be capped (its num_samples means
        grid-repeat count).  A restored run keeps its original budget unless
        the caller overrides num_samples explicitly."""
        if isinstance(searcher, BasicVariantGenerator):
            return None
        cfg = self.tune_config
        if restored_max_trials is not None and cfg.num_samples == 1:
            return restored_max_trials
        return cfg.num_samples
