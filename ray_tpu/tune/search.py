"""Search spaces + search algorithms.

Reference: ``python/ray/tune/search/sample.py`` (Domain/Float/Integer/
Categorical samplers), ``search/basic_variant.py`` (grid × random variant
generation), ``search/search_algorithm.py`` (Searcher interface).  External
optimizer wrappers (hyperopt/optuna/ax/...) are out of scope on this image —
the Searcher ABC is the plug point they'd use.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, List, Optional, Tuple


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)

    def quantized(self, q: float) -> "Quantized":
        return Quantized(self, q)


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            return int(round(math.exp(rng.uniform(math.log(self.lower),
                                                  math.log(self.upper - 1)))))
        return rng.randint(self.lower, self.upper - 1)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Quantized(Domain):
    def __init__(self, inner: Domain, q: float):
        self.inner, self.q = inner, q

    def sample(self, rng):
        v = self.inner.sample(rng)
        return round(v / self.q) * self.q


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


class GridSearch:
    """Marker for exhaustive expansion (cross product with other grids)."""

    def __init__(self, values: List[Any]):
        self.values = list(values)


# -- public constructors (reference tune.uniform/loguniform/choice/...) -----

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper), q)


def qloguniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper, log=True), q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda: random.gauss(mean, sd))


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


# ---------------------------------------------------------------- searchers

class Searcher:
    """Suggest configs; receive results (reference Searcher interface)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


def _split_grid(space: Dict[str, Any]):
    """Separate grid axes from sampleable/constant leaves (nested dicts ok)."""
    grids: List[Tuple[Tuple[str, ...], GridSearch]] = []

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))
        elif isinstance(node, GridSearch):
            grids.append((path, node))

    walk(space, ())
    return grids


def _instantiate(space, rng: random.Random, grid_assignment):
    def build(node, path):
        if isinstance(node, dict):
            return {k: build(v, path + (k,)) for k, v in node.items()}
        if isinstance(node, GridSearch):
            return grid_assignment[path]
        if isinstance(node, Domain):
            return node.sample(rng)
        return node

    return build(space, ())


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random samples — reference
    ``basic_variant.py`` semantics: num_samples repeats the whole grid."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict[str, Any]]] = None):
        super().__init__()
        self.space = space
        self.rng = random.Random(seed)
        self._preset = list(points_to_evaluate or [])
        grids = _split_grid(space)
        paths = [p for p, _ in grids]
        combos = list(itertools.product(*[g.values for _, g in grids])) or [()]
        # a plain list (not an iterator) so experiment snapshots can pickle
        # the searcher mid-stream (tune resume)
        self._variants: List[Dict] = [
            dict(zip(paths, combo))
            for _ in range(num_samples) for combo in combos
        ]
        self.total = num_samples * len(combos) + len(self._preset)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._preset:
            return self._preset.pop(0)
        if not self._variants:
            return None
        return _instantiate(self.space, self.rng, self._variants.pop(0))


# ------------------------------------------------------------------- TPE

def _flatten_domains(space: Dict[str, Any]):
    """(path -> Domain) for every sampleable leaf (grid axes excluded)."""
    out: Dict[Tuple[str, ...], Domain] = {}

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))
        elif isinstance(node, Domain):
            out[path] = node

    walk(space, ())
    return out


def _build_config(space, values: Dict[Tuple[str, ...], Any],
                  rng: random.Random):
    def build(node, path):
        if isinstance(node, dict):
            return {k: build(v, path + (k,)) for k, v in node.items()}
        if isinstance(node, Domain):
            return values.get(path, node.sample(rng))
        if isinstance(node, GridSearch):
            raise ValueError("grid_search is not supported by TPESearcher; "
                             "use choice() or BasicVariantGenerator")
        return node

    return build(space, ())


class TPESearcher(Searcher):
    """Tree-structured Parzen Estimator (model-based search).

    Reference parity target: ``python/ray/tune/search/hyperopt`` wraps
    hyperopt's TPE; this is a self-contained implementation of the same
    algorithm (Bergstra et al., NeurIPS 2011) because external optimizer
    packages are not in this image.

    Per dimension: past observations are split into the best ``gamma``
    fraction (l) and the rest (g); candidates are drawn from a Parzen mixture
    over l (plus a uniform prior component) and ranked by the density ratio
    l(x)/g(x).  Numeric domains work in transformed space (log where the
    domain is log-scaled); categoricals use smoothed count ratios.
    """

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", *, n_startup: int = 10,
                 gamma: float = 0.25, n_candidates: int = 24,
                 seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space = space
        self.domains = _flatten_domains(space)
        self.n_startup = n_startup
        self.gamma = gamma
        self.n_candidates = n_candidates
        self.rng = random.Random(seed)
        self._live: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        self._latest: Dict[str, Dict[str, Any]] = {}
        self._obs: List[Tuple[Dict[Tuple[str, ...], Any], float]] = []

    # -- domain helpers ---------------------------------------------------

    @staticmethod
    def _numeric(dom: Domain):
        if isinstance(dom, Quantized):
            inner = dom.inner
            if isinstance(inner, (Float, Integer)):
                return inner
            return None
        if isinstance(dom, (Float, Integer)):
            return dom
        return None

    def _to_z(self, dom, x: float) -> float:
        return math.log(x) if dom.log else float(x)

    def _from_z(self, dom, z: float, outer: Domain):
        lo, hi = self._z_bounds(dom)
        z = min(max(z, lo), hi)
        v = math.exp(z) if dom.log else z
        # exp(log(hi)) can exceed hi by an ulp — clamp in value space too
        v = min(max(v, dom.lower), dom.upper)
        if isinstance(dom, Integer):
            v = int(round(v))
            v = min(max(v, dom.lower), dom.upper - 1)
        if isinstance(outer, Quantized):
            v = round(v / outer.q) * outer.q
        return v

    def _z_bounds(self, dom) -> Tuple[float, float]:
        if dom.log:
            return math.log(dom.lower), math.log(dom.upper)
        return float(dom.lower), float(dom.upper)

    # -- the estimator ----------------------------------------------------

    def _suggest_dim(self, path, dom, good, bad):
        num = self._numeric(dom)
        if num is not None:
            lo, hi = self._z_bounds(num)
            span = max(hi - lo, 1e-12)
            gz = [self._to_z(num, c[path]) for c in good if path in c]
            bz = [self._to_z(num, c[path]) for c in bad if path in c]
            if not gz:
                return dom.sample(self.rng)

            def bandwidth(pts):
                # Scott's rule on the sample std (NOT the domain span — a
                # span-scaled bandwidth exceeds the domain for small n and
                # piles clamped candidates onto the boundaries)
                n = len(pts)
                if n < 2:
                    return span * 0.25
                mean = sum(pts) / n
                std = (sum((p - mean) ** 2 for p in pts) / (n - 1)) ** 0.5
                return min(max(std * 1.06 * n ** -0.2, span * 0.01), span)

            bw_g = bandwidth(gz)
            bw_b = bandwidth(bz) if bz else span

            def density(z, pts, bw):
                # Parzen mixture + uniform prior mass (keeps exploration alive)
                p = 1.0 / span
                for m in pts:
                    p += math.exp(-0.5 * ((z - m) / bw) ** 2) / (
                        bw * 2.5066282746310002)
                return p / (len(pts) + 1)

            best_z, best_score = None, -1.0
            for _ in range(self.n_candidates):
                # draw from the actual mixture l: uniform prior component
                # with weight 1/(n+1), else a Parzen kernel — keeps
                # exploration alive after the good set concentrates
                if self.rng.random() < 1.0 / (len(gz) + 1):
                    z = self.rng.uniform(lo, hi)
                else:
                    z = self.rng.gauss(self.rng.choice(gz), bw_g)
                    z = min(max(z, lo), hi)
                score = density(z, gz, bw_g) / density(z, bz, bw_b)
                if score > best_score:
                    best_z, best_score = z, score
            return self._from_z(num, best_z, dom)
        if isinstance(dom, Categorical):
            cats = dom.categories
            gcounts = {i: 1.0 for i in range(len(cats))}
            bcounts = {i: 1.0 for i in range(len(cats))}
            for c in good:
                if path in c and c[path] in cats:
                    gcounts[cats.index(c[path])] += 1
            for c in bad:
                if path in c and c[path] in cats:
                    bcounts[cats.index(c[path])] += 1
            gsum = sum(gcounts.values())
            weights = [gcounts[i] / gsum for i in range(len(cats))]
            # draw candidates from l, rank by l/g
            best_i, best_score = None, -1.0
            for _ in range(self.n_candidates):
                i = self.rng.choices(range(len(cats)), weights)[0]
                score = gcounts[i] / bcounts[i]
                if score > best_score:
                    best_i, best_score = i, score
            return cats[best_i]
        return dom.sample(self.rng)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if len(self._obs) < self.n_startup:
            flat = {p: d.sample(self.rng) for p, d in self.domains.items()}
        else:
            obs = sorted(self._obs, key=lambda o: o[1], reverse=True)
            n_good = max(1, int(math.ceil(self.gamma * len(obs))))
            good = [c for c, _ in obs[:n_good]]
            bad = [c for c, _ in obs[n_good:]] or good
            flat = {p: self._suggest_dim(p, d, good, bad)
                    for p, d in self.domains.items()}
        self._live[trial_id] = flat
        return _build_config(self.space, flat, self.rng)

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        self._latest[trial_id] = result

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        flat = self._live.pop(trial_id, None)
        latest = self._latest.pop(trial_id, None)  # always pop: no leak
        result = result or latest
        if flat is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._obs.append((flat, score))


class BOHBSearcher(TPESearcher):
    """Budget-aware model-based search (BOHB; Falkner et al., ICML 2018).

    Reference parity target: ``python/ray/tune/search/bohb`` (TuneBOHB,
    paired with HyperBandForBOHB).  Pair this with
    :class:`~ray_tpu.tune.schedulers.HyperBandScheduler`: the scheduler
    prunes at rungs while the searcher fits its TPE model ONLY on
    observations from the highest budget (``time_attr`` value) that has
    accumulated ``n_startup`` results — so cheap low-budget evaluations
    guide early sampling but stop polluting the model once real evidence
    at larger budgets exists.
    """

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", *, time_attr: str = "training_iteration",
                 n_startup: int = 8, gamma: float = 0.25,
                 n_candidates: int = 24, seed: Optional[int] = None):
        super().__init__(space, metric, mode, n_startup=n_startup,
                         gamma=gamma, n_candidates=n_candidates, seed=seed)
        self.time_attr = time_attr
        # budget -> [(flat_config, score)]
        self._budget_obs: Dict[int, List[Tuple[Dict, float]]] = {}

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        super().on_trial_result(trial_id, result)
        flat = self._live.get(trial_id)
        score = result.get(self.metric)
        budget = result.get(self.time_attr)
        if flat is None or score is None or budget is None:
            return
        score = float(score)
        if self.mode == "min":
            score = -score
        self._budget_obs.setdefault(int(budget), []).append((dict(flat),
                                                             score))
        # keep the base class's flat `_obs` tracking the model budget
        self._obs = self._model_observations()

    def _model_observations(self):
        for budget in sorted(self._budget_obs, reverse=True):
            obs = self._budget_obs[budget]
            if len(obs) >= self.n_startup:
                return obs
        # no budget has enough data: pool everything (startup phase)
        return [o for obs in self._budget_obs.values() for o in obs]

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        # per-budget results were already folded in on_trial_result; just
        # release the live slot (do NOT double-append to _obs)
        self._live.pop(trial_id, None)
        self._latest.pop(trial_id, None)


class BayesOptSearcher(Searcher):
    """Gaussian-process Bayesian optimization with Expected Improvement.

    Reference parity target: ``python/ray/tune/search/bayesopt``
    (BayesOptSearch wraps the ``bayes_opt`` package); self-contained here
    because external optimizer packages are not in this image.

    Numeric dimensions map to the unit cube (log-scaled where the domain
    is); categoricals map to their normalized index.  The surrogate is a GP
    with an RBF kernel fit by Cholesky; the acquisition (EI) is maximized
    over random candidates plus jittered copies of the incumbent.
    """

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", *, n_startup: int = 8,
                 n_candidates: int = 256, length_scale: float = 0.25,
                 noise: float = 1e-6, seed: Optional[int] = None):
        super().__init__(metric, mode)
        self.space = space
        self.domains = _flatten_domains(space)
        self.n_startup = n_startup
        self.n_candidates = n_candidates
        self.length_scale = length_scale
        self.noise = noise
        self.rng = random.Random(seed)
        self._live: Dict[str, Dict[Tuple[str, ...], Any]] = {}
        self._X: List[List[float]] = []   # unit-cube coordinates
        self._y: List[float] = []
        self._flats: List[Dict[Tuple[str, ...], Any]] = []

    # -- encoding ---------------------------------------------------------

    def _encode_dim(self, dom: Domain, v) -> float:
        base = dom.inner if isinstance(dom, Quantized) else dom
        if isinstance(base, (Float, Integer)):
            lo, hi = base.lower, base.upper
            if base.log:
                import math as m
                return ((m.log(float(v)) - m.log(lo))
                        / max(m.log(hi) - m.log(lo), 1e-12))
            return (float(v) - lo) / max(hi - lo, 1e-12)
        if isinstance(base, Categorical):
            return base.categories.index(v) / max(len(base.categories), 1)
        return 0.5

    def _decode_dim(self, dom: Domain, z: float):
        import math as m
        z = min(max(z, 0.0), 1.0)
        base = dom.inner if isinstance(dom, Quantized) else dom
        if isinstance(base, (Float, Integer)):
            lo, hi = base.lower, base.upper
            v = (m.exp(m.log(lo) + z * (m.log(hi) - m.log(lo)))
                 if base.log else lo + z * (hi - lo))
            if isinstance(dom, Quantized):
                v = round(v / dom.q) * dom.q
            if isinstance(base, Integer):
                v = int(min(max(round(v), base.lower), base.upper - 1))
            else:
                v = min(max(v, lo), hi)
            return v
        if isinstance(base, Categorical):
            idx = int(z * len(base.categories))
            return base.categories[min(idx, len(base.categories) - 1)]
        return base.sample(self.rng)

    # -- GP surrogate ------------------------------------------------------

    def _posterior(self, Xc):
        import numpy as np
        X = np.asarray(self._X)
        y = np.asarray(self._y, dtype=float)
        mu0, sd = y.mean(), max(y.std(), 1e-9)
        yn = (y - mu0) / sd
        ls = self.length_scale

        def k(A, B):
            d2 = ((A[:, None, :] - B[None, :, :]) ** 2).sum(-1)
            return np.exp(-0.5 * d2 / ls ** 2)

        K = k(X, X) + (self.noise + 1e-8) * np.eye(len(X))
        L = np.linalg.cholesky(K)
        alpha = np.linalg.solve(L.T, np.linalg.solve(L, yn))
        Ks = k(np.asarray(Xc), X)
        mean = Ks @ alpha
        v = np.linalg.solve(L, Ks.T)
        var = np.clip(1.0 - (v ** 2).sum(0), 1e-12, None)
        return mean * sd + mu0, np.sqrt(var) * sd

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        params = list(self.domains)
        if len(self._y) < self.n_startup:
            flat = {p: d.sample(self.rng) for p, d in self.domains.items()}
        else:
            import numpy as np
            rng = np.random.default_rng(self.rng.randrange(1 << 30))
            cand = rng.uniform(0, 1, (self.n_candidates, len(params)))
            best_x = np.asarray(self._X[int(np.argmax(self._y))])
            jitter = np.clip(best_x[None]
                             + rng.normal(0, 0.08, (32, len(params))), 0, 1)
            Xc = np.concatenate([cand, jitter])
            mean, std = self._posterior(Xc)
            best = max(self._y)
            z = (mean - best) / std
            from math import erf, exp, pi, sqrt
            pdf = np.exp(-0.5 * z ** 2) / sqrt(2 * pi)
            cdf = 0.5 * (1 + np.vectorize(erf)(z / sqrt(2)))
            ei = (mean - best) * cdf + std * pdf
            x = Xc[int(np.argmax(ei))]
            flat = {p: self._decode_dim(self.domains[p], x[i])
                    for i, p in enumerate(params)}
        self._live[trial_id] = flat
        return _build_config(self.space, flat, self.rng)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        flat = self._live.pop(trial_id, None)
        if flat is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._X.append([self._encode_dim(self.domains[p], flat[p])
                        for p in self.domains])
        self._y.append(score)
        self._flats.append(flat)


class CMAESSearcher(BayesOptSearcher):
    """CMA-ES over the unit cube (reference parity target:
    ``python/ray/tune/search``'s external CMA wrappers, e.g. nevergrad/
    optuna CmaEs samplers; self-contained here — no optimizer packages
    in the image).

    Shares BayesOptSearcher's domain encoding (numeric -> unit interval,
    log-aware, categoricals by index) but replaces the GP surrogate with
    the standard (mu/mu_w, lambda) covariance-matrix adaptation: rank-one
    + rank-mu covariance updates and CSA step-size control, batched into
    generations of ``popsize`` completed trials (asynchronous trials
    simply fill the generation as they finish)."""

    def __init__(self, space: Dict[str, Any], metric: Optional[str] = None,
                 mode: str = "max", *, popsize: Optional[int] = None,
                 sigma0: float = 0.3, seed: Optional[int] = None):
        import numpy as np

        super().__init__(space, metric, mode, seed=seed)
        d = max(len(self.domains), 1)
        self.popsize = popsize or (4 + int(3 * math.log(d)))
        if self.popsize < 2:
            raise ValueError(
                f"popsize must be >= 2 (got {self.popsize}): the "
                "recombination weights need at least one parent")
        mu = self.popsize // 2
        w = np.log(mu + 0.5) - np.log(np.arange(1, mu + 1))
        self._w = w / w.sum()
        self._mueff = 1.0 / (self._w ** 2).sum()
        self._cc = (4 + self._mueff / d) / (d + 4 + 2 * self._mueff / d)
        self._cs = (self._mueff + 2) / (d + self._mueff + 5)
        self._c1 = 2 / ((d + 1.3) ** 2 + self._mueff)
        self._cmu = min(1 - self._c1,
                        2 * (self._mueff - 2 + 1 / self._mueff)
                        / ((d + 2) ** 2 + self._mueff))
        self._damps = (1 + 2 * max(0.0, math.sqrt(
            (self._mueff - 1) / (d + 1)) - 1) + self._cs)
        self._chi = math.sqrt(d) * (1 - 1 / (4 * d) + 1 / (21 * d * d))
        self._mean = np.full(d, 0.5)
        self._sigma = sigma0
        self._C = np.eye(d)
        self._pc = np.zeros(d)
        self._ps = np.zeros(d)
        self._gen: List[Tuple[float, Any]] = []   # (score, x)
        self._pending_x: Dict[str, Any] = {}
        self._np_rng = np.random.default_rng(seed)
        self._eig = None  # cached (B, D) of C, invalidated per generation

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        import numpy as np

        d = len(self._mean)
        if self._eig is None:
            vals, B = np.linalg.eigh(self._C)
            self._eig = (B, np.sqrt(np.clip(vals, 1e-20, None)))
        B, D = self._eig
        z = self._np_rng.standard_normal(d)
        y = B @ (D * z)
        x = np.clip(self._mean + self._sigma * y, 0.0, 1.0)
        params = list(self.domains)
        flat = {p: self._decode_dim(self.domains[p], x[i])
                for i, p in enumerate(params)}
        self._live[trial_id] = flat
        self._pending_x[trial_id] = x
        return _build_config(self.space, flat, self.rng)

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        import numpy as np

        x = self._pending_x.pop(trial_id, None)
        super().on_trial_complete(trial_id, result=result, error=error)
        if x is None or error or not result or self.metric not in result:
            return
        score = float(result[self.metric])
        if self.mode == "min":
            score = -score
        self._gen.append((score, x))
        if len(self._gen) < self.popsize:
            return
        # ---- one CMA generation (maximization: best first) -------------
        self._gen.sort(key=lambda t: -t[0])
        mu = len(self._w)
        X = np.stack([g[1] for g in self._gen[:mu]])
        old_mean = self._mean
        self._gen = []
        d = len(old_mean)
        self._mean = self._w @ X
        y_w = (self._mean - old_mean) / max(self._sigma, 1e-12)
        if self._eig is None:
            vals_, B_ = np.linalg.eigh(self._C)
            self._eig = (B_, np.sqrt(np.clip(vals_, 1e-20, None)))
        B, D = self._eig
        C_inv_sqrt = B @ np.diag(1.0 / D) @ B.T
        self._ps = ((1 - self._cs) * self._ps
                    + math.sqrt(self._cs * (2 - self._cs) * self._mueff)
                    * (C_inv_sqrt @ y_w))
        hsig = (np.linalg.norm(self._ps)
                / math.sqrt(1 - (1 - self._cs) ** (2 * (len(self._y) + 1)))
                < (1.4 + 2 / (d + 1)) * self._chi)
        self._pc = ((1 - self._cc) * self._pc
                    + (math.sqrt(self._cc * (2 - self._cc) * self._mueff)
                       * y_w if hsig else 0.0))
        Y = (X - old_mean) / max(self._sigma, 1e-12)
        rank_mu = (self._w[:, None, None]
                   * (Y[:, :, None] @ Y[:, None, :])).sum(0)
        self._C = ((1 - self._c1 - self._cmu) * self._C
                   + self._c1 * (np.outer(self._pc, self._pc)
                                 + (0.0 if hsig else
                                    self._cc * (2 - self._cc)) * self._C)
                   + self._cmu * rank_mu)
        self._sigma *= math.exp(
            (self._cs / self._damps)
            * (np.linalg.norm(self._ps) / self._chi - 1))
        self._sigma = float(np.clip(self._sigma, 1e-8, 1.0))
        self._eig = None  # C changed: re-decompose lazily next suggest
