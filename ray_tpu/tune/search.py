"""Search spaces + search algorithms.

Reference: ``python/ray/tune/search/sample.py`` (Domain/Float/Integer/
Categorical samplers), ``search/basic_variant.py`` (grid × random variant
generation), ``search/search_algorithm.py`` (Searcher interface).  External
optimizer wrappers (hyperopt/optuna/ax/...) are out of scope on this image —
the Searcher ABC is the plug point they'd use.
"""

from __future__ import annotations

import itertools
import math
import random
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple


class Domain:
    """A sampleable hyperparameter domain."""

    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class Float(Domain):
    def __init__(self, lower: float, upper: float, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            return math.exp(rng.uniform(math.log(self.lower),
                                        math.log(self.upper)))
        return rng.uniform(self.lower, self.upper)

    def quantized(self, q: float) -> "Quantized":
        return Quantized(self, q)


class Integer(Domain):
    def __init__(self, lower: int, upper: int, log: bool = False):
        self.lower, self.upper, self.log = lower, upper, log

    def sample(self, rng):
        if self.log:
            return int(round(math.exp(rng.uniform(math.log(self.lower),
                                                  math.log(self.upper - 1)))))
        return rng.randint(self.lower, self.upper - 1)


class Categorical(Domain):
    def __init__(self, categories: List[Any]):
        self.categories = list(categories)

    def sample(self, rng):
        return rng.choice(self.categories)


class Quantized(Domain):
    def __init__(self, inner: Domain, q: float):
        self.inner, self.q = inner, q

    def sample(self, rng):
        v = self.inner.sample(rng)
        return round(v / self.q) * self.q


class Function(Domain):
    def __init__(self, fn: Callable[[], Any]):
        self.fn = fn

    def sample(self, rng):
        return self.fn()


class GridSearch:
    """Marker for exhaustive expansion (cross product with other grids)."""

    def __init__(self, values: List[Any]):
        self.values = list(values)


# -- public constructors (reference tune.uniform/loguniform/choice/...) -----

def uniform(lower: float, upper: float) -> Float:
    return Float(lower, upper)


def loguniform(lower: float, upper: float) -> Float:
    return Float(lower, upper, log=True)


def quniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper), q)


def qloguniform(lower: float, upper: float, q: float) -> Quantized:
    return Quantized(Float(lower, upper, log=True), q)


def randint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper)


def lograndint(lower: int, upper: int) -> Integer:
    return Integer(lower, upper, log=True)


def choice(categories: List[Any]) -> Categorical:
    return Categorical(categories)


def sample_from(fn: Callable[[], Any]) -> Function:
    return Function(fn)


def randn(mean: float = 0.0, sd: float = 1.0) -> Function:
    return Function(lambda: random.gauss(mean, sd))


def grid_search(values: List[Any]) -> GridSearch:
    return GridSearch(values)


# ---------------------------------------------------------------- searchers

class Searcher:
    """Suggest configs; receive results (reference Searcher interface)."""

    def __init__(self, metric: Optional[str] = None, mode: str = "max"):
        self.metric, self.mode = metric, mode

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def on_trial_result(self, trial_id: str, result: Dict[str, Any]) -> None:
        pass

    def on_trial_complete(self, trial_id: str,
                          result: Optional[Dict[str, Any]] = None,
                          error: bool = False) -> None:
        pass


def _split_grid(space: Dict[str, Any]):
    """Separate grid axes from sampleable/constant leaves (nested dicts ok)."""
    grids: List[Tuple[Tuple[str, ...], GridSearch]] = []

    def walk(node, path):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(v, path + (k,))
        elif isinstance(node, GridSearch):
            grids.append((path, node))

    walk(space, ())
    return grids


def _instantiate(space, rng: random.Random, grid_assignment):
    def build(node, path):
        if isinstance(node, dict):
            return {k: build(v, path + (k,)) for k, v in node.items()}
        if isinstance(node, GridSearch):
            return grid_assignment[path]
        if isinstance(node, Domain):
            return node.sample(rng)
        return node

    return build(space, ())


class BasicVariantGenerator(Searcher):
    """Grid cross-product × num_samples random samples — reference
    ``basic_variant.py`` semantics: num_samples repeats the whole grid."""

    def __init__(self, space: Dict[str, Any], num_samples: int = 1,
                 seed: Optional[int] = None,
                 points_to_evaluate: Optional[List[Dict[str, Any]]] = None):
        super().__init__()
        self.space = space
        self.rng = random.Random(seed)
        self._preset = list(points_to_evaluate or [])
        grids = _split_grid(space)
        paths = [p for p, _ in grids]
        combos = list(itertools.product(*[g.values for _, g in grids])) or [()]
        self._variants: Iterator = iter([
            dict(zip(paths, combo))
            for _ in range(num_samples) for combo in combos
        ])
        self.total = num_samples * len(combos) + len(self._preset)

    def suggest(self, trial_id: str) -> Optional[Dict[str, Any]]:
        if self._preset:
            return self._preset.pop(0)
        try:
            assignment = next(self._variants)
        except StopIteration:
            return None
        return _instantiate(self.space, self.rng, assignment)
