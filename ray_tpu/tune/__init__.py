"""ray_tpu.tune — hyperparameter search over the trial-as-actor substrate.

Reference surface: ``python/ray/tune`` (SURVEY.md §2.6): ``Tuner.fit`` →
controller event loop → trials as actors; search spaces; ASHA / median /
PBT schedulers; per-trial checkpoints; experiment state snapshots.
"""

from .helpers import with_parameters, with_resources
from .search import (BasicVariantGenerator, BayesOptSearcher, BOHBSearcher,
                     Categorical, CMAESSearcher, Domain, Float, GridSearch,
                     Integer, Searcher, TPESearcher, choice, grid_search,
                     lograndint, loguniform, qloguniform, quniform, randint,
                     randn, sample_from, uniform)
from .schedulers import (PB2, AsyncHyperBandScheduler, FIFOScheduler,
                         HyperBandScheduler, MedianStoppingRule,
                         PopulationBasedTraining, ResourceChangingScheduler,
                         TrialScheduler)
from .session import (get_checkpoint, get_session, get_trial_dir,
                      get_trial_id, get_trial_resources, report,
                      report_bridge)
from .trial import Trial
from .controller import TuneController
from .tuner import ResultGrid, TuneConfig, Tuner

ASHAScheduler = AsyncHyperBandScheduler

__all__ = [
    "Tuner", "TuneConfig", "ResultGrid", "TuneController", "Trial",
    "Searcher", "BasicVariantGenerator", "TPESearcher", "BayesOptSearcher",
    "uniform", "loguniform", "quniform",
    "qloguniform", "randint", "lograndint", "choice", "sample_from", "randn",
    "grid_search", "Domain", "Float", "Integer", "Categorical", "GridSearch",
    "TrialScheduler", "FIFOScheduler", "AsyncHyperBandScheduler",
    "ASHAScheduler", "HyperBandScheduler", "MedianStoppingRule",
    "PopulationBasedTraining", "PB2", "BOHBSearcher", "CMAESSearcher",
    "report", "get_checkpoint", "get_session", "get_trial_id",
    "get_trial_dir", "get_trial_resources", "report_bridge",
    "ResourceChangingScheduler",
]

# Usage telemetry: which libraries a cluster actually uses (reference:
# usage_lib.record_library_usage at import time).  Never raises.
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("tune")
del _rlu
