"""TuneController — the experiment event loop.

Reference: ``python/ray/tune/execution/tune_controller.py:81``: manage N
trials as actors, pump results, apply searcher + scheduler decisions, retry
failed trials, snapshot experiment state.  Differences are deliberate: trial
results multiplex over ``ray_tpu.wait`` on the runner actors' ``next_result``
calls instead of a callback event manager, and PBT checkpoint transplants are
a directory copy + actor restart (checkpoints are directories, train/checkpoint.py).
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu import ActorDiedError, RayTpuError, TaskError

from . import schedulers as sched_mod
from .schedulers import (CONTINUE, PERTURB, RESIZE, STOP, FIFOScheduler,
                         TrialScheduler)
from .search import BasicVariantGenerator, Searcher
from .trial import (ERROR, PENDING, RUNNING, TERMINATED, Trial, TrialRunner)


class TuneController:
    def __init__(self, trainable: Callable,
                 searcher: Searcher,
                 scheduler: Optional[TrialScheduler],
                 experiment_dir: str,
                 *,
                 metric: Optional[str] = None,
                 mode: str = "max",
                 max_concurrent: Optional[int] = None,
                 max_failures_per_trial: int = 0,
                 resources_per_trial: Optional[Dict[str, float]] = None,
                 worker_env: Optional[Dict[str, str]] = None,
                 result_poll_timeout: float = 3600.0,
                 initial_trials: Optional[List[Trial]] = None,
                 max_trials: Optional[int] = None):
        self.trainable = trainable
        self.searcher = searcher
        self.scheduler = scheduler or FIFOScheduler(metric, mode)
        if self.scheduler.metric is None:
            self.scheduler.metric = metric
            self.scheduler.mode = mode
        self.experiment_dir = experiment_dir
        self.metric, self.mode = metric, mode
        self.max_concurrent = max_concurrent or 8
        self.max_failures = max_failures_per_trial
        self.resources = resources_per_trial or {"CPU": 1}
        self.worker_env = worker_env
        self.poll_timeout = result_poll_timeout
        self.trials: List[Trial] = list(initial_trials or [])
        # Cap for open-ended searchers (TPE etc. always have a suggestion —
        # num_samples is the budget; BasicVariant self-exhausts instead).
        self.max_trials = max_trials
        self._exhausted = False
        # Per-trial result loggers (progress.csv / result.json / tfevents —
        # reference: python/ray/tune/logger/).
        self._loggers: Dict[str, Any] = {}

    # ------------------------------------------------------------- lifecycle

    def _next_trial(self) -> Optional[Trial]:
        if self._exhausted:
            return None
        if self.max_trials is not None and len(self.trials) >= self.max_trials:
            self._exhausted = True
            return None
        t = Trial.new({}, self.experiment_dir)
        config = self.searcher.suggest(t.trial_id)
        if config is None:
            self._exhausted = True
            return None
        t.config = config
        self.trials.append(t)
        return t

    def _start_trial(self, trial: Trial,
                     checkpoint_path: Optional[str] = None) -> None:
        cls = ray_tpu.remote(TrialRunner)
        res = trial.resources or self.resources
        opts: Dict[str, Any] = {"num_cpus": res.get("CPU", 1)}
        if res.get("TPU"):
            opts["num_tpus"] = res["TPU"]
        extra = {k: v for k, v in res.items()
                 if k not in ("CPU", "TPU", "GPU")}
        if extra:
            opts["resources"] = extra
        trial._pending_ref = None
        trial.runner = cls.options(**opts).remote(self.worker_env)
        # Fire-and-forget: do NOT block on actor readiness here — when trials
        # oversubscribe the cluster the creation queues at the lease layer,
        # and blocking would deadlock the event loop (running trials wait for
        # the controller, the queued actor waits for them to finish).  Actor
        # method ordering guarantees run() precedes the next_result() poll.
        trial.runner.run.remote(
            self.trainable, trial.config, trial.trial_id, trial.trial_dir,
            checkpoint_path or trial.latest_checkpoint, resources=res)
        trial.status = RUNNING

    def _stop_trial(self, trial: Trial, status: str = TERMINATED) -> None:
        trial.status = status
        trial._pending_ref = None
        if trial.runner is not None:
            try:
                ray_tpu.kill(trial.runner)
            except Exception:
                pass
            trial.runner = None

    # ---------------------------------------------------------------- events

    def _log_result(self, trial: Trial, metrics: Dict[str, Any]) -> None:
        from .loggers import TrialLoggers
        lg = self._loggers.get(trial.trial_id)
        if lg is None:
            lg = self._loggers[trial.trial_id] = TrialLoggers(
                trial.trial_dir, trial.trial_id)
        try:
            lg.log(metrics)
        except OSError:
            pass  # a full disk must not kill the experiment loop

    def _on_report(self, trial: Trial, metrics: Dict[str, Any],
                   ckpt_path: Optional[str]) -> None:
        trial.last_result = metrics
        trial.metrics_history.append(metrics)
        trial.iteration = metrics.get("training_iteration", trial.iteration + 1)
        self._log_result(trial, metrics)
        if ckpt_path:
            trial.ckpt_seq += 1
            dest = os.path.join(trial.trial_dir,
                                f"checkpoint_{trial.ckpt_seq:06d}")
            if os.path.abspath(ckpt_path) != os.path.abspath(dest):
                if os.path.exists(dest):  # stale leftovers must not mix in
                    shutil.rmtree(dest)
                shutil.copytree(ckpt_path, dest)
            trial.latest_checkpoint = dest
        self.searcher.on_trial_result(trial.trial_id, metrics)
        decision = self.scheduler.on_result(trial, metrics)
        if decision == CONTINUE:
            trial.runner.resume.remote()
        elif decision == STOP:
            self._stop_trial(trial)
            self.searcher.on_trial_complete(trial.trial_id, metrics)
        elif isinstance(decision, tuple) and decision[0] == RESIZE:
            # ResourceChangingScheduler: restart the trial actor with the
            # new allocation, resuming from its latest checkpoint.  Before
            # the first checkpoint a restart would lose all progress (same
            # hazard the PERTURB no-donor path guards), so defer the resize
            # to a later report.
            _, new_resources = decision
            if trial.latest_checkpoint is None:
                trial.runner.resume.remote()
            else:
                self._stop_trial(trial, status=PENDING)
                trial.resources = new_resources
                trial.restarts += 1
                self._start_trial(trial)
        elif isinstance(decision, tuple) and decision[0] == PERTURB:
            _, new_config, donor_id = decision
            donor = next((t for t in self.trials
                          if t.trial_id == donor_id), None)
            ckpt = donor.latest_checkpoint if donor else None
            if ckpt is None:
                # Exploit requires a donor checkpoint (reference pbt.py
                # skips with a warning): restarting from scratch would lose
                # all progress and can loop forever on a resetting
                # time_attr.
                trial.runner.resume.remote()
            else:
                self._stop_trial(trial, status=PENDING)
                trial.config = new_config
                trial.restarts += 1
                self._start_trial(trial, checkpoint_path=ckpt)

    def _on_failure(self, trial: Trial, err: BaseException) -> None:
        self._stop_trial(trial, status=ERROR)
        trial.error = repr(err)
        if trial.restarts < self.max_failures:
            trial.restarts += 1
            trial.status = PENDING
            self._start_trial(trial)
        else:
            self.searcher.on_trial_complete(trial.trial_id, error=True)

    # ------------------------------------------------------------------ loop

    def run(self) -> List[Trial]:
        # One outstanding next_result ref per running trial; ray_tpu.wait
        # multiplexes — a slow trial never blocks processing of fast ones.
        pending: Dict[Any, Trial] = {}
        while True:
            running = [t for t in self.trials if t.status == RUNNING]
            # restored/restartable trials first (resume from checkpoint),
            # then fresh suggestions from the searcher
            waiting = [t for t in self.trials if t.status == PENDING
                       and t.runner is None]
            while waiting and len(running) < self.max_concurrent:
                t = waiting.pop(0)
                self._start_trial(t)
                running.append(t)
            while len(running) < self.max_concurrent:
                t = self._next_trial()
                if t is None:
                    break
                self._start_trial(t)
                running.append(t)
            for t in running:
                if t.runner is not None and t._pending_ref is None:
                    ref = t.runner.next_result.remote(self.poll_timeout)
                    t._pending_ref = ref
                    pending[ref] = t
            # Drop refs whose trial was stopped/restarted meanwhile.
            for ref in [r for r, t in pending.items()
                        if t._pending_ref is not r]:
                pending.pop(ref)
            if not pending:
                break
            ready, _ = ray_tpu.wait(list(pending), num_returns=1,
                                    timeout=self.poll_timeout)
            for ref in ready:
                trial = pending.pop(ref)
                if trial._pending_ref is ref:
                    trial._pending_ref = None
                else:
                    continue  # stale (trial restarted)
                try:
                    kind, payload, ckpt = ray_tpu.get(ref)
                except RayTpuError as e:
                    # TaskError, ActorDiedError, and typed system faults
                    # (OutOfMemoryError, WorkerCrashedError, …) all mark the
                    # TRIAL failed — never crash the experiment loop.
                    self._on_failure(trial, e)
                    continue
                if kind == "done":
                    self._stop_trial(trial)
                    self.searcher.on_trial_complete(trial.trial_id,
                                                    trial.last_result)
                else:
                    self._on_report(trial, payload, ckpt)
            self._save_state()
        self._save_state()
        for lg in self._loggers.values():
            lg.close()
        self._loggers.clear()
        return self.trials

    # ------------------------------------------------------------- state io

    def _save_state(self) -> None:
        """Snapshot the experiment (reference:
        ``tune/execution/experiment_state.py``): a JSON summary for humans
        plus a pickled (trials, searcher) pair that ``Tuner.restore`` resumes
        from — terminated trials keep their results, interrupted ones restart
        from their latest checkpoint."""
        state = [{
            "trial_id": t.trial_id, "status": t.status, "config": repr(t.config),
            "last_result": {k: v for k, v in (t.last_result or {}).items()
                            if isinstance(v, (int, float, str, bool))},
            "iterations": t.iteration, "error": t.error,
            "checkpoint": t.latest_checkpoint,
        } for t in self.trials]
        try:
            with open(os.path.join(self.experiment_dir,
                                   "experiment_state.json"), "w") as f:
                json.dump({"timestamp": time.time(), "trials": state}, f,
                          indent=2)
            import cloudpickle
            import dataclasses as dc
            bare = [dc.replace(t, runner=None, _pending_ref=None)
                    for t in self.trials]
            blob = cloudpickle.dumps({"trials": bare,
                                      "searcher": self.searcher,
                                      "max_trials": self.max_trials})
            tmp = os.path.join(self.experiment_dir, ".experiment_state.tmp")
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, os.path.join(self.experiment_dir,
                                         "experiment_state.pkl"))
        except Exception:  # noqa: BLE001 — a snapshot failure (e.g. an
            # unpicklable user searcher attribute) must not abort the run
            pass

    @staticmethod
    def load_state(experiment_dir: str):
        """-> (trials, searcher, max_trials) from the last snapshot;
        interrupted trials come back PENDING so the run loop restarts them
        from checkpoints."""
        import cloudpickle
        with open(os.path.join(experiment_dir, "experiment_state.pkl"),
                  "rb") as f:
            state = cloudpickle.loads(f.read())
        for t in state["trials"]:
            if t.status == RUNNING:
                t.status = PENDING
        return state["trials"], state["searcher"], state.get("max_trials")
