"""Tune worker-side session: ``tune.report`` / ``tune.get_checkpoint``.

Reference: ``python/ray/tune/trainable/session.py`` — the function-trainable
API.  Also hosts the bridge that lets a Trainer.fit() running inside a tune
trial forward its per-report metrics upward (reference: Train's
``as_trainable`` wraps the trainer in a Tune Trainable).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Dict, Optional

from ..train.checkpoint import Checkpoint
from ..train.context import SessionFinished


class _TuneSession:
    def __init__(self, trial_id: str, trial_dir: str,
                 checkpoint: Optional[Checkpoint] = None,
                 resources: Optional[Dict[str, float]] = None):
        self.trial_id = trial_id
        self.trial_dir = trial_dir
        self.checkpoint = checkpoint
        self.trial_resources = dict(resources or {})
        self._q: "queue.Queue" = queue.Queue()
        self._evt = threading.Event()
        self._aborted = False
        self.iteration = 0

    def report(self, metrics: Dict[str, Any],
               checkpoint: Optional[Checkpoint] = None) -> None:
        if self._aborted:
            raise SessionFinished()
        self.iteration += 1
        metrics = dict(metrics)
        metrics.setdefault("training_iteration", self.iteration)
        self._evt.clear()
        self._q.put(("report", metrics, checkpoint.path if checkpoint else None))
        self._evt.wait()
        if self._aborted:
            raise SessionFinished()

    def _finish(self, value: Any) -> None:
        self._q.put(("done", value, None))

    def _fail(self, err: BaseException) -> None:
        self._q.put(("error", err, None))

    def _next(self, timeout: Optional[float] = None):
        return self._q.get(timeout=timeout)

    def _resume(self) -> None:
        self._evt.set()

    def _abort(self) -> None:
        self._aborted = True
        self._evt.set()


_session: Optional[_TuneSession] = None


def _set_session(s: Optional[_TuneSession]) -> None:
    global _session
    _session = s


def get_session() -> _TuneSession:
    if _session is None:
        raise RuntimeError("tune.report()/get_checkpoint() called outside a "
                           "tune trial")
    return _session


def report(metrics: Dict[str, Any],
           checkpoint: Optional[Checkpoint] = None) -> None:
    get_session().report(metrics, checkpoint=checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().checkpoint


def get_trial_id() -> str:
    return get_session().trial_id


def get_trial_dir() -> str:
    return get_session().trial_dir


def get_trial_resources() -> Dict[str, float]:
    """Resources currently allocated to this trial (reference:
    tune.get_trial_resources, used with ResourceChangingScheduler to adapt
    e.g. batch size to a mid-run reallocation)."""
    return dict(get_session().trial_resources)


def report_bridge(metrics: Dict[str, Any], checkpoint=None) -> None:
    """Forward a Train-side report into the enclosing tune trial, if any
    (used by Trainer.as_trainable)."""
    if _session is not None:
        ckpt = None
        if checkpoint is not None:
            ckpt = checkpoint if isinstance(checkpoint, Checkpoint) \
                else Checkpoint(str(checkpoint))
        _session.report(metrics, checkpoint=ckpt)
