"""ray_tpu.parallel — mesh construction, sharding, the pjit train step, and
GPipe pipeline parallelism."""

from .mesh import AXIS_ORDER, MeshSpec, make_mesh, named_sharding
from .pipeline import (init_pp_state, make_pp_train_step, merge_layers,
                       partition_layers)
from .train_step import (TrainState, init_sharded_state, make_eval_step,
                         make_optimizer, make_train_step, state_shardings)
from .zero import OptimizerSpec, init_zero_state, make_dp_train_step

__all__ = ["MeshSpec", "make_mesh", "named_sharding", "AXIS_ORDER",
           "TrainState", "make_optimizer", "init_sharded_state",
           "make_train_step", "make_eval_step", "state_shardings",
           "init_pp_state", "make_pp_train_step", "partition_layers",
           "merge_layers", "OptimizerSpec", "init_zero_state",
           "make_dp_train_step"]
