"""ray_tpu.parallel — mesh construction, sharding, and the pjit train step."""

from .mesh import AXIS_ORDER, MeshSpec, make_mesh, named_sharding
from .train_step import (TrainState, init_sharded_state, make_eval_step,
                         make_optimizer, make_train_step, state_shardings)

__all__ = ["MeshSpec", "make_mesh", "named_sharding", "AXIS_ORDER",
           "TrainState", "make_optimizer", "init_sharded_state",
           "make_train_step", "make_eval_step", "state_shardings"]
