"""ZeRO-sharded weight update + quantized gradient reduction: the dp-manual
train step.

The default train step (``train_step.make_train_step``) lets the XLA SPMD
partitioner place one fp32 all-reduce for the gradients and keeps the full
fp32 Adam state replicated on every data-parallel rank.  At >=1B params
that replication is what caps model size: Adam mu+nu alone is 8 bytes/param
per rank.  This module implements the two knobs that change it, per
"Automatic Cross-Replica Sharding of Weight Update" (ZeRO) and EQuARX
(PAPERS.md):

* ``zero_sharded_update`` — decompose the all-reduce into
  reduce-scatter -> local shard update -> all-gather(params): each rank
  owns 1/dp of the flattened parameter vector, keeps ONLY that shard's
  optimizer state (HBM ~ world_size x smaller), applies AdamW to the shard,
  and all-gathers the updated params.  AdamW is elementwise, so the shard
  update equals the replicated update restricted to the shard — the CPU
  exactness gate pins params allclose to the replicated path over 10 steps.
  The one cross-element op, global-norm clipping, is recovered exactly with
  a psum of per-shard square sums (same semantics as
  ``optax.clip_by_global_norm``).

* ``grad_quant_enabled`` — the reduce-scatter / all-gather payloads go
  int8 block-scaled over the wire (``quant_collectives``), ~4x fewer
  gradient bytes where DCN/ICI bandwidth bounds the dp step.

Both knobs build one full-manual shard_map over the whole step body: the
0.4.x CPU partitioner rejects partial-auto shard_map (see
``jax_compat.has_native_shard_map``), and full-manual is also what makes
the collective schedule explicit instead of compiler-chosen.  The step
requires every mesh axis except dp (and a size-1 fsdp) to be trivial —
these knobs target the data-parallel axis, compose with tp/pp elsewhere
is future work.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.flatten_util import ravel_pytree
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import transformer
from ..models.config import TransformerConfig
from ..models.transformer import ParallelContext
from ..util import jax_compat
from .quant_collectives import (DEFAULT_BLOCK, quantized_all_gather,
                                quantized_psum_scatter)
from .train_step import TrainState

__all__ = ["OptimizerSpec", "init_zero_state", "make_dp_train_step",
           "zero_opt_state_bytes"]


@dataclasses.dataclass(frozen=True)
class OptimizerSpec:
    """The hyperparameters behind ``train_step.make_optimizer``, reified.

    The ZeRO path applies the optimizer to a per-rank parameter shard, so
    it needs the raw hyperparameters (a built optax chain can't be split
    into its clip and AdamW stages after the fact).  ``build()`` returns
    exactly what ``make_optimizer`` with the same arguments returns.
    """
    learning_rate: float = 3e-4
    weight_decay: float = 0.1
    warmup_steps: int = 100
    total_steps: int = 10_000
    b1: float = 0.9
    b2: float = 0.95
    grad_clip: float = 1.0

    def schedule(self):
        return optax.warmup_cosine_decay_schedule(
            0.0, self.learning_rate, self.warmup_steps,
            max(self.total_steps, self.warmup_steps + 1))

    def adamw(self) -> optax.GradientTransformation:
        """The elementwise stage (everything but the global-norm clip)."""
        return optax.adamw(self.schedule(), b1=self.b1, b2=self.b2,
                           weight_decay=self.weight_decay)

    def build(self) -> optax.GradientTransformation:
        return optax.chain(optax.clip_by_global_norm(self.grad_clip),
                           self.adamw())


def _param_count(cfg: TransformerConfig, param_dtype) -> int:
    shapes = jax.eval_shape(
        lambda: transformer.init_params(jax.random.PRNGKey(0), cfg,
                                        dtype=param_dtype))
    return sum(int(np.prod(l.shape)) for l in jax.tree.leaves(shapes))


def _padded(n: int, dp: int, block: int) -> int:
    """Flat length padded so both the dp split and the quant blocks tile."""
    unit = dp * block
    return -(-n // unit) * unit


def _validate_mesh(mesh: Mesh) -> int:
    dp = mesh.shape.get("dp", 1)
    extra = {a: s for a, s in mesh.shape.items() if a != "dp" and s > 1}
    if extra:
        raise ValueError(
            "grad_quant/zero_sharded_update shard over the dp axis only; "
            f"mesh has non-trivial axes {extra}")
    return dp


def zero_opt_state_bytes(cfg: TransformerConfig, mesh: Mesh,
                         quant_block: int = DEFAULT_BLOCK,
                         param_dtype=jnp.float32) -> int:
    """Per-rank resident optimizer-state bytes under the ZeRO split
    (Adam mu+nu fp32 shards + counters)."""
    dp = mesh.shape.get("dp", 1)
    npad = _padded(_param_count(cfg, param_dtype), dp, quant_block)
    return 2 * (npad // dp) * 4 + 8


def init_zero_state(cfg: TransformerConfig, mesh: Mesh,
                    opt_spec: Optional[OptimizerSpec] = None, *,
                    quant_block: int = DEFAULT_BLOCK, seed: int = 0,
                    param_dtype=jnp.float32) -> Tuple[TrainState, TrainState]:
    """TrainState for the ZeRO step: params replicated, optimizer state a
    flat fp32 vector [npad] sharded P("dp") — each rank materializes only
    its own mu/nu shard (out_shardings on the jitted init).

    The flat vector is the ravel of the param tree (ravel_pytree order),
    zero-padded so dp * quant_block tiles it; mu = nu = 0 and count = 0
    match ``optimizer.init`` of the replicated path exactly.
    """
    opt_spec = opt_spec or OptimizerSpec()
    dp = _validate_mesh(mesh)
    npad = _padded(_param_count(cfg, param_dtype), dp, quant_block)
    inner = opt_spec.adamw()

    def init_fn():
        params = transformer.init_params(jax.random.PRNGKey(seed), cfg,
                                         dtype=param_dtype)
        opt_state = inner.init({"p": jnp.zeros((npad,), jnp.float32)})
        return TrainState(params=params, opt_state=opt_state,
                          step=jnp.zeros((), jnp.int32))

    shapes = jax.eval_shape(init_fn)
    shardings = TrainState(
        params=jax.tree.map(lambda _: NamedSharding(mesh, P()),
                            shapes.params),
        opt_state=jax.tree.map(
            lambda l: NamedSharding(mesh, P("dp") if l.ndim else P()),
            shapes.opt_state),
        step=NamedSharding(mesh, P()))
    state = jax.jit(init_fn, out_shardings=shardings)()
    return state, shardings


def collective_bytes_per_step(cfg: TransformerConfig, mesh: Mesh, *,
                              grad_quant: bool, zero_update: bool,
                              quant_block: int = DEFAULT_BLOCK,
                              param_dtype=jnp.float32) -> Dict[Tuple[str, str], int]:
    """Per-device wire bytes each step puts on the dp axis, by (op, dtype).

    The observability plane (StepTracker.set_collectives) turns this into
    ``raytpu_train_collective_bytes_total{op,dtype}``; it is also how the
    quant win is *visible*: flipping grad_quant moves the reduce bytes
    from float32 to int8 + a small float32 scale stream.
    """
    dp = mesh.shape.get("dp", 1)
    if dp <= 1:
        return {}
    npad = _padded(_param_count(cfg, param_dtype), dp, quant_block)
    out: Dict[Tuple[str, str], int] = {}

    def add(op, dtype, nbytes):
        out[(op, dtype)] = out.get((op, dtype), 0) + nbytes

    if grad_quant:  # grads: int8 payload + fp32 scale stream
        add("reduce_scatter", "int8", npad)
        add("reduce_scatter", "float32", npad // quant_block * 4)
    else:
        add("reduce_scatter", "float32", npad * 4)
    if zero_update:
        # updated params all-gather fp32 — weights stay lossless everywhere
        add("all_gather", "float32", npad * 4)
    elif grad_quant:
        add("all_gather", "int8", npad)
        add("all_gather", "float32", npad // quant_block * 4)
    else:
        add("all_gather", "float32", npad * 4)
    return out


def make_dp_train_step(cfg: TransformerConfig, mesh: Mesh,
                       optimizer: Optional[optax.GradientTransformation],
                       state_sh: TrainState,
                       compute_dtype=jnp.bfloat16,
                       sp_axis: Optional[str] = None,
                       remat: Union[bool, str, None] = True, *,
                       grad_quant: bool = False,
                       quant_block: int = DEFAULT_BLOCK,
                       quant_stochastic: bool = False,
                       zero_update: bool = False,
                       opt_spec: Optional[OptimizerSpec] = None,
                       param_dtype=jnp.float32) -> Callable:
    """The dp-manual (state, batch) -> (state, metrics) step.

    Drop-in for ``make_train_step`` when grad_quant and/or zero_update is
    on.  ``optimizer`` drives the update for the non-ZeRO arm (state from
    ``init_sharded_state``); the ZeRO arm uses ``opt_spec`` (state from
    ``init_zero_state``) because the update applies to a flat shard.
    """
    if sp_axis is not None and mesh.shape.get(sp_axis, 1) > 1:
        raise ValueError("sequence parallelism doesn't compose with the "
                         "dp-manual step; use the default train step")
    dp = _validate_mesh(mesh)
    if zero_update:
        opt_spec = opt_spec or OptimizerSpec()
    elif optimizer is None:
        raise ValueError("grad_quant without zero_update updates with the "
                         "stock optimizer; pass it")
    n = _param_count(cfg, param_dtype)
    npad = _padded(n, dp, quant_block)
    shard_len = npad // dp

    # inside the manual region everything is per-device local
    pctx = ParallelContext(manual_collectives=True)
    loss_fn = functools.partial(transformer.causal_lm_loss, cfg=cfg,
                                pctx=pctx, compute_dtype=compute_dtype,
                                remat=remat)

    def body(state: TrainState, batch: Dict[str, jnp.ndarray]):
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch)
        flat_g, unravel = ravel_pytree(grads)
        flat_g = jnp.pad(flat_g.astype(jnp.float32), (0, npad - n))
        rank = jax.lax.axis_index("dp")
        if quant_stochastic:
            base = jax.random.fold_in(jax.random.PRNGKey(0x0E0A), state.step)
            rkey = jax.random.fold_in(base, rank)
            key_rs, key_ag = jax.random.split(rkey)
        else:
            key_rs = key_ag = None
        # local grads are local-batch means; sum/dp = global-batch mean
        if grad_quant:
            g_shard = quantized_psum_scatter(
                flat_g, "dp", dp, block=quant_block,
                stochastic=quant_stochastic, key=key_rs) / dp
        else:
            g_shard = jax.lax.psum_scatter(flat_g, "dp",
                                           scatter_dimension=0,
                                           tiled=True) / dp
        gnorm = jnp.sqrt(jax.lax.psum(jnp.sum(g_shard * g_shard), "dp"))

        if zero_update:
            flat_p, unravel_p = ravel_pytree(state.params)
            flat_p = jnp.pad(flat_p.astype(jnp.float32), (0, npad - n))
            p_shard = jax.lax.dynamic_slice_in_dim(
                flat_p, rank * shard_len, shard_len)
            # optax.clip_by_global_norm, shard-wise: same select, psum'd norm
            g_shard = jax.lax.select(
                gnorm < opt_spec.grad_clip, g_shard,
                (g_shard / gnorm) * opt_spec.grad_clip)
            updates, new_opt = opt_spec.adamw().update(
                {"p": g_shard}, state.opt_state, {"p": p_shard})
            new_p_shard = optax.apply_updates({"p": p_shard}, updates)["p"]
            new_flat = jax.lax.all_gather(new_p_shard, "dp", tiled=True)
            new_params = unravel_p(new_flat[:n].astype(flat_p.dtype))
        else:
            if grad_quant:
                flat_mean = quantized_all_gather(
                    g_shard, "dp", block=quant_block,
                    stochastic=quant_stochastic, key=key_ag)
            else:
                flat_mean = jax.lax.all_gather(g_shard, "dp", tiled=True)
            grads_mean = unravel(flat_mean[:n])
            updates, new_opt = optimizer.update(grads_mean, state.opt_state,
                                                state.params)
            new_params = optax.apply_updates(state.params, updates)

        metrics = dict(metrics)
        metrics["total_loss"] = loss
        metrics = {k: (jax.lax.psum(v, "dp") if k == "tokens"
                       else jax.lax.pmean(v, "dp"))
                   for k, v in metrics.items()}
        metrics["grad_norm"] = gnorm
        return TrainState(new_params, new_opt, state.step + 1), metrics

    is_sh = lambda x: isinstance(x, NamedSharding)
    state_specs = jax.tree.map(lambda s: s.spec, state_sh, is_leaf=is_sh)
    batch_spec = P(tuple(a for a in ("dp", "fsdp") if a in mesh.axis_names),
                   None)
    sharded = jax_compat.shard_map(
        body, mesh=mesh,
        in_specs=(state_specs, batch_spec),
        out_specs=(state_specs, P()),
        check_vma=False)
    jitted = jax.jit(sharded, in_shardings=(state_sh, None),
                     out_shardings=(state_sh, None), donate_argnums=(0,))

    batch_sh = NamedSharding(mesh, batch_spec)
    multiprocess = len({d.process_index for d in mesh.devices.flat}) > 1

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        if multiprocess:
            batch = {k: jax.make_array_from_process_local_data(
                batch_sh, np.asarray(v)) for k, v in batch.items()}
        else:
            batch = {k: jax.device_put(v, batch_sh) for k, v in batch.items()}
        return jitted(state, batch)

    step._jitted = jitted
    step.batch_sharding = batch_sh
    step.collective_bytes = collective_bytes_per_step(
        cfg, mesh, grad_quant=grad_quant, zero_update=zero_update,
        quant_block=quant_block, param_dtype=param_dtype)
    step.opt_state_bytes = (
        zero_opt_state_bytes(cfg, mesh, quant_block, param_dtype)
        if zero_update else 2 * n * 4 + 8)
    return step
