"""Pipeline parallelism: GPipe-style microbatching inside one jitted program.

The reference has no pipeline parallelism in core (SURVEY §2.3 PP row —
delegated to Alpa/DeepSpeed on top of Ray actors).  The TPU-native design runs
the whole pipeline *inside* a single SPMD program under ``shard_map``:

* the stacked layer params [L, ...] are reshaped to [P, L/P, ...] and the
  stage dim is sharded over the ``pp`` mesh axis — each device holds one
  stage's layers;
* microbatches march through stages on a ``lax.scan`` over
  ``M + P - 1`` ticks; each tick every stage runs its layers on its current
  microbatch, then activations rotate one hop along the ``pp`` ring with
  ``ppermute`` (ICI neighbor traffic, overlapping the next tick's compute);
* stage 0 injects embedded microbatches, the last stage's outputs are
  collected from the scan ys, and the loss (final norm + chunked CE) runs on
  the last stage only — ``where``-masked, SPMD-uniform;
* autodiff of the scan+ppermute gives the reverse pipeline schedule for
  gradients; the replicated in-specs of embed/head params transpose into the
  correct cross-stage psums.

Composes with ``dp`` (batch sharding) in the same shard_map.  Bubble fraction
is the GPipe (P-1)/(M+P-1); pick num_microbatches >= 4*P to amortize — or use
``virtual_stages`` V > 1 (``interleaved_pipeline_loss_fn``) for the
Megatron-style interleaved schedule, which cuts the fill bubble to
(P-1)/V stage-times at the cost of V× more (smaller) ppermute hops.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..util import jax_compat
from ..models import sharding as shard_rules
from ..models import transformer
from ..models.config import TransformerConfig
from .mesh import named_sharding
from .train_step import TrainState


def partition_layers(params, num_stages: int, virtual_stages: int = 1):
    """Reshape every stacked-layer leaf [L, ...] -> [P, V*Lc, ...].

    With ``virtual_stages`` V > 1 the assignment is INTERLEAVED
    (Megatron-style): device d owns chunks d, P+d, 2P+d, … of the V*P
    total chunks, so layers [L] -> [V, P, Lc] -> transpose -> [P, V, Lc]
    -> flatten the local dims to [P, V*Lc].  A microbatch then makes V
    circuits of the ring, running one chunk per visit."""
    def fix(x):
        L = x.shape[0]
        assert L % (num_stages * virtual_stages) == 0, \
            (L, num_stages, virtual_stages)
        lc = L // (num_stages * virtual_stages)
        tail = x.shape[1:]
        if virtual_stages == 1:
            return x.reshape(num_stages, lc, *tail)
        x = x.reshape(virtual_stages, num_stages, lc, *tail)
        x = jnp.swapaxes(x, 0, 1)
        return x.reshape(num_stages, virtual_stages * lc, *tail)
    return {**params, "blocks": jax.tree.map(fix, params["blocks"])}


def merge_layers(params, virtual_stages: int = 1):
    """Inverse of partition_layers."""
    def fix(x):
        P_, VL = x.shape[0], x.shape[1]
        tail = x.shape[2:]
        if virtual_stages == 1:
            return x.reshape(P_ * VL, *tail)
        lc = VL // virtual_stages
        x = x.reshape(P_, virtual_stages, lc, *tail)
        x = jnp.swapaxes(x, 0, 1)
        return x.reshape(P_ * VL, *tail)
    return {**params, "blocks": jax.tree.map(fix, params["blocks"])}


def pipeline_param_specs(cfg: TransformerConfig,
                         auto_axes: Tuple[str, ...] = ()) -> Dict[str, Any]:
    """PartitionSpec tree for stage-partitioned params: blocks get a leading
    pp stage dim; embed/head/final-norm replicated across stages (their grads
    psum through the shard_map in-spec transpose).

    ``auto_axes`` retains those mesh axes from the logical (tensor-parallel)
    specs — used to build the STATE sharding when the pipeline shard_map
    leaves e.g. ``tp`` automatic (pp manual + tp compiler-inserted
    collectives).  With the default empty tuple this is the manual in-spec
    view: everything but pp/dp replicated."""
    base = shard_rules.logical_param_specs(cfg)

    def keep(d):
        return d if d in auto_axes else None

    def add_stage_dim(spec: P) -> P:
        # original leading dim was the layer dim (None).
        return P("pp", *[keep(d) for d in spec])

    blocks = jax.tree.map(add_stage_dim, base["blocks"],
                          is_leaf=lambda x: isinstance(x, P))

    def outer(spec: P) -> P:
        return P(*[keep(d) for d in spec])

    out = {k: (blocks if k == "blocks" else
               jax.tree.map(outer, v, is_leaf=lambda x: isinstance(x, P)))
           for k, v in base.items()}
    return out


def _stage_apply(x, stage_params, cfg, positions, compute_dtype,
                 pctx=transformer.ParallelContext()):
    """Run this device's L/P layers on x [mb, S, H]."""
    def body(x, layer_params):
        x, aux = transformer.block_forward(x, layer_params, cfg, positions,
                                           pctx)
        return x, aux

    body = jax.checkpoint(body, policy=jax.checkpoint_policies.nothing_saveable)
    x, aux = jax.lax.scan(body, x, stage_params)
    return x, aux.sum()


def _pp_axis_split(mesh: Mesh, dp_axes, sp_axis: str):
    """Partition the mesh axes for the pipeline shard_map.

    Returns (dp_axes, sp, auto_axes): dp_axes are the MANUAL batch axes,
    ``sp`` is the manual sequence axis (or None), and auto_axes stay with
    the COMPILER — tp's megatron collectives and fsdp's ZeRO
    gather/reduce-scatter of the stage-sharded params are both inserted by
    XLA from the storage shardings (scaling-book recipe), so composing
    pp x fsdp needs no hand-written gathers."""
    auto_axes = tuple(a for a in ("tp", "fsdp") if a in mesh.axis_names
                      and mesh.shape[a] > 1)
    dp_axes = tuple(a for a in dp_axes if a in mesh.axis_names
                    and mesh.shape[a] > 1 and a not in auto_axes) or None
    sp = (sp_axis if sp_axis in mesh.axis_names
          and mesh.shape[sp_axis] > 1 else None)
    return dp_axes, sp, auto_axes


def _final_stage_loss(final, params, targets, cfg, loss_chunk,
                      p_idx, n_stages, dp_axes, pp_axis):
    """Loss head shared by both pipeline schedules: final-norm + lm-head +
    (chunked) CE on the LAST stage, psum-masked SPMD-uniform, pmean over
    data axes (batch AND, under sequence parallelism, the sp shard axis —
    every shard holds an equal token count, so mean-of-means is exact)."""
    n, s, h = final.shape[0] * final.shape[1], final.shape[2], final.shape[3]
    final = final.reshape(n, s, h)
    x = transformer._norm(final, params["final_norm"], cfg)
    w = transformer.lm_head_weight(params, cfg, x.dtype)
    tgt = targets.reshape(n, s)
    chunk = loss_chunk
    if chunk == 0:
        chunk = 512 if s * cfg.vocab_size > 2 ** 25 else None
    if chunk:
        nll = transformer.chunked_cross_entropy(x, w, tgt, min(chunk, s))
    else:
        logits = (x @ w).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    local_loss = nll.mean()
    loss = jax.lax.psum(
        jnp.where(p_idx == n_stages - 1, local_loss, 0.0), pp_axis)
    if dp_axes:
        loss = jax.lax.pmean(loss, dp_axes)
    return loss


def _wrap_pipeline_loss(smapped):
    def loss_fn(params, batch):
        if "targets" in batch:
            tokens, targets = batch["tokens"], batch["targets"]
        else:
            tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
        loss, moe_aux = smapped(params, tokens, targets)
        total = loss + 0.01 * moe_aux
        return total, {"loss": loss, "moe_aux_loss": moe_aux,
                       "tokens": tokens.size}
    return loss_fn


def pipeline_loss_fn(cfg: TransformerConfig, mesh: Mesh,
                     num_microbatches: int,
                     compute_dtype=jnp.bfloat16,
                     loss_chunk: Optional[int] = 0,
                     pp_axis: str = "pp",
                     dp_axes: Tuple[str, ...] = ("dp", "fsdp"),
                     sp_axis: str = "sp"):
    """Returns loss(params_staged, batch) -> (loss, metrics), shard_mapped
    over the pp (stages), dp (batch) and sp (sequence, ring attention) mesh
    axes; tp and fsdp stay automatic (compiler-inserted collectives — fsdp
    is the ZeRO sharding of the stage-local params and optimizer state)."""
    M = num_microbatches
    dp_axes, sp, auto_axes = _pp_axis_split(mesh, dp_axes, sp_axis)
    if sp and not cfg.use_rope:
        raise ValueError("pp x sp needs RoPE positions (learned positional "
                         "embeddings are not sequence-shard aware)")

    pspec_tree = pipeline_param_specs(cfg)
    batch_dim = dp_axes if dp_axes and len(dp_axes) > 1 else (
        dp_axes[0] if dp_axes else None)
    batch_spec = P(batch_dim, sp)
    reduce_axes = tuple(dp_axes or ()) + ((sp,) if sp else ()) or None
    pctx = transformer.ParallelContext(mesh=mesh, sp_axis=sp,
                                       manual_collectives=True)

    def body(params, tokens, targets):
        p_idx = jax.lax.axis_index(pp_axis)
        n_stages = jax.lax.psum(1, pp_axis)
        # Local view of the stage-sharded blocks has stage-dim extent 1.
        stage = jax.tree.map(lambda x: x[0], params["blocks"])
        b_local, s = tokens.shape   # s is the sp-LOCAL sequence shard
        mb = b_local // M
        positions = jnp.arange(s)
        if sp:
            positions = positions + jax.lax.axis_index(sp) * s

        toks_mb = tokens.reshape(M, mb, s)
        h = cfg.hidden_size

        def tick(carry, t):
            act = carry
            # Inject microbatch t at stage 0 (all ranks compute the cheap
            # embed; the where selects). Clamp t to a valid index for the
            # trailing bubble ticks.
            t_in = jnp.clip(t, 0, M - 1)
            inject = transformer.embed_tokens(
                params, jax.lax.dynamic_index_in_dim(toks_mb, t_in, 0,
                                                     keepdims=False),
                cfg, compute_dtype)
            act = jnp.where((p_idx == 0) & (t < M), inject, act)
            act, aux = _stage_apply(act, stage, cfg, positions,
                                    compute_dtype, pctx)
            # Rotate activations one hop forward along the pp ring; the wrap
            # from the last stage back to 0 carries garbage that the next
            # tick's stage-0 inject overwrites.
            nxt = jax.lax.ppermute(
                act, pp_axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return nxt, (act, aux)

        init = jnp.zeros((mb, s, h), compute_dtype)
        _, (outs, auxes) = jax.lax.scan(tick, init, jnp.arange(M + n_stages - 1))

        # The last stage produced microbatch m's output at tick m + P - 1.
        # n_stages is static on a concrete mesh: mesh.shape[pp_axis].
        P_static = mesh.shape[pp_axis]
        final = outs[P_static - 1: P_static - 1 + M]        # [M, mb, S, H]
        loss = _final_stage_loss(final, params, targets, cfg, loss_chunk,
                                 p_idx, n_stages, reduce_axes, pp_axis)
        moe_aux = jax.lax.psum(auxes.sum(), pp_axis) / (M * n_stages)
        if reduce_axes:
            moe_aux = jax.lax.pmean(moe_aux, reduce_axes)
        return loss, moe_aux

    param_specs = jax.tree.map(lambda s: s, pspec_tree,
                               is_leaf=lambda x: isinstance(x, P))

    smap_kwargs: Dict[str, Any] = {}
    if auto_axes:
        manual = {pp_axis} | set(dp_axes or ()) | ({sp} if sp else set())
        smap_kwargs["axis_names"] = manual
    smapped = jax_compat.shard_map(
        body, mesh=mesh,
        in_specs=(param_specs, batch_spec, batch_spec),
        out_specs=(P(), P()),
        check_vma=False, **smap_kwargs)
    return _wrap_pipeline_loss(smapped)


def interleaved_pipeline_loss_fn(cfg: TransformerConfig, mesh: Mesh,
                                 num_microbatches: int, virtual_stages: int,
                                 compute_dtype=jnp.bfloat16,
                                 loss_chunk: Optional[int] = 0,
                                 pp_axis: str = "pp",
                                 dp_axes: Tuple[str, ...] = ("dp", "fsdp"),
                                 sp_axis: str = "sp"):
    """Interleaved (virtual-stage) pipeline schedule — Megatron-style.

    Device d owns V layer chunks (global chunks d, P+d, 2P+d, …); a
    microbatch makes V circuits of the pp ring, running ONE chunk per
    device visit, so each tick is 1/V of a GPipe stage-time and the
    pipeline-fill bubble shrinks from (P-1) stage-times to (P-1)/V.
    Microbatches inject in waves of P every V*P ticks (a ring slot frees
    exactly when its resident finishes circuit V); the schedule is fully
    static, so the whole thing stays one ``lax.scan`` inside ``shard_map``
    — autodiff gives the reverse interleaved schedule for free.

    Because the schedule is static, each resident's identity is a pure
    function of (device, tick): a resident injected at tick t0 has made
    h = t - t0 hops, sits on device h mod P, circuit h // P — so device d
    at tick t solves c = ((t - d) mod V*P) // P and
    m = ((t - h) div V*P)*P + ((t - h) mod V*P).  Only the activation
    itself rides the ppermute ring; chunk selection is a dynamic slice of
    the device's [V*Lc] local layer stack; embeddings are precomputed once
    outside the scan; finished outputs (c == V-1 at the last stage) write
    into a carried output buffer that the final-stage loss consumes."""
    M = num_microbatches
    V = virtual_stages
    dp_axes, sp, auto_axes = _pp_axis_split(mesh, dp_axes, sp_axis)
    if sp and not cfg.use_rope:
        raise ValueError("pp x sp needs RoPE positions (learned positional "
                         "embeddings are not sequence-shard aware)")
    P_static = mesh.shape[pp_axis]
    assert M % P_static == 0, \
        (f"interleaved schedule injects waves of P: num_microbatches {M} "
         f"must be a multiple of pp={P_static}")
    n_ticks = (M // P_static) * V * P_static + P_static - 1

    pspec_tree = pipeline_param_specs(cfg)
    batch_dim = dp_axes if dp_axes and len(dp_axes) > 1 else (
        dp_axes[0] if dp_axes else None)
    batch_spec = P(batch_dim, sp)
    reduce_axes = tuple(dp_axes or ()) + ((sp,) if sp else ()) or None
    pctx = transformer.ParallelContext(mesh=mesh, sp_axis=sp,
                                       manual_collectives=True)

    def body(params, tokens, targets):
        p_idx = jax.lax.axis_index(pp_axis)
        n_stages = jax.lax.psum(1, pp_axis)
        stage = jax.tree.map(lambda x: x[0], params["blocks"])  # [V*Lc,...]
        n_layers_local = jax.tree.leaves(stage)[0].shape[0]
        lc = n_layers_local // V
        b_local, s = tokens.shape
        mb = b_local // M
        positions = jnp.arange(s)
        if sp:
            positions = positions + jax.lax.axis_index(sp) * s
        h = cfg.hidden_size
        VP = V * n_stages
        # Embeddings once, outside the scan (the per-tick inject only
        # indexes this buffer).
        emb_mb = transformer.embed_tokens(params, tokens, cfg,
                                          compute_dtype).reshape(M, mb, s, h)

        def tick(carry, t):
            act, out_buf, aux_sum = carry
            # Resident identity is analytic in (p_idx, t) — see docstring.
            r = (t - p_idx) % VP
            c = r // n_stages                    # circuit of this resident
            t0 = t - (c * n_stages + p_idx)      # its injection tick
            m = (t0 // VP) * n_stages + t0 % VP  # its microbatch
            valid = (t0 >= 0) & (m < M)
            m_safe = jnp.clip(m, 0, M - 1)
            # stage 0, circuit 0: this tick IS the injection
            act = jnp.where((p_idx == 0) & (c == 0),
                            jax.lax.dynamic_index_in_dim(emb_mb, m_safe, 0,
                                                         keepdims=False),
                            act)
            # run this visit's chunk: rows [c*lc, (c+1)*lc) of the local
            # layer stack
            chunk = jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(x, c * lc, lc, 0),
                stage)
            act, aux = _stage_apply(act, chunk, cfg, positions, compute_dtype,
                                    pctx)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            # a resident finishing circuit V-1 at the last stage is done
            done = (p_idx == n_stages - 1) & (c == V - 1) & valid
            out_buf = jax.lax.dynamic_update_index_in_dim(
                out_buf,
                jnp.where(done, act,
                          jax.lax.dynamic_index_in_dim(out_buf, m_safe, 0,
                                                       keepdims=False)),
                m_safe, 0)
            act = jax.lax.ppermute(
                act, pp_axis,
                [(i, (i + 1) % n_stages) for i in range(n_stages)])
            return (act, out_buf, aux_sum), ()

        init = (jnp.zeros((mb, s, h), compute_dtype),
                jnp.zeros((M, mb, s, h), compute_dtype),
                jnp.zeros((), jnp.float32))
        (_act, out_buf, aux_sum), _ = jax.lax.scan(
            tick, init, jnp.arange(n_ticks))

        loss = _final_stage_loss(out_buf, params, targets, cfg, loss_chunk,
                                 p_idx, n_stages, reduce_axes, pp_axis)
        # Same convention as the GPipe path (sum over all layer-chunk aux
        # values / (M * P)) so the two schedules are interchangeable.
        moe_aux = jax.lax.psum(aux_sum, pp_axis) / (M * P_static)
        if reduce_axes:
            moe_aux = jax.lax.pmean(moe_aux, reduce_axes)
        return loss, moe_aux

    smap_kwargs: Dict[str, Any] = {}
    if auto_axes:
        smap_kwargs["axis_names"] = ({pp_axis} | set(dp_axes or ())
                                     | ({sp} if sp else set()))
    smapped = jax_compat.shard_map(
        body, mesh=mesh,
        in_specs=(pspec_tree, batch_spec, batch_spec),
        out_specs=(P(), P()),
        check_vma=False, **smap_kwargs)
    return _wrap_pipeline_loss(smapped)


def init_pp_state(cfg: TransformerConfig, mesh: Mesh,
                  optimizer: optax.GradientTransformation, seed: int = 0,
                  param_dtype=jnp.float32,
                  virtual_stages: int = 1) -> Tuple[TrainState, TrainState]:
    """Initialize a stage-partitioned TrainState sharded over the mesh."""
    num_stages = mesh.shape["pp"]

    def init_fn():
        params = transformer.init_params(jax.random.PRNGKey(seed), cfg,
                                         dtype=param_dtype)
        params = partition_layers(params, num_stages, virtual_stages)
        return TrainState(params=params, opt_state=optimizer.init(params),
                          step=jnp.zeros((), jnp.int32))

    # State arrays keep their tensor-parallel AND ZeRO (fsdp) shardings on
    # top of the stage partition — the loss shard_map treats both as
    # automatic axes, so XLA inserts the tp matmul collectives and the
    # fsdp param-gather / grad-reduce-scatter from these storage shardings.
    auto = tuple(a for a in ("tp", "fsdp") if a in mesh.axis_names
                 and mesh.shape[a] > 1)
    pspecs = pipeline_param_specs(cfg, auto_axes=auto)
    param_sh = named_sharding(mesh, pspecs)
    shapes = jax.eval_shape(init_fn)
    from .train_step import state_shardings as _ss  # reuse opt-state recursion

    # state_shardings builds from logical_param_specs; do the same recursion
    # against the pipeline specs instead.
    params_struct = jax.tree.structure(param_sh)

    def shard_opt_state(node):
        try:
            if jax.tree.structure(node) == params_struct:
                return param_sh
        except Exception:
            pass
        if hasattr(node, "_fields"):
            return type(node)(*(shard_opt_state(x) for x in node))
        if isinstance(node, tuple):
            return tuple(shard_opt_state(x) for x in node)
        if isinstance(node, list):
            return [shard_opt_state(x) for x in node]
        if dataclasses.is_dataclass(node) and not isinstance(node, type):
            return type(node)(**{f.name: shard_opt_state(getattr(node, f.name))
                                 for f in dataclasses.fields(node)})
        if isinstance(node, dict):
            return {k: shard_opt_state(v) for k, v in node.items()}
        return NamedSharding(mesh, P())

    sh = TrainState(params=param_sh,
                    opt_state=shard_opt_state(shapes.opt_state),
                    step=NamedSharding(mesh, P()))
    state = jax.jit(init_fn, out_shardings=sh)()
    return state, sh


def make_pp_train_step(cfg: TransformerConfig, mesh: Mesh,
                       optimizer: optax.GradientTransformation,
                       state_sh: TrainState, num_microbatches: int = 4,
                       compute_dtype=jnp.bfloat16,
                       loss_chunk: Optional[int] = 0,
                       virtual_stages: int = 1) -> Callable:
    """Jitted pipeline train step over a mesh with a pp axis (+ optional
    dp).  ``virtual_stages`` > 1 selects the interleaved schedule (the
    state must be initialized with the same value)."""
    if virtual_stages > 1:
        loss_fn = interleaved_pipeline_loss_fn(
            cfg, mesh, num_microbatches, virtual_stages, compute_dtype,
            loss_chunk)
    else:
        loss_fn = pipeline_loss_fn(cfg, mesh, num_microbatches,
                                   compute_dtype, loss_chunk)
    batch_sh = NamedSharding(mesh, shard_rules.batch_spec())

    def step_fn(state: TrainState, batch):
        (total, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = dict(metrics)
        metrics["total_loss"] = total
        metrics["grad_norm"] = optax.global_norm(grads)
        return TrainState(new_params, new_opt, state.step + 1), metrics

    jitted = jax.jit(step_fn, in_shardings=(state_sh, None),
                     out_shardings=(state_sh, None), donate_argnums=(0,))

    def step(state, batch):
        batch = {k: jax.device_put(v, batch_sh) for k, v in batch.items()}
        return jitted(state, batch)

    step._jitted = jitted
    return step
