"""Device mesh construction: named axes for dp/fsdp/sp/ep/tp(/pp).

The TPU-native replacement for the reference's process-group setup
(``python/ray/train/torch/config.py:63-160`` ``_setup_torch_process_group``): instead
of rendezvous + NCCL communicators, every host builds the same ``jax.sharding.Mesh``
and XLA compiles collectives over ICI/DCN.  Axis order is chosen so the most
communication-intensive axis (tp) maps to the innermost (closest) devices on the
physical topology.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

AXIS_ORDER = ("pp", "dp", "fsdp", "sp", "ep", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Sizes per axis; -1 on at most one axis = fill with remaining devices."""
    dp: int = 1
    fsdp: int = -1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    pp: int = 1

    def sizes(self) -> Dict[str, int]:
        return {"pp": self.pp, "dp": self.dp, "fsdp": self.fsdp,
                "sp": self.sp, "ep": self.ep, "tp": self.tp}

    def resolve(self, n_devices: int) -> Dict[str, int]:
        sizes = self.sizes()
        fill = [k for k, v in sizes.items() if v == -1]
        if len(fill) > 1:
            raise ValueError("at most one mesh axis may be -1")
        fixed = math.prod(v for v in sizes.values() if v != -1)
        if fill:
            if n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by fixed axes {fixed}")
            sizes[fill[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(f"mesh {sizes} needs {fixed} devices, "
                             f"have {n_devices}")
        return sizes

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.resolve(len(devices))
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        arr = np.array(devices).reshape(shape)
        return Mesh(arr, AXIS_ORDER)


def make_mesh(n_devices: Optional[int] = None, **axis_sizes) -> Mesh:
    """Convenience: make_mesh(fsdp=4, tp=2)."""
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return MeshSpec(**axis_sizes).build(devices)


def named_sharding(mesh: Mesh, spec_tree):
    """Map a PartitionSpec tree to a NamedSharding tree for the given mesh,
    dropping axis names the mesh doesn't have (so the same rules work on a
    dp-only mesh and a full dp×fsdp×tp×sp×ep mesh)."""
    mesh_axes = set(mesh.axis_names)

    def fix_spec(spec: PartitionSpec) -> NamedSharding:
        parts = []
        for entry in spec:
            if entry is None:
                parts.append(None)
            elif isinstance(entry, (tuple, list)):
                kept = tuple(a for a in entry if a in mesh_axes
                             and mesh.shape[a] > 0)
                parts.append(kept if kept else None)
            else:
                parts.append(entry if entry in mesh_axes else None)
        return NamedSharding(mesh, PartitionSpec(*parts))

    return jax.tree.map(fix_spec, spec_tree,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape.get(axis, 1) if hasattr(mesh.shape, "get") else (
        dict(zip(mesh.axis_names, mesh.devices.shape)).get(axis, 1))
