"""Int8 block-scaled gradient collectives (the EQuARX scheme).

When ICI/DCN bandwidth — not flops — bounds the data-parallel step, the
fp32 gradient all-reduce is the wire cost.  EQuARX (PAPERS.md) cuts it
~4x by quantizing each reduce-scatter / all-gather payload to int8 with a
per-block fp32 scale: the all-reduce decomposes into

    reduce-scatter(quantized)  ->  dequant + sum  ->  all-gather(quantized)

so every byte on the wire is int8 + one fp32 scale per ``block`` elements
(wire bytes ~ n + 4n/block vs 4n for fp32).  Accumulation stays fp32 —
only the wire payload is narrow.

Quantization is symmetric per-block: ``scale = amax / 127``, values
rounded to nearest (deterministic, the default) or stochastically
(``stochastic=True`` — unbiased, E[dequant(q)] == x, for long training
runs where rounding bias compounds).  The absolute error of one
quantize/dequant round-trip is bounded by ``scale / 2 = amax / 254`` per
element per participating device — the bound the CPU exactness harness in
tests/test_chipspeed.py checks against.

Both collectives here are written for a **manual** (shard_map) region:
they take per-device local arrays and use ``jax.lax`` collectives over a
named axis.  ``parallel/zero.py`` is the caller.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "DEFAULT_BLOCK", "quantize_int8_block", "dequantize_int8_block",
    "quantized_psum_scatter", "quantized_all_gather", "quant_error_bound",
]

#: Elements sharing one fp32 scale.  256 keeps the scale overhead at
#: 4/256 ≈ 1.6% of the int8 payload while staying lane-aligned.
DEFAULT_BLOCK = 256


def quantize_int8_block(x: jnp.ndarray, block: int = DEFAULT_BLOCK,
                        stochastic: bool = False,
                        key: Optional[jax.Array] = None
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """[..., n] fp32 -> (int8 [..., n], fp32 scales [..., n/block]).

    Symmetric per-block quantization: scale = amax/127 (1 for all-zero
    blocks so dequant is exact there).  ``stochastic`` rounds x/scale to
    floor(y + u), u ~ U[0,1) — unbiased stochastic rounding.
    """
    *lead, n = x.shape
    assert n % block == 0, (n, block)
    xb = x.astype(jnp.float32).reshape(*lead, n // block, block)
    amax = jnp.max(jnp.abs(xb), axis=-1, keepdims=True)
    scale = jnp.where(amax > 0.0, amax / 127.0, jnp.ones_like(amax))
    y = xb / scale
    if stochastic:
        if key is None:
            raise ValueError("stochastic rounding needs a PRNG key")
        u = jax.random.uniform(key, xb.shape, dtype=jnp.float32)
        q = jnp.floor(y + u)
    else:
        q = jnp.round(y)
    q = jnp.clip(q, -127.0, 127.0).astype(jnp.int8)
    return q.reshape(*lead, n), scale.squeeze(-1)


def dequantize_int8_block(q: jnp.ndarray, scale: jnp.ndarray,
                          block: int = DEFAULT_BLOCK) -> jnp.ndarray:
    """Inverse of quantize_int8_block: (int8 [..., n], scales) -> fp32."""
    *lead, n = q.shape
    xb = q.astype(jnp.float32).reshape(*lead, n // block, block)
    return (xb * scale[..., None]).reshape(*lead, n)


def quant_error_bound(x_amax: float, block: int, world: int) -> float:
    """Worst-case absolute error of a quantized ``world``-way reduction of
    values whose per-block amax is <= x_amax: each device contributes at
    most scale/2 = amax/254 rounding error per element (deterministic
    rounding); stochastic rounding is bounded by a full step, amax/127."""
    del block  # the bound is per-element; block only sets scale locality
    return world * x_amax / 254.0


def quantized_psum_scatter(flat: jnp.ndarray, axis_name: str, axis_size: int,
                           *, block: int = DEFAULT_BLOCK,
                           stochastic: bool = False,
                           key: Optional[jax.Array] = None) -> jnp.ndarray:
    """Quantized reduce-scatter inside a manual region.

    ``flat``: per-device fp32 [n], n % (axis_size*block) == 0.  Returns this
    device's [n/axis_size] chunk of the elementwise sum over ``axis_name``
    (chunk i to device i — matches ``lax.psum_scatter(tiled=True)``).

    Wire: one all_to_all of int8 [n] + one of fp32 scales [n/block] —
    the fp32 payload would have been 4n bytes.  The sum is accumulated in
    fp32 *after* dequantization, so error does not compound across ranks
    beyond the per-rank rounding bound.
    """
    n = flat.shape[0]
    assert n % (axis_size * block) == 0, (n, axis_size, block)
    x = flat.reshape(axis_size, n // axis_size)
    q, scale = quantize_int8_block(x, block, stochastic, key)
    # all_to_all: row i of every device -> device i; each device ends up
    # holding every rank's version of its own chunk.
    q = jax.lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0)
    scale = jax.lax.all_to_all(scale, axis_name, split_axis=0, concat_axis=0)
    return dequantize_int8_block(q, scale, block).sum(axis=0)


def quantized_all_gather(shard: jnp.ndarray, axis_name: str, *,
                         block: int = DEFAULT_BLOCK,
                         stochastic: bool = False,
                         key: Optional[jax.Array] = None) -> jnp.ndarray:
    """Quantized tiled all-gather inside a manual region.

    ``shard``: per-device fp32 [k] -> fp32 [axis_size*k], rank order
    (matches ``lax.all_gather(tiled=True)``).  Each element crosses the
    wire as int8 + amortized scale instead of fp32.
    """
    q, scale = quantize_int8_block(shard, block, stochastic, key)
    q = jax.lax.all_gather(q, axis_name, tiled=True)
    scale = jax.lax.all_gather(scale, axis_name, tiled=True)
    return dequantize_int8_block(q, scale, block)
