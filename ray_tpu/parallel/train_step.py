"""Sharded train-step builder: the pjit data plane of the Train library.

Replaces the reference's DDP/FSDP wrapping (``train_loop_utils.py:263``
``prepare_model``) with the XLA-native formulation: params/optimizer state sharded by
spec trees, batch sharded over (dp, fsdp, sp), gradients reduced by the compiler over
ICI.  One jitted function = forward + backward + optimizer update, with donated state
(no double-buffered params in HBM).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import sharding as shard_rules
from ..models import transformer
from ..models.config import TransformerConfig
from ..models.transformer import ParallelContext
from .mesh import named_sharding


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jnp.ndarray


def make_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.1,
                   warmup_steps: int = 100, total_steps: int = 10_000,
                   b1: float = 0.9, b2: float = 0.95,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(sched, b1=b1, b2=b2, weight_decay=weight_decay),
    )


def state_shardings(cfg: TransformerConfig, mesh: Mesh,
                    optimizer: optax.GradientTransformation,
                    example_state_shapes) -> TrainState:
    """Build the NamedSharding tree for a TrainState: opt state leaves inherit
    the sharding of the param they track (ZeRO — optimizer sharded like params)."""
    pspecs = shard_rules.logical_param_specs(cfg)
    param_sh = named_sharding(mesh, pspecs)

    # optax states (adam mu/nu, etc.) embed subtrees with the exact param tree
    # structure — recurse and substitute the param sharding wherever a subtree
    # matches it; everything else (counts, scalars) is replicated.
    params_struct = jax.tree.structure(param_sh)

    def shard_opt_state(opt_shapes):
        def rec(node):
            try:
                if jax.tree.structure(node) == params_struct:
                    return param_sh
            except Exception:
                pass
            if hasattr(node, "_fields"):  # namedtuple (optax state classes)
                return type(node)(*(rec(x) for x in node))
            if isinstance(node, tuple):
                return tuple(rec(x) for x in node)
            if isinstance(node, list):
                return [rec(x) for x in node]
            if dataclasses.is_dataclass(node) and not isinstance(node, type):
                return type(node)(**{f.name: rec(getattr(node, f.name))
                                     for f in dataclasses.fields(node)})
            if isinstance(node, dict):
                return {k: rec(v) for k, v in node.items()}
            return NamedSharding(mesh, P())  # scalars: replicated
        return rec(opt_shapes)

    return TrainState(params=param_sh,
                      opt_state=shard_opt_state(example_state_shapes.opt_state),
                      step=NamedSharding(mesh, P()))


def init_sharded_state(cfg: TransformerConfig, mesh: Mesh,
                       optimizer: optax.GradientTransformation,
                       seed: int = 0, param_dtype=jnp.float32) -> Tuple[TrainState, TrainState]:
    """Initialize TrainState directly sharded on the mesh (out_shardings on the
    jitted init — params never materialize replicated)."""
    def init_fn():
        params = transformer.init_params(jax.random.PRNGKey(seed), cfg,
                                         dtype=param_dtype)
        return TrainState(params=params, opt_state=optimizer.init(params),
                          step=jnp.zeros((), jnp.int32))

    shapes = jax.eval_shape(init_fn)
    shardings = state_shardings(cfg, mesh, optimizer, shapes)
    state = jax.jit(init_fn, out_shardings=shardings)()
    return state, shardings


def make_train_step(cfg: TransformerConfig, mesh: Mesh,
                    optimizer: optax.GradientTransformation,
                    state_sh: TrainState,
                    compute_dtype=jnp.bfloat16,
                    sp_axis: Optional[str] = None,
                    remat: Union[bool, str, None] = True, *,
                    grad_quant_enabled: bool = False,
                    quant_block: Optional[int] = None,
                    quant_stochastic: bool = False,
                    zero_sharded_update: bool = False,
                    opt_spec=None) -> Callable:
    """Returns jitted (state, batch) -> (state, metrics).

    With ``grad_quant_enabled`` and/or ``zero_sharded_update`` the step is
    built by ``zero.make_dp_train_step`` instead: an explicit dp-manual
    reduce-scatter / update / all-gather schedule with optional int8
    block-scaled wire payloads (see parallel/zero.py).  Both knobs off —
    the default — is byte-for-byte today's path.
    """
    if grad_quant_enabled or zero_sharded_update:
        from . import zero
        return zero.make_dp_train_step(
            cfg, mesh, optimizer, state_sh, compute_dtype=compute_dtype,
            sp_axis=sp_axis, remat=remat, grad_quant=grad_quant_enabled,
            quant_block=quant_block or zero.DEFAULT_BLOCK,
            quant_stochastic=quant_stochastic,
            zero_update=zero_sharded_update, opt_spec=opt_spec)
    pctx = ParallelContext(mesh=mesh, sp_axis=sp_axis,
                           batch_axes=shard_rules.BATCH_AXES)
    batch_sh = NamedSharding(mesh, shard_rules.batch_spec())

    loss_fn = functools.partial(transformer.causal_lm_loss, cfg=cfg, pctx=pctx,
                                compute_dtype=compute_dtype, remat=remat)

    def step_fn(state: TrainState, batch: Dict[str, jnp.ndarray]):
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, batch)
        updates, new_opt = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        gnorm = optax.global_norm(grads)
        metrics = dict(metrics)
        metrics["grad_norm"] = gnorm
        metrics["total_loss"] = loss
        return TrainState(new_params, new_opt, state.step + 1), metrics

    jitted = jax.jit(
        step_fn,
        in_shardings=(state_sh, None),  # batch sharding from the arrays
        out_shardings=(state_sh, None),
        donate_argnums=(0,))

    # Multi-controller (jax.distributed across hosts): each process feeds its
    # LOCAL slice of the global batch; device_put can't target non-addressable
    # shards (reference seam: train/torch/config.py rendezvous — here the
    # equivalent is the global-array assembly step).
    multiprocess = len({d.process_index for d in mesh.devices.flat}) > 1

    def step(state: TrainState, batch: Dict[str, jnp.ndarray]):
        import numpy as np
        if multiprocess:
            batch = {k: jax.make_array_from_process_local_data(
                batch_sh, np.asarray(v)) for k, v in batch.items()}
        else:
            batch = {k: jax.device_put(v, batch_sh) for k, v in batch.items()}
        return jitted(state, batch)

    step._jitted = jitted
    step.batch_sharding = batch_sh
    # wire/HBM accounting for the observability plane: the compiler-placed
    # fp32 gradient all-reduce over dp, and fully-replicated Adam state.
    # A ring all-reduce moves ~2x the payload per device (reduce-scatter
    # phase + all-gather phase) — counted as such so the number is
    # comparable with the explicit RS/AG schedule of parallel/zero.py.
    dp = 1
    for ax in ("dp", "fsdp"):
        dp *= mesh.shape.get(ax, 1)
    n_params = cfg.num_params()
    step.collective_bytes = (
        {("all_reduce", "float32"): 2 * n_params * 4} if dp > 1 else {})
    step.opt_state_bytes = 2 * n_params * 4 + 8
    return step


def make_eval_step(cfg: TransformerConfig, mesh: Mesh, state_sh: TrainState,
                   compute_dtype=jnp.bfloat16, sp_axis: Optional[str] = None):
    pctx = ParallelContext(mesh=mesh, sp_axis=sp_axis,
                           batch_axes=shard_rules.BATCH_AXES)
    batch_sh = NamedSharding(mesh, shard_rules.batch_spec())

    def eval_fn(params, batch):
        loss, metrics = transformer.causal_lm_loss(params, batch, cfg=cfg,
                                                   pctx=pctx,
                                                   compute_dtype=compute_dtype)
        return metrics

    return jax.jit(eval_fn, in_shardings=(state_sh.params, {"tokens": batch_sh}),
                   out_shardings=None)
