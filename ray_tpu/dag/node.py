"""DAG nodes: lazy ``.bind()`` graphs over tasks and actors.

Reference: ``python/ray/dag/dag_node.py`` (DAGNode ABC + execute),
``function_node.py``, ``class_node.py``, ``input_node.py``.  Semantics kept:
``bind`` captures args (which may be other nodes), ``execute`` resolves the
graph bottom-up, one task/actor call per node, sharing results across fan-out
(a node consumed twice runs once).
"""

from __future__ import annotations

import uuid
from typing import Any, Dict, List, Optional, Tuple


class DAGNode:
    """A lazily-bound call in the graph."""

    def __init__(self, args: Tuple, kwargs: Dict[str, Any]):
        self._bound_args = args
        self._bound_kwargs = kwargs
        self._uuid = uuid.uuid4().hex

    # -- graph walking ----------------------------------------------------

    def _upstream(self) -> List["DAGNode"]:
        out = []

        def scan(v):
            if isinstance(v, DAGNode):
                out.append(v)
            elif isinstance(v, (list, tuple)):
                for x in v:
                    scan(x)
            elif isinstance(v, dict):
                for x in v.values():
                    scan(x)

        for a in self._bound_args:
            scan(a)
        for v in self._bound_kwargs.values():
            scan(v)
        return out

    def _resolve_args(self, memo: Dict[str, Any]):
        def sub(v):
            if isinstance(v, DAGNode):
                return memo[v._uuid]
            if isinstance(v, list):
                return [sub(x) for x in v]
            if isinstance(v, tuple):
                return tuple(sub(x) for x in v)
            if isinstance(v, dict):
                return {k: sub(x) for k, x in v.items()}
            return v

        args = tuple(sub(a) for a in self._bound_args)
        kwargs = {k: sub(v) for k, v in self._bound_kwargs.items()}
        return args, kwargs

    def _apply(self, args, kwargs, memo: Dict[str, Any]):
        raise NotImplementedError

    # -- execution --------------------------------------------------------

    def execute(self, *input_args, **input_kwargs):
        """Walk the DAG; returns this node's result ref (``ray_tpu.get``
        it) or value.  Each node executes exactly once per call."""
        memo: Dict[str, Any] = {}
        order: List[DAGNode] = []
        seen = set()

        def topo(n: DAGNode):
            if n._uuid in seen:
                return
            seen.add(n._uuid)
            for up in n._upstream():
                topo(up)
            order.append(n)

        topo(self)
        for node in order:
            if isinstance(node, InputNode):
                if len(input_args) == 1 and not input_kwargs:
                    memo[node._uuid] = input_args[0]
                else:
                    memo[node._uuid] = (input_args, input_kwargs)
                continue
            args, kwargs = node._resolve_args(memo)
            memo[node._uuid] = node._apply(args, kwargs, memo)
        return memo[self._uuid]


class InputNode(DAGNode):
    """The runtime input placeholder (reference: input_node.py).  Usable as
    a context manager for parity with the reference's idiom::

        with InputNode() as inp:
            dag = f.bind(inp)
    """

    def __init__(self):
        super().__init__((), {})

    def __enter__(self) -> "InputNode":
        return self

    def __exit__(self, *exc):
        return False


class FunctionNode(DAGNode):
    """``remote_fn.bind(...)`` (reference: function_node.py)."""

    def __init__(self, remote_fn, args, kwargs):
        super().__init__(args, kwargs)
        self._fn = remote_fn

    def _apply(self, args, kwargs, memo):
        return self._fn.remote(*args, **kwargs)


class ClassNode(DAGNode):
    """``ActorClass.bind(...)`` — the actor is created at execute time; its
    methods are bound via attribute access (reference: class_node.py)."""

    def __init__(self, actor_cls, args, kwargs):
        super().__init__(args, kwargs)
        self._cls = actor_cls

    def _apply(self, args, kwargs, memo):
        return self._cls.remote(*args, **kwargs)

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _MethodStub(self, name)


class _MethodStub:
    def __init__(self, class_node: ClassNode, method: str):
        self._class_node = class_node
        self._method = method

    def bind(self, *args, **kwargs) -> "ClassMethodNode":
        return ClassMethodNode(self._class_node, self._method, args, kwargs)


class ClassMethodNode(DAGNode):
    def __init__(self, class_node: ClassNode, method: str, args, kwargs):
        super().__init__(args, kwargs)
        self._class_node = class_node
        self._method = method

    def _upstream(self):
        return [self._class_node] + super()._upstream()

    def _apply(self, args, kwargs, memo):
        actor = memo[self._class_node._uuid]
        return getattr(actor, self._method).remote(*args, **kwargs)
