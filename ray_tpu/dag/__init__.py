"""ray_tpu.dag — lazy task/actor call graphs.

Reference: ``python/ray/dag/`` (``dag_node.py``, ``function_node.py``,
``class_node.py``, ``input_node.py``) — ``fn.bind(...)`` builds a DAG instead
of executing; ``dag.execute(input)`` walks it, submitting each node as a task
once its upstream refs exist.  The serve deployment-graph and workflow
libraries build on this.
"""

from .node import (ClassMethodNode, ClassNode, DAGNode, FunctionNode,
                   InputNode)

__all__ = ["DAGNode", "FunctionNode", "ClassNode", "ClassMethodNode",
           "InputNode"]
