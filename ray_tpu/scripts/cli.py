"""The ``raytpu`` CLI (reference: ``python/ray/scripts/scripts.py`` —
``ray start`` :542, ``ray status`` :1963, ``ray submit`` :1550, plus the
state-API ``ray list`` family).

Invoke as ``python -m ray_tpu.scripts.cli <cmd>`` or via the ``raytpu``
wrapper at the repo root.  argparse instead of click (not adding deps).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time

ADDRESS_FILE = "/tmp/raytpu/head.json"


def _read_head() -> dict:
    try:
        with open(ADDRESS_FILE) as f:
            return json.load(f)
    except OSError:
        raise SystemExit("no running head found (raytpu start --head first?)")


def _connect():
    import ray_tpu

    if ray_tpu.is_initialized():
        return ray_tpu  # in-process runtime (tests drive cmd_* directly)
    head = _read_head()
    os.environ["RAYTPU_GCS_ADDRESS"] = head["gcs_address"]
    ray_tpu.init(address="auto", ignore_reinit_error=True)
    return ray_tpu


# ------------------------------------------------------------------ start

def cmd_start(args):
    if args.head:
        if os.path.exists(ADDRESS_FILE):
            try:
                head = json.load(open(ADDRESS_FILE))
                os.kill(head["pid"], 0)
                raise SystemExit(f"head already running (pid {head['pid']}); "
                                 f"raytpu stop first")
            except (OSError, KeyError, json.JSONDecodeError):
                pass  # stale file
        cmd = [sys.executable, "-m", "ray_tpu.core.head_main"]
    else:
        if not args.address:
            raise SystemExit("--address required for non-head nodes")
        cmd = [sys.executable, "-m", "ray_tpu.core.node_main",
               "--gcs-address", args.address]
    if args.num_cpus is not None:
        cmd += ["--num-cpus", str(args.num_cpus)]
    if args.num_tpus is not None:
        cmd += ["--num-tpus", str(args.num_tpus)]
    if args.resources:
        cmd += ["--resources", args.resources]
    if args.labels:
        cmd += ["--labels", args.labels]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.DEVNULL,
                            start_new_session=True)
    line = proc.stdout.readline().decode()
    if not line:
        raise SystemExit("node process failed to start")
    info = json.loads(line)
    if args.head:
        print(f"head started: gcs={info['gcs_address']} pid={proc.pid}")
        print(f"join with: raytpu start --address={info['gcs_address']}")
    else:
        print(f"node started: {info['node_id'][:12]} pid={proc.pid}")


def cmd_stop(_args):
    head = _read_head()
    try:
        os.kill(head["pid"], signal.SIGTERM)
        print(f"stopped head (pid {head['pid']})")
    except OSError as e:
        print(f"head pid {head['pid']}: {e}")
    # node agents registered via `raytpu start --address` are independent
    # processes; kill by module name
    subprocess.run(["pkill", "-f", "ray_tpu.core.node_main"], check=False)
    try:
        os.unlink(ADDRESS_FILE)
    except OSError:
        pass


# --------------------------------------------------------------- launcher

def _launcher(args):
    from ray_tpu.autoscaler.launcher import ClusterLauncher, load_config
    if not args.config:
        raise SystemExit("--config CONFIG.yaml required")
    return ClusterLauncher(load_config(args.config),
                           state_path=getattr(args, "state", None))


def cmd_up(args):
    """Summon the fleet described by a launcher YAML (queued-resource
    creates via the GCE TPU provider; idempotent against live nodes)."""
    launcher = _launcher(args)
    created = launcher.up(wait=args.wait)
    if not created:
        print(f"cluster {launcher.cluster_name!r}: already at configured "
              f"node counts")
    for pid in created:
        nt = launcher.provider._nodes.get(pid, {}).get("node_type")
        print(f"created {pid} ({nt})")
    print(f"state -> {launcher.state_path}")


def cmd_down(args):
    launcher = _launcher(args)
    pids = launcher.down()
    for pid in pids:
        print(f"terminated {pid}")
    print(f"cluster {launcher.cluster_name!r}: {len(pids)} node(s) torn down")


def _print_launcher_status(args):
    launcher = _launcher(args)
    rows = launcher.status()
    if not rows:
        print(f"cluster {launcher.cluster_name!r}: no tracked nodes")
        return
    print(f"{'PROVIDER_ID':<16} {'NODE_TYPE':<16} {'STATE':<24} NODE")
    for r in rows:
        print(f"{r['provider_id']:<16} {str(r['node_type']):<16} "
              f"{str(r['state']):<24} {r.get('raytpu_node_id') or '-'}")


# ----------------------------------------------------------------- status

def cmd_status(args):
    if getattr(args, "config", None):
        # launcher mode: fleet/QR states from the provider, no cluster
        # connection needed (the fleet may still be provisioning)
        _print_launcher_status(args)
        return
    rt = _connect()
    nodes = rt.nodes()
    total = rt.cluster_resources()
    avail = rt.available_resources()
    print(f"{len(nodes)} node(s)")
    for n in nodes:
        print(f"  {n['NodeID'][:12]}  alive={n['Alive']}  {n['Resources']}")
    print("resources:")
    for k in sorted(total):
        print(f"  {k}: {avail.get(k, 0):.1f}/{total[k]:.1f} available")
    _print_node_telemetry(rt, nodes)
    _print_stage_summary()
    _print_sched_summary()


def _print_sched_summary():
    """Pending-reason rollup + control-plane saturation line: which typed
    reason the non-running tasks are waiting on, how busy the GCS loop is
    and which handlers are eating it (the explain plane's status view)."""
    from ray_tpu.util import state as state_api

    try:
        summary = state_api.summarize_tasks()
        stats = state_api.sched_stats()
    except Exception:
        return
    reasons = {k: v for k, v in
               (summary.get("pending_reasons") or {}).items() if v}
    if reasons:
        print("pending tasks by reason:")
        for reason, n in sorted(reasons.items(), key=lambda kv: -kv[1]):
            print(f"  {reason:<18} {n}")
    busy = stats.get("loop_busy_fraction")
    parts = []
    if busy is not None:
        parts.append(f"gcs router busy={busy * 100:.0f}%")
    shard_busy = stats.get("shard_busy_fractions") or {}
    if shard_busy:
        # horizontal control plane: there is no longer ONE GCS loop —
        # show each shard process's busy fraction next to the router's
        parts.append("shards " + " ".join(
            f"{name.split(':', 1)[1]}={(b or 0) * 100:.0f}%"
            for name, b in sorted(shard_busy.items())))
    top = [(m, s) for m, s in (stats.get("top_handlers") or [])[:3] if s]
    if top:
        parts.append("top handlers: " + ", ".join(
            f"{m}={s:.2f}s" for m, s in top))
    if stats.get("task_events_dropped"):
        parts.append(f"events_dropped={stats['task_events_dropped']}")
    if parts:
        print("control plane: " + "  ".join(parts))
    sp = stats.get("submit_plane") or {}
    if sp:
        # submission-plane rollup: exact counters behind the sampled event
        # stream, plus free-list hit rate and whether the C encoder is live
        emitted = sum(c.get("events_emitted") or 0 for c in sp.values())
        sampled = sum(c.get("events_sampled") or 0 for c in sp.values())
        hits = sum(c.get("freelist_hits") or 0 for c in sp.values())
        misses = sum(c.get("freelist_misses") or 0 for c in sp.values())
        native = any(c.get("native_loaded") for c in sp.values())
        enabled = any(c.get("native_enabled") for c in sp.values())
        alloc = hits + misses
        line = (f"submit plane: events emitted={emitted} sampled={sampled}"
                f"  freelist hit-rate="
                + (f"{hits / alloc * 100:.0f}%" if alloc else "n/a")
                + f"  native encoder="
                + ("on" if (native and enabled) else
                   "fallback" if enabled else "off"))
        print(line)


def _print_node_telemetry(rt, nodes):
    """Per-node runtime telemetry (live worker/queue/store occupancy, from
    each agent's node_info — same data the gauges on /metrics export).
    Probes run concurrently so K wedged agents cost ONE timeout of wall
    clock, not K (same pattern as the dashboard's telemetry handler)."""
    import asyncio

    from ray_tpu.core.core_worker import global_worker
    from ray_tpu.core.rpc import run_async

    w = global_worker()
    alive = [n for n in nodes if n.get("Alive") and n.get("AgentAddress")]

    async def probe_all():
        async def one(n):
            try:
                return await asyncio.wait_for(
                    w.agent_clients.get(n["AgentAddress"]).call(
                        "node_info", _timeout=5.0), 8)
            except Exception:
                return None
        return await asyncio.gather(*[one(n) for n in alive])

    try:
        infos = run_async(probe_all(), timeout=15)
    except Exception:
        return
    printed_header = False
    for info in infos:
        if info is None:
            continue
        if not printed_header:
            print("telemetry:")
            printed_header = True
        st = info.get("store", {})
        busy = info.get("loop_busy_fraction")
        bp = info.get("backpressure_rejects") or {}
        line = (f"  {info['node_id'][:12]}  workers={info['num_workers']} "
                f"queue={info.get('queue_len', 0)} "
                f"store={_fmt_bytes(st.get('used', 0))}"
                f"/{_fmt_bytes(st.get('capacity', 0))} "
                f"pinned={st.get('num_pinned', 0)} "
                f"oom_kills={info.get('oom_kills', 0)}")
        if busy is not None:
            line += f" busy={busy * 100:.0f}%"
        if bp:
            line += " bp_rejects=" + ",".join(
                f"{k}:{v}" for k, v in sorted(bp.items()))
        if info.get("draining"):
            line += " DRAINING"
        print(line)


def _print_stage_summary():
    """Task-stage latency percentiles (summarize_tasks' stage_latency)."""
    from ray_tpu.util import state as state_api

    try:
        summary = state_api.summarize_tasks()
    except Exception:
        return
    stages = {k: v for k, v in (summary.get("stage_latency") or {}).items()
              if v}
    if not stages:
        return
    print(f"task stages ({summary.get('total_tasks', 0)} tasks):")
    print(f"  {'STAGE':<12} {'COUNT':>6} {'P50':>9} {'P90':>9} "
          f"{'P99':>9} {'MAX':>9}")
    for stage, s in stages.items():
        print(f"  {stage:<12} {s['count']:>6} {s['p50'] * 1e3:>8.1f}ms "
              f"{s['p90'] * 1e3:>8.1f}ms {s['p99'] * 1e3:>8.1f}ms "
              f"{s['max'] * 1e3:>8.1f}ms")


def cmd_explain(args):
    """``raytpu explain <task|actor|pg id>`` — the full decision trail:
    current state, typed pending-reason transitions with timestamps, and
    every scheduler decision record that mentions the id (candidates,
    per-node rejection causes, outcome).  The stuck-task debugging
    entry point (see README "Debugging a stuck task")."""
    _connect()
    from ray_tpu.util import state as state_api

    report = state_api.explain(args.id)
    if report.get("kind") is None:
        # not a task/actor/pg: try the object-plane flight recorder (the
        # explain CLI covers every id kind the runtime can explain)
        obj = state_api.explain_object(args.id)
        if obj.get("kind") is not None:
            if getattr(args, "json", False):
                print(json.dumps(obj, indent=2, default=str))
            else:
                _render_object_explain(obj)
            return
    if getattr(args, "json", False):
        print(json.dumps(report, indent=2, default=str))
        return
    if report.get("kind") is None:
        print(f"no task/actor/pg/object with id {args.id!r} "
              "in the event window")
        return
    kind = report["kind"]
    name = report.get("name") or (report.get("actor") or {}).get(
        "class_name") or (report.get("pg") or {}).get("name") or ""
    head = f"{kind} {name} ({args.id[:16]}) — {report.get('state', '?')}"
    if report.get("pending_reason"):
        head += f" [{report['pending_reason']}]"
    print(head)
    if kind == "actor" and report.get("actor"):
        a = report["actor"]
        if a.get("node_id"):
            print(f"  node={a['node_id'][:12]} restarts_left="
                  f"{a.get('restarts_left')}")
        if a.get("death_cause"):
            print(f"  death_cause: {a['death_cause']}")
    if kind == "pg" and report.get("pg"):
        p = report["pg"]
        print(f"  strategy={p.get('strategy')} bundles="
              f"{len(p.get('bundles') or [])}")
    events = [e for e in (report.get("events") or [])
              if e.get("state") not in ("STAGES", "SPAN")]
    if events:
        t0 = events[0].get("ts", 0.0)
        print("event trail:")
        for ev in events:
            line = (f"  +{ev.get('ts', 0.0) - t0:8.3f}s  "
                    f"{ev.get('state', '?'):<10}")
            if ev.get("reason"):
                line += f" {ev['reason']}"
            for k in ("node", "node_id", "actor", "error"):
                if ev.get(k):
                    line += f" {k}={str(ev[k])[:40]}"
            print(line)
    decisions = report.get("decisions") or []
    print(f"decisions ({len(decisions)}):")
    for rec in decisions[-20:]:
        ts = time.strftime("%H:%M:%S", time.localtime(rec.get("ts", 0)))
        line = f"  {ts}  {rec.get('outcome', '?'):<12}"
        if rec.get("node"):
            line += f" node={str(rec['node'])[:12]}"
        if rec.get("candidates") is not None:
            line += f" candidates={rec['candidates']}"
        rejected = rec.get("rejected") or {}
        if rejected:
            line += " rejected: " + ", ".join(
                f"{nid[:12]}={cause}" for nid, cause in
                list(rejected.items())[:6])
        if rec.get("reason"):
            line += f" -> {rec['reason']}"
        if rec.get("task_count") is not None:
            line += f" (queue={rec['task_count']})"
        print(line)
    if not decisions and not events:
        print("  (no records — was the id right, and did it age out?)")


def _render_object_explain(report):
    """``raytpu explain <object_id>`` — the object's lifecycle trail:
    every flight-recorder transition (created/sealed/spilled/restored/
    transferred/re-homed/freed) with node, tier and size history.  The
    leaked/slow-object debugging entry point (see README "Debugging a
    leaked / slow object")."""
    head = f"object ({report['id'][:16]}) — {report.get('state', '?')}"
    if report.get("size") is not None:
        head += f"  {_fmt_bytes(report['size'])}"
    print(head)
    if report.get("owner"):
        print(f"  owner={report['owner']}")
    if report.get("nodes"):
        print(f"  nodes seen: {', '.join(report['nodes'])}")
    if report.get("tiers"):
        print(f"  spill tiers touched: {', '.join(report['tiers'])}")
    events = report.get("events") or []
    if not events:
        print("  (no events — was the id right, and did it age out?)")
        return
    t0 = events[0].get("ts", 0.0)
    print("lifecycle trail:")
    for ev in events:
        line = (f"  +{ev.get('ts', 0.0) - t0:8.3f}s  "
                f"{ev.get('event', '?'):<14}")
        for k in ("node", "tier", "size", "source", "sources", "to",
                  "holder", "pins", "uri", "zero_copy"):
            if ev.get(k) is not None:
                v = ev[k]
                if k == "size":
                    v = _fmt_bytes(v)
                line += f" {k}={str(v)[:48]}"
        print(line)


def cmd_transfers(args):
    """``raytpu transfers`` — completed-pull flight records from every
    node's bounded ring: per-source stripe stats, steal/retry counts and
    relay fraction per chunked pull, plus zero-copy proxy attaches."""
    _connect()
    from ray_tpu.util import state as state_api

    rows = state_api.transfers(limit=args.limit)
    if args.json:
        print(json.dumps(rows, indent=2, default=str))
        return
    if not rows:
        print("no recorded transfers (ring empty — pulls happen on "
              "cross-node reads; is object_metrics_enabled on?)")
        return
    print(f"{'OBJECT_ID':<16} {'KIND':<8} {'STATUS':<9} {'BYTES':>10} "
          f"{'DUR':>8} {'SRCS':>4} {'STEAL':>5} {'RETRY':>5} {'RELAY':>6}  "
          f"NODE")
    for r in rows:
        srcs = len(r.get("sources_used", []) or ([r["source"]]
                                                 if r.get("source") else []))
        relay = r.get("relay_fraction")
        print(f"{r['object_id'][:14]:<16} {r.get('kind', '?'):<8} "
              f"{r.get('status', '?'):<9} {_fmt_bytes(r.get('bytes')):>10} "
              f"{r.get('duration_s', 0):>7.3f}s {srcs:>4} "
              f"{r.get('stolen', 0):>5} {r.get('retried', 0):>5} "
              f"{relay if relay is not None else '-':>6}  "
              f"{r.get('node', '?')}")
        for addr, src in sorted((r.get("per_source") or {}).items()):
            print(f"    {addr:<28} chunks={src.get('chunks', 0):<5} "
                  f"bytes={_fmt_bytes(src.get('bytes', 0)):<10} "
                  f"failures={src.get('failures', 0)}"
                  + (" partial" if src.get("partial") else "")
                  + (" DEAD" if src.get("dead") else ""))


def cmd_list(args):
    rt = _connect()
    from ray_tpu.util import state as state_api

    kind = args.kind
    fns = {"actors": state_api.list_actors, "tasks": state_api.list_tasks,
           "nodes": state_api.list_nodes, "objects": state_api.list_objects,
           "memory": state_api.list_memory,
           "placement-groups": state_api.list_placement_groups}
    if kind not in fns:
        raise SystemExit(f"unknown kind {kind}; one of {sorted(fns)}")
    rows = fns[kind]()
    print(json.dumps(rows, indent=2, default=str))


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024
    return f"{n:.1f}GiB"


def cmd_memory(args):
    """Per-object store report (reference: the ``ray memory`` debug command):
    refcounts, sizes, pin state, and which node holds each copy."""
    _connect()
    from ray_tpu.util import state as state_api

    if getattr(args, "leaks", False):
        leaks = state_api.memory_leaks(pin_ttl_s=args.pin_ttl)
        if args.json:
            print(json.dumps(leaks, indent=2, default=str))
            return
        if not leaks:
            print("no leak suspects")
            return
        print(f"{len(leaks)} leak suspect(s):")
        for r in leaks:
            line = (f"  {r.get('kind', '?'):<14} {r['object_id'][:16]:<18} "
                    f"node={r.get('node', '?')}")
            for k in ("holder", "owner", "age_s", "pins", "accounted",
                      "size"):
                if r.get(k) is not None:
                    v = _fmt_bytes(r[k]) if k == "size" else r[k]
                    line += f" {k}={v}"
            refs = r.get("refs")
            if refs:
                line += (f" refs(l/s/b)={refs['local']}/{refs['submitted']}"
                         f"/{refs['borrowers']}")
            print(line)
        return

    report = state_api.memory_summary()
    if args.json:
        print(json.dumps(report, indent=2, default=str))
        return
    for nid, st in report["nodes"].items():
        line = (f"node {nid[:12]} ({st.get('address', '?')}): "
                f"{_fmt_bytes(st['used'])}/{_fmt_bytes(st['capacity'])} used, "
                f"{st['num_objects']} objects, {st['num_proxies']} proxies, "
                f"{st['num_pinned']} pinned, "
                f"{st['num_deferred_frees']} deferred frees")
        if st.get("largest_free_block"):
            line += f", largest free {_fmt_bytes(st['largest_free_block'])}"
        if st.get("frag_fraction"):
            line += f", frag {st['frag_fraction']:.0%}"
        print(line)
        # spill tiers: external bytes/objects used to be invisible here
        # (only the cumulative spill counter saw them)
        if st.get("num_spilled_local") or st.get("num_spilled_external"):
            print(f"  spilled: local {st.get('num_spilled_local', 0)} obj "
                  f"({_fmt_bytes(st.get('spilled_local_bytes', 0))}), "
                  f"external {st.get('num_spilled_external', 0)} obj "
                  f"({_fmt_bytes(st.get('spilled_external_bytes', 0))})")
    rows = report["objects"]
    if not rows:
        print("no tracked objects")
        return
    print(f"{'OBJECT_ID':<20} {'KIND':<8} {'SIZE':>10} {'PINS':>4} "
          f"{'REFS(l/s/b)':>12}  LOCATION")
    for r in rows:
        refs = r.get("refs")
        refstr = (f"{refs['local']}/{refs['submitted']}/{refs['borrowers']}"
                  if refs else "-")
        loc = r.get("node_id", "")[:12] or "driver"
        if r.get("freed"):
            loc += " (freed:deferred)"
        print(f"{r['object_id'][:18]:<20} {r.get('kind', '?'):<8} "
              f"{_fmt_bytes(r.get('size')):>10} {r.get('pinned', 0):>4} "
              f"{refstr:>12}  {loc}")


# -------------------------------------------------------------------- top

def _scrape_cluster_frame(rt, store):
    """One scrape of every alive node's /metrics into the history store
    (the same store/parse the dashboard head feeds, so the terminal view
    and the REST surface agree on what a sample means).  Nodes scrape
    CONCURRENTLY — K unreachable nodes must cost one timeout of wall
    clock per frame, not K (the head's async loop has the same shape)."""
    from concurrent.futures import ThreadPoolExecutor

    from ray_tpu.dashboard import history as hist

    rows = rt.nodes()
    alive, targets = [], []
    for n in rows:
        if not n.get("Alive"):
            continue
        nid = (n.get("NodeID") or "")[:12]
        alive.append((nid, n))
        port = (n.get("Labels") or {}).get("metrics_port")
        if not port:
            store.record_error(nid, "no metrics_port advertised")
            continue
        host = (n.get("AgentAddress") or "127.0.0.1:0").rsplit(":", 1)[0]
        targets.append((nid, host, port))
    alive_ids = {nid for nid, _n in alive}
    for known in store.nodes():
        if known not in alive_ids:  # dead nodes drop, not freeze
            store.forget(known)

    def scrape(target):
        nid, host, port = target
        try:
            samples, counters = hist.scrape_node_sync(host, port, timeout=5.0)
            store.add_sample(nid, samples, counters)
        except Exception as e:  # noqa: BLE001 — rendered in the table
            store.record_error(nid, f"{type(e).__name__}: {e}")

    if targets:
        with ThreadPoolExecutor(max_workers=min(8, len(targets))) as pool:
            list(pool.map(scrape, targets))
    return alive


def _sum_rate(store, nid, name):
    """Latest per-second rate summed across all series of one counter."""
    rates = store.rates(nid, prefix=name)
    total, found = 0.0, False
    for key, pts in rates.items():
        if key.split("{", 1)[0] == name and pts:
            total += pts[-1][1]
            found = True
    return total if found else None


def _hist_mean_rate(store, nid, name):
    """Mean value per observation over the last tick, from a histogram's
    _sum/_count rates (e.g. average TTFT or step time right now)."""
    dsum = _sum_rate(store, nid, name + "_sum")
    dcount = _sum_rate(store, nid, name + "_count")
    if dsum is None or not dcount:
        return None
    return dsum / dcount


def _render_top(store, alive_nodes) -> str:
    """The `raytpu top` frame: per-node cpu/shm/lease-queue/loop-lag next
    to the train (step-time/MFU/goodput) and serve (req/s, TTFT) rollups
    derived from the same scrape."""
    from ray_tpu.dashboard.history import find_one, find_samples

    _ts, latest = store.latest()
    lines = [f"raytpu top — {len(alive_nodes)} node(s) @ "
             f"{time.strftime('%H:%M:%S')}",
             f"{'NODE':<14} {'CPU':>9} {'SHM':>19} {'LEASEQ':>6} "
             f"{'LOOPLAG':>8} {'BUSY':>5} {'WORKERS':>7}"]
    for nid, _row in alive_nodes:
        s = latest.get(nid)
        if not s or "error" in s:
            err = (s or {}).get("error", "no sample yet")
            lines.append(f"{nid:<14} <unreachable: {err}>")
            continue
        cpu_t = find_one(s, "raytpu_resource_total", node=nid,
                         resource="CPU") or 0.0
        cpu_a = find_one(s, "raytpu_resource_available", node=nid,
                         resource="CPU")
        cpu = (f"{cpu_t - cpu_a:.1f}/{cpu_t:.0f}"
               if cpu_a is not None else "?")
        used = find_one(s, "raytpu_object_store_bytes", node=nid)
        cap = find_one(s, "raytpu_object_store_capacity_bytes", node=nid)
        shm = (f"{_fmt_bytes(used)}/{_fmt_bytes(cap)}"
               if used is not None else "?")
        leaseq = find_one(s, "raytpu_node_lease_queue_len", node=nid)
        lag = find_samples(s, "raytpu_event_loop_lag_seconds")
        lags = f"{max(lag) * 1e3:.0f}ms" if lag else "-"
        # saturation plane: worst per-process event-loop busy fraction
        # reported by this node's registry (gcs/agent/driver/workers)
        busy = find_samples(s, "raytpu_loop_busy_fraction")
        busys = f"{max(busy) * 100:.0f}%" if busy else "-"
        nworkers = find_one(s, "raytpu_node_workers", node=nid)
        lines.append(f"{nid:<14} {cpu:>9} {shm:>19} "
                     f"{int(leaseq) if leaseq is not None else '-':>6} "
                     f"{lags:>8} {busys:>5} "
                     f"{int(nworkers) if nworkers is not None else '-':>7}")

    # train rollup: raytpu_train_* series land on the agent of whichever
    # node the train workers run on — aggregate across all nodes
    mfus, goodputs, steps_s, step_mean, compile_s = [], [], 0.0, [], []
    opt_bytes, wire_rate = [], 0.0
    any_train = False
    for nid, _row in alive_nodes:
        s = latest.get(nid) or {}
        if "error" in s:
            continue
        mfus += find_samples(s, "raytpu_train_mfu")
        goodputs += find_samples(s, "raytpu_train_goodput_fraction")
        opt_bytes += find_samples(s, "raytpu_train_opt_state_bytes")
        if find_samples(s, "raytpu_train_steps_total"):
            any_train = True
        r = _sum_rate(store, nid, "raytpu_train_steps_total")
        if r:
            steps_s += r
        w = _sum_rate(store, nid, "raytpu_train_collective_bytes_total")
        if w:
            wire_rate += w
        m = _hist_mean_rate(store, nid, "raytpu_train_step_seconds")
        if m is not None:
            step_mean.append(m)
        compile_s += find_samples(s, "raytpu_train_compile_seconds_sum")
    if any_train or mfus:
        def avg(xs):
            return sum(xs) / len(xs) if xs else None
        mfu, gp = avg(mfus), avg(goodputs)
        st = avg(step_mean)
        lines.append(
            "TRAIN  "
            + f"steps/s={steps_s:.2f}  "
            + (f"step={st * 1e3:.1f}ms  " if st is not None else "")
            + (f"mfu={mfu:.3f}  " if mfu is not None else "mfu=-  ")
            + (f"goodput={gp:.3f}  " if gp is not None else "goodput=-  ")
            + (f"wire={wire_rate / 1e6:.1f}MB/s  " if wire_rate else "")
            + (f"opt={sum(opt_bytes) / 1e6:.0f}MB  " if opt_bytes else "")
            + (f"compile={max(compile_s):.1f}s" if compile_s else ""))
    else:
        lines.append("TRAIN  (no raytpu_train_* series; is a run live and "
                     "train_metrics_enabled on?)")

    # object-plane rollup: copy amplification (bytes_copied/bytes_moved
    # over the raytpu_object_bytes_total ledger — delegated to the
    # canonical object_explain.copy_amplification so the weighting lives
    # in ONE place), worst arena fragmentation, spill-tier residency and
    # leak suspects
    import re as _re

    from ray_tpu.core.object_explain import copy_amplification

    frag, spill_b, leak_n = [], 0.0, 0.0
    ledger: dict = {}
    name = "raytpu_object_bytes_total"
    for nid, _row in alive_nodes:
        s = latest.get(nid) or {}
        if "error" in s:
            continue
        frag += find_samples(s, "raytpu_mem_arena_frag_fraction")
        spill_b += sum(find_samples(s, "raytpu_mem_spill_bytes"))
        leak_n += sum(find_samples(s, "raytpu_mem_leak_suspects"))
        for key, val in s.items():
            if key != name and not key.startswith(name + "{"):
                continue
            tags = tuple(sorted(
                (m.group(1), m.group(2)) for m in
                _re.finditer(r'(\w+)="([^"]*)"', key)
                if m.group(1) in ("path", "copies")))
            ledger[tags] = ledger.get(tags, 0.0) + val
    if ledger or frag:
        amp = copy_amplification(ledger)
        lines.append(
            "OBJECT "
            + (f"copy_amp={amp:.2f}  " if amp is not None
               else "copy_amp=-  ")
            + (f"arena_frag={max(frag):.0%}  " if frag else "arena_frag=-  ")
            + f"spilled={_fmt_bytes(spill_b)}  "
            + f"leak_suspects={int(leak_n)}")
    else:
        lines.append("OBJECT (no raytpu_object_* series; is "
                     "object_metrics_enabled on?)")

    # serve rollup
    req_s, ttft = 0.0, []
    any_serve = False
    for nid, _row in alive_nodes:
        s = latest.get(nid) or {}
        if "error" in s:
            continue
        if find_samples(s, "raytpu_serve_requests_total"):
            any_serve = True
        r = _sum_rate(store, nid, "raytpu_serve_requests_total")
        if r:
            req_s += r
        t = _hist_mean_rate(store, nid, "raytpu_serve_ttft_seconds")
        if t is not None:
            ttft.append(t)
    if any_serve:
        t = (sum(ttft) / len(ttft)) if ttft else None
        lines.append("SERVE  "
                     + f"req/s={req_s:.1f}  "
                     + (f"ttft_avg={t * 1e3:.1f}ms" if t is not None
                        else "ttft_avg=-"))

    # control-plane rollup: the BUSY column above shows each NODE's worst
    # loop; with the horizontal control plane the GCS is router + N shard
    # processes, whose busy fractions come from sched_stats, not a node
    # scrape — one line names each loop so "which control-plane process
    # is pegged" is answerable from top.
    try:
        from ray_tpu.util import state as _state_api
        stats = _state_api.sched_stats()
    except Exception:
        stats = None
    if stats:
        parts = []
        b = stats.get("loop_busy_fraction")
        if b is not None:
            parts.append(f"router={b * 100:.0f}%")
        for name, b in sorted((stats.get("shard_busy_fractions")
                               or {}).items()):
            parts.append(f"{name.split(':', 1)[1]}={(b or 0) * 100:.0f}%")
        if parts:
            lines.append("CONTROL  busy: " + "  ".join(parts))

    # health plane: the deduplicated active-alert set from the GCS ring
    # (GCS-side + dashboard-head detectors) — top answers "is anything
    # wrong" without a second command
    try:
        from ray_tpu.util import state as _state_api
        h = _state_api.health()
    except Exception:
        h = None
    if h is not None:
        active = h.get("active") or []
        if active:
            shown = ", ".join(
                f"{a.get('rule')}({a.get('scope')})" for a in active[:4])
            more = f" +{len(active) - 4} more" if len(active) > 4 else ""
            lines.append(f"ALERTS {len(active)} active: {shown}{more}"
                         "  (raytpu doctor for evidence)")
        elif h.get("enabled"):
            lines.append("ALERTS none")
        else:
            lines.append("ALERTS (health_metrics_enabled off; "
                         "raytpu doctor still evaluates on demand)")
    return "\n".join(lines)


def cmd_top(args):
    """Live cluster view (reference: `ray status` + the dashboard metrics
    pages, as a terminal refresh loop): per-node cpu/shm/lease-queue/
    loop-lag columns plus the train (step/MFU/goodput) and serve
    (req/s, TTFT) rollups, all derived from the agents' /metrics.
    ``--once`` prints one frame (two scrapes, so rates exist) and
    exits."""
    rt = _connect()
    from ray_tpu.dashboard.history import MetricsHistory

    interval = max(args.interval, 0.2)
    store = MetricsHistory(window_s=max(60.0, interval * 30),
                           period_s=interval)
    alive = _scrape_cluster_frame(rt, store)
    if args.once:
        time.sleep(interval)
        alive = _scrape_cluster_frame(rt, store)
        print(_render_top(store, alive))
        return
    try:
        while True:
            time.sleep(interval)
            alive = _scrape_cluster_frame(rt, store)
            # clear screen + home, then the frame
            print("\x1b[2J\x1b[H" + _render_top(store, alive), flush=True)
    except KeyboardInterrupt:
        pass


# ----------------------------------------------------------- health plane

def _doctor_snapshot(rt):
    """The one-shot evidence snapshot behind ``raytpu doctor``: two
    metric frames (so rates exist), the serve SLO signal, sched_stats
    (events shed, hot handlers), and the on-demand leak sweep — the same
    surfaces the background detectors watch, pulled fresh."""
    from ray_tpu.dashboard.history import MetricsHistory
    from ray_tpu.util import health as health_plane
    from ray_tpu.util import state as state_api

    store = MetricsHistory(window_s=60.0, period_s=1.0)
    _scrape_cluster_frame(rt, store)
    time.sleep(1.0)
    _scrape_cluster_frame(rt, store)
    try:
        stats = state_api.sched_stats()
    except Exception:
        stats = {}
    try:
        from ray_tpu import serve as serve_api
        slo = serve_api.slo_signal()
    except Exception:
        slo = {}
    # elastic evidence: active drain notices + in-progress resizes feed
    # the NODE_DRAINING/TRAIN_RESIZING rules AND suppress NODE_FLAPPING
    # for nodes that are dying on purpose
    try:
        notices = state_api.drain_notices()
    except Exception:
        notices = []
    try:
        resizes = state_api.train_resizes()
    except Exception:
        resizes = {}
    snap = health_plane.build_head_snapshot(store, slo=slo,
                                            sched_stats=stats,
                                            drain_notices=notices)
    snap["draining_notices"] = {
        str(n.get("node_id"))[:12]: n.get("remaining_s", 0.0)
        for n in notices if n.get("active")}
    snap["train_resizing"] = resizes.get("in_progress") or {}
    snap["resize_records"] = resizes.get("records") or []
    snap["oneshot"] = True
    leak_rows = []
    try:
        leak_rows = state_api.memory_leaks()
    except Exception:
        pass
    if leak_rows and not snap.get("leak_suspects"):
        # agents answered the sweep but their gauge sample is stale or
        # object telemetry is off — the sweep is the authority
        snap["leak_suspects"] = {"all": len(leak_rows)}
    return snap, leak_rows


def _print_alert(a, t0=None):
    sev = a.get("severity", "?").upper()
    since = a.get("since_ts")
    age = f" for {time.time() - since:.0f}s" if since else ""
    print(f"  [{sev:<8}] {a.get('rule')}  scope={a.get('scope')}{age}")
    ev = a.get("evidence") or {}
    if ev:
        print("             evidence: " + ", ".join(
            f"{k}={v}" for k, v in sorted(ev.items())))
    if a.get("next_step"):
        print(f"             next: {a['next_step']}")


def cmd_doctor(args):
    """``raytpu doctor`` — one-shot cluster diagnosis: every health rule
    evaluated NOW (no hysteresis hold) over a fresh evidence pull,
    merged with the active alerts the background detectors hold, each
    with its evidence snapshot and the explain-surface to run next.
    Works with health_metrics_enabled off (on-demand evaluation is
    requested work, not background CPU)."""
    rt = _connect()
    from ray_tpu.util import health as health_plane
    from ray_tpu.util import state as state_api

    snap, leak_rows = _doctor_snapshot(rt)
    findings = health_plane.evaluate_oneshot(snap)
    try:
        ring = state_api.health(limit=getattr(args, "limit", 20))
    except Exception:
        ring = {}
    # merge: a background alert for the same (rule, scope) wins — its
    # since_ts covers the whole episode, not just this probe
    merged = {(a.get("rule"), a.get("scope")): a
              for a in findings}
    for a in (ring.get("active") or []):
        merged[(a.get("rule"), a.get("scope"))] = a
    alerts = sorted(merged.values(),
                    key=lambda a: (a.get("severity") != "critical",
                                   a.get("rule", ""), a.get("scope", "")))
    if getattr(args, "json", False):
        print(json.dumps({"alerts": alerts, "recent": ring.get("recent"),
                          "leak_rows": leak_rows},
                         indent=2, default=str))
        return
    nodes = [n for n in rt.nodes() if n.get("Alive")]
    print(f"raytpu doctor — {len(nodes)} alive node(s), "
          f"{len(alerts)} finding(s)")
    # elastic plane: planned churn, rendered apart from the alert list so
    # an operator reads "resizing" before they read "unhealthy"
    draining = snap.get("draining_notices") or {}
    resizing = snap.get("train_resizing") or {}
    records = snap.get("resize_records") or []
    if draining or resizing or records:
        print("elastic:")
        for nid, left in sorted(draining.items()):
            print(f"  draining  node={nid}  notice expires in {left:.0f}s "
                  "(scheduler routing around it)")
        for trial, rec in sorted(resizing.items()):
            print(f"  resizing  trial={trial}  {rec.get('direction', '?')} "
                  f"from world={rec.get('from', '?')} (re-form in flight)")
        for rec in records[-3:]:
            ts = time.strftime("%H:%M:%S",
                               time.localtime(rec.get("ts", 0)))
            print(f"  resized   {ts}  trial={rec.get('trial', '?')}  "
                  f"{rec.get('direction', '?')}: world {rec.get('from', '?')}"
                  f" -> {rec.get('to', '?')} in {rec.get('wall_s', 0):.1f}s"
                  f" ({rec.get('reason', '?')})")
    if not alerts:
        print("  healthy: no rule above its raise threshold "
              f"({len(health_plane.HealthRule.ALL)} rules evaluated)")
        return
    for a in alerts:
        _print_alert(a)
    if leak_rows:
        print(f"leak sweep detail ({len(leak_rows)} suspect(s)):")
        for r in leak_rows[:10]:
            print(f"  {r.get('kind')}: object={str(r.get('object_id'))[:16]} "
                  f"holder={r.get('holder')} age={r.get('age_s')}s "
                  f"pins={r.get('pins')}")
    recent = ring.get("recent") or []
    if recent:
        print(f"recent transitions ({len(recent)}):")
        for ev in recent[:10]:
            ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
            print(f"  {ts}  {ev.get('kind', '?'):<8} {ev.get('rule')}"
                  f"  scope={ev.get('scope')}")


def cmd_alerts(args):
    """``raytpu alerts [--follow]`` — the health alert ring: active
    alerts + recent raised/cleared transitions, newest first.
    ``--follow`` polls and prints new transitions as they land."""
    _connect()
    from ray_tpu.util import state as state_api

    def frame():
        return state_api.health(limit=args.limit)

    h = frame()
    if getattr(args, "json", False):
        print(json.dumps(h, indent=2, default=str))
        return
    active = h.get("active") or []
    print(f"active alerts: {len(active)}"
          + ("" if h.get("enabled")
             else "  (health_metrics_enabled off — background detectors "
                  "idle; ring shows history only)"))
    for a in active:
        _print_alert(a)
    recent = h.get("recent") or []
    if recent:
        print(f"recent transitions ({len(recent)}):")
        for ev in recent:
            ts = time.strftime("%H:%M:%S", time.localtime(ev.get("ts", 0)))
            print(f"  {ts}  {ev.get('kind', '?'):<8} {ev.get('rule')}"
                  f"  scope={ev.get('scope')}")
    if not getattr(args, "follow", False):
        return
    seen = {(ev.get("ts"), ev.get("kind"), ev.get("rule"), ev.get("scope"))
            for ev in recent}
    try:
        while True:
            time.sleep(max(args.interval, 0.2))
            try:
                h = frame()
            except Exception:
                continue
            for ev in reversed(h.get("recent") or []):  # oldest first
                key = (ev.get("ts"), ev.get("kind"), ev.get("rule"),
                       ev.get("scope"))
                if key in seen:
                    continue
                seen.add(key)
                ts = time.strftime("%H:%M:%S",
                                   time.localtime(ev.get("ts", 0)))
                print(f"{ts}  {ev.get('kind', '?'):<8} {ev.get('rule')}"
                      f"  scope={ev.get('scope')}  "
                      + ", ".join(f"{k}={v}" for k, v in
                                  sorted((ev.get("evidence") or {}).items())),
                      flush=True)
    except KeyboardInterrupt:
        pass


# -------------------------------------------------------------------- logs

def cmd_logs(args):
    """``raytpu logs <node-id> [name] [--follow]`` — a node's log files
    via its agent's list_logs/tail_log RPCs: no name lists them (name +
    size); with a name, prints the tail (``--follow`` keeps polling and
    prints what grew) — where a doctor alert's next-step points when the
    evidence lives in a worker/agent log."""
    rt = _connect()
    from ray_tpu.core.core_worker import global_worker
    from ray_tpu.core.rpc import run_async

    target = None
    for n in rt.nodes():
        if not (n.get("Alive") and n.get("AgentAddress")):
            continue
        if n["NodeID"].startswith(args.node):
            target = n
            break
    if target is None:
        raise SystemExit(f"no alive node matching {args.node!r}")
    client = global_worker().agent_clients.get(target["AgentAddress"])

    if not args.name:
        rows = run_async(client.call("list_logs"))
        if not rows:
            print("(no log files)")
            return
        for r in sorted(rows, key=lambda r: r.get("name", "")):
            print(f"{_fmt_bytes(r.get('size')):>10}  {r.get('name')}")
        return

    def tail():
        return run_async(client.call("tail_log", name=args.name,
                                     nbytes=args.nbytes))

    text = tail()
    print(text, end="" if text.endswith("\n") else "\n")
    if not args.follow:
        return
    prev = text
    try:
        while True:
            time.sleep(1.0)
            try:
                text = tail()
            except Exception:
                continue
            if text == prev:
                continue
            if text.startswith(prev):
                delta = text[len(prev):]
            else:
                # the tail window slid: re-anchor on the old tail's end
                probe = prev[-256:]
                idx = text.find(probe) if probe else -1
                delta = text[idx + len(probe):] if idx >= 0 else text
            if delta:
                print(delta, end="" if delta.endswith("\n") else "\n",
                      flush=True)
            prev = text
    except KeyboardInterrupt:
        pass


# ---------------------------------------------------------------- profile

def cmd_profile(args):
    """On-demand profiler capture on one node (``jax.profiler.trace`` on
    a TPU-backed worker; thread-stack sampling to chrome-trace JSON on
    CPU).  Prints the artifact path on the TARGET node."""
    rt = _connect()
    from ray_tpu.core.core_worker import global_worker
    from ray_tpu.core.rpc import run_async

    target = None
    for n in rt.nodes():
        if not (n.get("Alive") and n.get("AgentAddress")):
            continue
        if args.node is None or n["NodeID"].startswith(args.node):
            target = n
            break
    if target is None:
        raise SystemExit(f"no alive node matching {args.node!r}")
    w = global_worker()
    res = run_async(
        w.agent_clients.get(target["AgentAddress"]).call(
            "profile", duration_s=args.duration,
            _timeout=args.duration + 60.0),
        timeout=args.duration + 90.0)
    print(f"profile captured on {target['NodeID'][:12]} "
          f"({res['process']}, mode={res['mode']})")
    print(res["path"])
    return res


def cmd_timeline(args):
    _connect()
    from ray_tpu.util.tracing import export_chrome_trace

    out = export_chrome_trace(args.output or "timeline.json",
                              breakdown=args.breakdown)
    what = "with per-stage sub-slices " if args.breakdown else ""
    print(f"chrome trace {what}-> {out} "
          f"(open in chrome://tracing or Perfetto)")


def cmd_dashboard(args):
    _connect()
    from ray_tpu.dashboard import start_dashboard

    port = start_dashboard(port=args.port)
    print(f"dashboard REST at http://127.0.0.1:{port}/api "
          f"(healthz/cluster/nodes/actors/tasks/jobs/serve/timeline)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass


# ------------------------------------------------------------------ chaos

def cmd_chaos(args):
    """Runtime control of the fault-injection plane (core/chaos.py):
    ``raytpu chaos set '<spec json>'`` broadcasts a FaultInjector spec
    through the GCS to every agent and worker; ``clear`` removes it;
    ``status`` prints the active spec, its version, and the GCS-side
    injected-fault counts (``raytpu_chaos_injected_total``)."""
    _connect()
    from ray_tpu.core.core_worker import global_worker
    from ray_tpu.core.rpc import run_async

    gcs = global_worker().gcs
    if args.action == "set":
        spec_text = args.spec
        if args.file:
            with open(args.file) as f:
                spec_text = f.read()
        if not spec_text:
            raise SystemExit("usage: raytpu chaos set '<spec json>' "
                             "(or --file spec.json)")
        spec = json.loads(spec_text)
        version = run_async(gcs.call("chaos_set", spec=spec))
        # the CLI's own driver process participates too
        from ray_tpu.core import chaos as chaos_mod
        chaos_mod.install(spec)
        print(f"chaos spec v{version} installed (seed="
              f"{spec.get('seed', 0)}, {len(spec.get('rules', []))} rule(s),"
              f" {len(spec.get('kills', []))} kill(s))")
    elif args.action == "clear":
        version = run_async(gcs.call("chaos_clear"))
        from ray_tpu.core import chaos as chaos_mod
        chaos_mod.install(None)
        print(f"chaos cleared (v{version})")
    else:  # status
        print(json.dumps(run_async(gcs.call("chaos_get")), indent=2,
                         default=str))


# ------------------------------------------------------------------- jobs

def cmd_submit(args):
    _connect()
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    runtime_env = {}
    if args.working_dir:
        runtime_env["working_dir"] = args.working_dir
    entry = list(args.entrypoint)
    if entry and entry[0] == "--":  # argparse.REMAINDER keeps the separator
        entry = entry[1:]
    if not entry:
        raise SystemExit("no entrypoint given (raytpu submit -- cmd ...)")
    job_id = client.submit_job(entrypoint=" ".join(entry),
                               runtime_env=runtime_env or None)
    print(f"submitted {job_id}")
    if args.no_wait:
        return
    status = client.wait_until_finish(job_id, timeout=args.timeout)
    print(client.get_job_logs(job_id), end="")
    print(f"job {job_id}: {status}")
    if status != "SUCCEEDED":
        sys.exit(1)


def cmd_job(args):
    _connect()
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient()
    if args.action == "list":
        print(json.dumps(client.list_jobs(), indent=2, default=str))
    elif args.action == "status":
        print(json.dumps(client.get_job_info(args.job_id), indent=2,
                         default=str))
    elif args.action == "logs":
        print(client.get_job_logs(args.job_id), end="")
    elif args.action == "stop":
        client.stop_job(args.job_id)
        print(f"stopped {args.job_id}")


def cmd_serve(args):
    """Declarative serve flows (reference: serve/scripts.py
    `serve deploy/run/status/shutdown`)."""
    _connect()
    from ray_tpu.serve import schema as serve_schema

    if args.action == "deploy":
        if not args.target:
            raise SystemExit("usage: raytpu serve deploy CONFIG.yaml")
        cfg = serve_schema.load_config(args.target)
        names = serve_schema.deploy_config(cfg, blocking=not args.no_wait)
        print(f"deployed: {', '.join(names)}")
    elif args.action == "run":
        if not args.target:
            raise SystemExit("usage: raytpu serve run module:app")
        from ray_tpu import serve as serve_api
        app = serve_schema.build_application({"import_path": args.target})
        serve_api.run(app, route_prefix=args.route_prefix or "/__auto__")
        print(f"running {app.name} (ctrl-c to exit)")
        try:
            import time as _t
            while True:
                _t.sleep(3600)
        except KeyboardInterrupt:
            pass
    elif args.action == "status":
        status = serve_schema.status_summary()
        if args.json or not status:
            print(json.dumps(status, indent=2, default=str))
        else:
            _print_serve_status(status)
            _print_autoscale_decisions(args.decisions)
    elif args.action == "shutdown":
        from ray_tpu import serve as serve_api
        serve_api.shutdown()
        print("serve shut down")


def _print_serve_status(status: dict):
    """Per-deployment table with the SLO signal surface: replica counts,
    live queue depth, the rolling TTFT percentiles each replica
    piggybacks on its health-check heartbeat (worst fresh replica wins;
    STALE counts heartbeats the staleness guard dropped), and the
    autoscaling policy driving the target — the exact per-deployment
    signal the SLO autoscaler consumes."""
    print(f"{'DEPLOYMENT':<20} {'STATUS':<10} {'REPLICAS':>8} "
          f"{'QUEUE':>6} {'TTFT p50':>9} {'TTFT p95':>9} "
          f"{'TTFT p99':>9} {'WINDOW':>7} {'STALE':>5} {'POLICY':>8}")

    def ms(v):
        return f"{v:.1f}ms" if v is not None else "-"

    for name, d in sorted(status.items()):
        slo = d.get("slo") or {}
        auto = d.get("autoscale") or {}
        running = len([r for r in d.get("replicas", [])
                       if r.get("state") == "RUNNING"])
        print(f"{name:<20} {d.get('status', '?'):<10} "
              f"{running}/{d.get('target_replicas', '?'):<6} "
              f"{slo.get('queue_depth', 0):>6} "
              f"{ms(slo.get('ttft_p50_ms')):>9} "
              f"{ms(slo.get('ttft_p95_ms')):>9} "
              f"{ms(slo.get('ttft_p99_ms')):>9} "
              f"{slo.get('window_n', 0):>7} "
              f"{slo.get('stale_replicas', 0):>5} "
              f"{auto.get('policy', '-'):>8}")


def _print_autoscale_decisions(limit: int):
    """Tail of the autoscaler decision ring: one line per scale event —
    WHY the replica count moved (or why a wanted surge was capped)."""
    if limit <= 0:
        return
    from ray_tpu import serve as serve_api
    try:
        decisions = serve_api.autoscale_decisions(limit=limit)
    except Exception:
        return
    if not decisions:
        return
    print(f"\n{'WHEN':<9} {'DEPLOYMENT':<20} {'DIR':<5} {'REPLICAS':>9} "
          f"{'REASON':<12} {'SIGNAL'}")
    now = time.time()
    for d in decisions:
        sig = d.get("signal") or {}
        detail = (f"queue={sig.get('queue_depth', 0)} "
                  f"p95={sig.get('ttft_p95_ms', '-')}ms "
                  f"stale={sig.get('stale_replicas', 0)}")
        if d.get("capped"):
            detail += f"  [wanted {d['wanted']}, cluster capped at " \
                      f"{d['to_replicas']}]"
        print(f"{now - d['ts']:>7.1f}s {d['deployment']:<20} "
              f"{d['direction']:<5} "
              f"{d['from_replicas']:>3}->{d['to_replicas']:<3} "
              f"{d['reason']:<12} {detail}")


# ------------------------------------------------------------------ main

def main(argv=None):
    p = argparse.ArgumentParser(prog="raytpu")
    sub = p.add_subparsers(dest="cmd", required=True)

    s = sub.add_parser("start", help="start a head or worker node daemon")
    s.add_argument("--head", action="store_true")
    s.add_argument("--address", default=None)
    s.add_argument("--num-cpus", type=float, default=None)
    s.add_argument("--num-tpus", type=float, default=None)
    s.add_argument("--resources", default=None)
    s.add_argument("--labels", default=None)
    s.set_defaults(fn=cmd_start)

    s = sub.add_parser("stop", help="stop local daemons")
    s.set_defaults(fn=cmd_stop)

    s = sub.add_parser("status", help="cluster nodes + resources + per-node "
                                      "telemetry and task-stage latency "
                                      "(--config: launcher fleet status)")
    s.add_argument("--config", default=None,
                   help="launcher YAML: show the fleet's QR states instead")
    s.add_argument("--state", default=None, help="launcher state file")
    s.set_defaults(fn=cmd_status)

    s = sub.add_parser("up", help="summon the fleet from a launcher YAML "
                                  "(GCE TPU queued resources)")
    s.add_argument("--config", required=True)
    s.add_argument("--state", default=None,
                   help="state file (default /tmp/raytpu/launcher-NAME.json)")
    s.add_argument("--wait", action="store_true",
                   help="block until created nodes reach ACTIVE")
    s.set_defaults(fn=cmd_up)

    s = sub.add_parser("down", help="tear down the fleet a previous "
                                    "`raytpu up` launched")
    s.add_argument("--config", required=True)
    s.add_argument("--state", default=None)
    s.set_defaults(fn=cmd_down)

    s = sub.add_parser("explain", help="decision/lifecycle trail for one "
                       "task/actor/pg/object id: pending reason or object "
                       "lifecycle transitions + decision records (why is "
                       "it not running / where did its bytes go?)")
    s.add_argument("id", help="task / actor / placement-group / object "
                              "id (hex)")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_explain)

    s = sub.add_parser("transfers", help="per-pull flight records: "
                       "per-source stripe stats, steals/retries, relay "
                       "fraction (why was this broadcast slow?)")
    s.add_argument("--limit", type=int, default=50)
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_transfers)

    s = sub.add_parser("list", help="state API listings")
    s.add_argument("kind")
    s.set_defaults(fn=cmd_list)

    s = sub.add_parser("memory", help="per-object store/refcount report "
                                      "(+ --leaks ref-debt suspects)")
    s.add_argument("--json", action="store_true",
                   help="machine-readable full report")
    s.add_argument("--leaks", action="store_true",
                   help="ref-debt report: pins past TTL, deferred frees "
                        "stuck behind vanished pins, owner-lost objects")
    s.add_argument("--pin-ttl", type=float, default=None,
                   help="--leaks pin-age threshold in seconds "
                        "(default: config object_pin_leak_ttl_s)")
    s.set_defaults(fn=cmd_memory)

    s = sub.add_parser("top", help="live cluster view: per-node cpu/shm/"
                                   "lease-queue/loop-lag + train step/MFU/"
                                   "goodput + serve req/s/TTFT")
    s.add_argument("--once", action="store_true",
                   help="print one frame (two scrapes for rates) and exit")
    s.add_argument("--interval", type=float, default=2.0,
                   help="refresh/scrape period in seconds")
    s.set_defaults(fn=cmd_top)

    s = sub.add_parser("doctor", help="one-shot cluster diagnosis: every "
                       "health rule evaluated now + active alerts, each "
                       "with evidence and the explain-surface to run next")
    s.add_argument("--json", action="store_true")
    s.add_argument("--limit", type=int, default=20,
                   help="recent-transition tail length")
    s.set_defaults(fn=cmd_doctor)

    s = sub.add_parser("alerts", help="health alert ring: active alerts + "
                       "recent raised/cleared transitions "
                       "(--follow streams new ones)")
    s.add_argument("--follow", action="store_true")
    s.add_argument("--limit", type=int, default=50)
    s.add_argument("--interval", type=float, default=2.0,
                   help="--follow poll period in seconds")
    s.add_argument("--json", action="store_true")
    s.set_defaults(fn=cmd_alerts)

    s = sub.add_parser("logs", help="list / tail a node's log files via "
                       "its agent (no name: list; name: tail, "
                       "--follow streams growth)")
    s.add_argument("node", help="node id prefix")
    s.add_argument("name", nargs="?", default=None,
                   help="log file name from the listing")
    s.add_argument("--follow", "-f", action="store_true")
    s.add_argument("--nbytes", type=int, default=65536,
                   help="tail window size in bytes")
    s.set_defaults(fn=cmd_logs)

    s = sub.add_parser("profile", help="capture an on-demand profile on one "
                                       "node (jax.profiler on TPU, thread-"
                                       "stack sampling chrome-trace on CPU)")
    s.add_argument("--node", default=None,
                   help="node id prefix (default: first alive node)")
    s.add_argument("--duration", type=float, default=2.0,
                   help="capture window in seconds")
    s.set_defaults(fn=cmd_profile)

    s = sub.add_parser("timeline", help="export chrome-trace timeline json")
    s.add_argument("--output", default=None)
    s.add_argument("--breakdown", action="store_true",
                   help="nest per-stage sub-slices (queue/dep_fetch/"
                        "arg_deser/execute/result_put) inside task slices")
    s.set_defaults(fn=cmd_timeline)

    s = sub.add_parser("dashboard", help="serve the REST dashboard")
    s.add_argument("--port", type=int, default=8265)
    s.set_defaults(fn=cmd_dashboard)

    s = sub.add_parser("chaos", help="fault-injection control "
                                     "(set/clear/status a chaos spec)")
    s.add_argument("action", choices=["set", "clear", "status"])
    s.add_argument("spec", nargs="?", help="FaultInjector spec JSON (set)")
    s.add_argument("--file", default=None, help="read the spec from a file")
    s.set_defaults(fn=cmd_chaos)

    s = sub.add_parser("submit", help="submit a job (entrypoint after --)")
    s.add_argument("--working-dir", default=None)
    s.add_argument("--no-wait", action="store_true")
    s.add_argument("--timeout", type=float, default=600.0)
    s.add_argument("entrypoint", nargs=argparse.REMAINDER)
    s.set_defaults(fn=cmd_submit)

    s = sub.add_parser("job", help="job list/status/logs/stop")
    s.add_argument("action",
                   choices=["list", "status", "logs", "stop"])
    s.add_argument("job_id", nargs="?")
    s.set_defaults(fn=cmd_job)

    s = sub.add_parser("serve", help="declarative serve deploy/run/status")
    s.add_argument("action",
                   choices=["deploy", "run", "status", "shutdown"])
    s.add_argument("target", nargs="?",
                   help="config file (deploy) or module:app (run)")
    s.add_argument("--route-prefix", default=None)
    s.add_argument("--no-wait", action="store_true")
    s.add_argument("--json", action="store_true",
                   help="status: raw JSON instead of the SLO table")
    s.add_argument("--decisions", type=int, default=10, metavar="N",
                   help="status: show the last N autoscale decision "
                        "records under the SLO table (0 = hide)")
    s.set_defaults(fn=cmd_serve)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
