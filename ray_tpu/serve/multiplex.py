"""Model multiplexing: many models behind one replica set.

Reference: ``python/ray/serve/multiplex.py`` (``@serve.multiplexed`` — an
async model loader memoized per model id with LRU eviction, so one
deployment serves N fine-tunes/checkpoints without N replica sets).

TPU angle: the loader typically materializes weights into HBM; the LRU cap
is the HBM budget knob.  Eviction calls the model's ``unload()`` (when it
defines one) — deployments that run long forwards should release models
only between requests (e.g. load at request start), as eviction does not
track in-flight use (the reference ties that to its request context).
"""

from __future__ import annotations

import asyncio
import functools
from collections import OrderedDict
from typing import Any, Callable, Optional


class _ModelCache:
    def __init__(self, loader: Callable, max_num_models: int):
        self.loader = loader
        self.max_num_models = max_num_models
        self._models: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: dict = {}           # model_id -> asyncio.Event

    async def get(self, instance, model_id: str) -> Any:
        while True:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return self._models[model_id]
            ev = self._loading.get(model_id)
            if ev is None:
                break
            await ev.wait()  # someone else is loading it
        ev = self._loading[model_id] = asyncio.Event()
        try:
            await self._evict_for_space()
            out = (self.loader(instance, model_id) if instance is not None
                   else self.loader(model_id))
            if asyncio.iscoroutine(out):
                out = await out
            self._models[model_id] = out
            return out
        finally:
            self._loading.pop(model_id, None)
            ev.set()

    async def _evict_for_space(self):
        while len(self._models) >= self.max_num_models:
            victim = next(iter(self._models))  # least recently used
            model = self._models.pop(victim, None)
            unload = getattr(model, "unload", None)
            if callable(unload):
                try:
                    res = unload()
                    if asyncio.iscoroutine(res):
                        await res
                except Exception:
                    pass


def multiplexed(_fn: Optional[Callable] = None, *, max_num_models: int = 3):
    """Decorator over an async model loader: ``loader(self, model_id)`` is
    called at most once per cached model; the cache LRU-evicts beyond
    ``max_num_models`` (reference: serve/multiplex.py)."""

    def wrap(loader: Callable):
        # the cache lives ON the instance: a module-level dict keyed by
        # id(instance) would leak models past the instance and alias a new
        # instance onto a dead one's cache when CPython reuses the id
        attr = f"__mux_cache_{loader.__name__}"
        fn_cache: list = []  # for free functions (no instance)

        @functools.wraps(loader)
        async def wrapper(*args):
            if len(args) == 2:
                instance, model_id = args
                cache = getattr(instance, attr, None)
                if cache is None:
                    cache = _ModelCache(loader, max_num_models)
                    setattr(instance, attr, cache)
            else:
                instance, model_id = None, args[0]
                if not fn_cache:
                    fn_cache.append(_ModelCache(loader, max_num_models))
                cache = fn_cache[0]
            return await cache.get(instance, model_id)

        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap


import contextvars  # noqa: E402

_current_model_id: "contextvars.ContextVar[str]" = contextvars.ContextVar(
    "raytpu_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The model id of the current request (reference API parity).  Set by
    the replica from the request's ``serve_multiplexed_model_id`` header or
    ``model_id`` JSON body field (replica.py); deployments can also just
    pass the id explicitly."""
    return _current_model_id.get("")


def _set_current_model_id(request) -> None:
    """Called by ReplicaActor around each request invocation."""
    mid = ""
    try:
        headers = getattr(request, "headers", None) or {}
        mid = headers.get("serve_multiplexed_model_id", "")
        if not mid and getattr(request, "body", None):
            body = request.json()
            if isinstance(body, dict):
                mid = str(body.get("model_id", ""))
    except Exception:
        mid = ""
    _current_model_id.set(mid)
