"""Continuous-batching LLM inference for the Serve-equivalent.

SURVEY §2.7 note: the reference snapshot has **no** vLLM-style LLM server —
``@serve.batch`` + streaming are its primitives.  This module is the
first-class TPU-native addition BASELINE.json config #4 calls for.

Architecture (TPU-first):
* The **engine** owns a slot-based KV cache (``models/decode.py``) and runs a
  scheduler loop on a dedicated thread: admit pending prompts into free slots
  via a **bucketed prefill** (prompt padded to the next length bucket — one
  compiled program per bucket, jit cache discipline), then run **one decode
  step for the whole active batch** (single compiled program, static shapes).
  New requests join the decode batch at the next step boundary — continuous
  batching without ever changing a tensor shape.
* Decode emits one token per active slot per step; tokens stream to callers
  through per-request queues, so TTFT ≈ one prefill + scheduling delay, and
  a long generation never blocks a short one (the short one retires early,
  freeing its slot for the next admit).
* Sampling is greedy or temperature/top-k, per request.

* **Paged KV cache** (``paged=True``): block-table pages instead of dense
  ``slots x max_len`` rows (``models/paged_decode.py``) — HBM scales with
  actual request lengths, and identical prompt prefixes share pages
  (prefix caching with refcounts).
* **In-replica tensor parallelism** (``tp=N``): params and KV heads are
  sharded over an N-chip mesh with ``NamedSharding``; the same jitted
  prefill/decode programs run SPMD (XLA inserts the collectives).  Deploy
  with ``num_replicas > 1`` for replica-level data parallelism on top.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, Iterator, List, Optional

from . import observability as obs
from .deployment import deployment as serve_deployment

DEFAULT_BUCKETS = (32, 64, 128, 256, 512, 1024, 2048)
_FLUSH = object()


class GenRequest:
    __slots__ = ("tokens", "max_tokens", "temperature", "top_k", "eos_id",
                 "out", "slot", "generated", "submitted_at", "first_token_at",
                 "pages", "prompt_len", "deployment", "trace_ctx",
                 "submitted_wall", "admitted_wall", "first_token_wall",
                 "span_parent")

    def __init__(self, tokens: List[int], max_tokens: int,
                 temperature: float, top_k: int, eos_id: Optional[int]):
        self.tokens = tokens
        self.max_tokens = max_tokens
        self.temperature = temperature
        self.top_k = top_k
        self.eos_id = eos_id
        self.out: "queue.Queue" = queue.Queue()
        self.slot = -1
        self.pages: List[int] = []
        self.generated = 0
        self.prompt_len = len(tokens)
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        # observability: who/what this request belongs to (the replica's
        # deployment tag + the caller's trace context, captured at submit
        # on the caller's thread) and the wall-clock stage stamps the
        # engine thread turns into batch_wait/prefill/decode spans
        self.deployment = "-"
        self.trace_ctx: Optional[tuple] = None
        self.submitted_wall = time.time()
        self.admitted_wall: Optional[float] = None
        self.first_token_wall: Optional[float] = None
        #: previous stage's span id — batch_wait -> prefill -> decode chain
        self.span_parent: Optional[str] = None


class LLMEngine:
    """Slot-scheduled continuous batching over prefill/decode programs."""

    def __init__(self, cfg, params=None, *, num_slots: int = 8,
                 max_len: Optional[int] = None, buckets=DEFAULT_BUCKETS,
                 compute_dtype=None, seed: int = 0, top_k: int = 0,
                 fetch_lag: int = 2, steps_per_dispatch: int = 8,
                 prefill_batch: Optional[int] = None,
                 warmup_buckets: bool = False,
                 paged: bool = False, page_size: int = 64,
                 num_pages: Optional[int] = None, prefix_cache: bool = True,
                 tp: int = 1, spec_decode_enabled: bool = False,
                 spec_k: int = 4, spec_draft_layers: int = 1,
                 spec_adaptive: bool = True):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import decode as dec
        from ray_tpu.models import transformer

        self.cfg = cfg
        self.max_len = max_len or cfg.max_seq_len
        self.num_slots = num_slots
        self.buckets = tuple(b for b in buckets if b <= self.max_len)
        self.compute_dtype = compute_dtype or jnp.bfloat16
        self.top_k = top_k
        self.fetch_lag = max(0, fetch_lag)
        # decode steps fused into one dispatch: amortizes host->device RTT
        # (tunnel) at the cost of <= steps_per_dispatch wasted steps after a
        # sequence finishes and <= one dispatch of added admission latency
        self.steps_per_dispatch = max(1, steps_per_dispatch)
        self._dec = dec
        self._jax = jax
        self._jnp = jnp
        if params is None:
            params = transformer.init_params(
                jax.random.PRNGKey(seed), cfg, dtype=jnp.bfloat16)
        self.params = params
        # Admission batches are padded to a FIXED size so each length bucket
        # compiles exactly one prefill program (a varying batch dim would
        # recompile mid-traffic).  Padding rows write into a scratch cache
        # slot (index num_slots) that decode never activates.
        self.prefill_batch = prefill_batch or min(num_slots, 8)
        self._scratch_slot = num_slots
        self.paged = paged
        if paged:
            from ray_tpu.models import paged_decode as pdec
            self._pdec = pdec
            self.page_size = page_size
            self.max_pages_per_slot = -(-self.max_len // page_size)
            # default HBM budget = half the dense cache (the paged win)
            self.num_pages = num_pages or max(
                (num_slots + 1) * self.max_pages_per_slot // 2, 16)
            self.cache = pdec.init_paged_cache(
                cfg, self.num_pages, page_size, num_slots + 1,
                self.max_pages_per_slot, self.compute_dtype)
            self.allocator = pdec.PageAllocator(self.num_pages)
            self.prefix = (pdec.PrefixCache(self.allocator, page_size)
                           if prefix_cache else None)
        else:
            self.cache = dec.init_kv_cache(cfg, num_slots + 1, self.max_len,
                                           self.compute_dtype)
        # In-replica tensor parallelism: place params + cache with tp
        # shardings; jit propagates them, XLA inserts the collectives.
        self.tp = tp
        self.mesh = None
        if tp > 1:
            if cfg.num_kv_heads % tp:
                raise ValueError(f"tp={tp} must divide num_kv_heads="
                                 f"{cfg.num_kv_heads}")
            from jax.sharding import Mesh
            devs = jax.devices()
            if len(devs) < tp:
                raise ValueError(f"tp={tp} but only {len(devs)} devices")
            self.mesh = Mesh(devs[:tp], ("tp",))
            self.params, self.cache = self._apply_tp_sharding(
                self.params, self.cache)
        # Device-resident autoregressive state: token/active/temp/budget/eos
        # per slot plus the PRNG key.  EVERYTHING the scheduler loop touches
        # on the device goes through exactly two jitted programs — over a
        # tunneled backend each eager op or small transfer costs a full
        # round trip (~60-80 ms measured), which round-4's per-retire
        # `.at[].set` and per-dispatch eager `fold_in` paid on every loop
        # iteration, capping the engine at ~130 tok/s vs the >2000 tok/s
        # the compiled decode program itself sustains.
        self._state = dec.init_decode_state(num_slots + 1,
                                            jax.random.PRNGKey(seed + 1))
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P
            self._state = jax.device_put(
                self._state, NamedSharding(self.mesh, P()))

        # Compiled programs: one decode dispatch (cache + state donated —
        # the multi-GB cache must be updated in place, not copied), one
        # prefill per bucket (lazy unless warmup_buckets).
        if paged:
            self._decode_fn = jax.jit(
                lambda p, c, st: self._pdec.paged_decode_state_loop(
                    p, c, st, self.steps_per_dispatch, cfg, top_k,
                    self.compute_dtype),
                donate_argnums=(1, 2))
        else:
            self._decode_fn = jax.jit(
                lambda p, c, st: dec.decode_state_loop(
                    p, c, st, self.steps_per_dispatch, cfg, top_k,
                    self.compute_dtype),
                donate_argnums=(1, 2))
        self._prefill_fns: Dict[int, Any] = {}

        # Speculative decoding (spec_decode_enabled=False => today's path
        # exactly: no draft state exists and _dispatch_step never branches).
        # A layers-sliced draft shares embed/lm_head with the target and
        # keeps a DENSE cache (the paged HBM win matters for the big
        # target); per dispatch the adaptive controller picks k from
        # occupancy — speculation pays when slots are idle, so k shrinks
        # as the batch fills (min k=2 rather than a plain-decode fallback,
        # which would let the draft cache diverge from the target's).
        self.spec_enabled = bool(spec_decode_enabled)
        if self.spec_enabled:
            if tp > 1:
                raise ValueError("spec_decode_enabled does not compose with "
                                 "tp>1 yet (draft params are unsharded)")
            import dataclasses as _dc

            from ray_tpu.models import speculative as spec_mod
            self._spec = spec_mod
            d = max(1, min(int(spec_draft_layers), cfg.num_layers - 1))
            self.spec_k = max(2, int(spec_k))
            self.spec_adaptive = bool(spec_adaptive)
            self.spec_draft_layers = d
            self._spec_draft_cfg = _dc.replace(cfg, num_layers=d)
            self._draft_params = spec_mod.make_draft_params(self.params, d)
            self._draft_cache = dec.init_kv_cache(
                self._spec_draft_cfg, num_slots + 1, self.max_len,
                self.compute_dtype)
            self._spec_fns: Dict[int, Any] = {}
            self._draft_prefill_fns: Dict[int, Any] = {}
            self._spec_ks = sorted({self.spec_k,
                                    max(2, (self.spec_k + 1) // 2), 2},
                                   reverse=True)
            # accounting (breakdown()["spec"] + raytpu_serve_spec_* read
            # these; derived host-side from per-round emit counts only)
            self.spec_rounds = 0
            self.spec_tokens = 0
            self.spec_drafted = 0
            self.spec_accepted = 0
            self.spec_draft_errors = 0
            self.spec_dispatch_k: Dict[int, int] = {}
        else:
            self._spec = None

        # scheduler state
        self._pending: "queue.Queue[GenRequest]" = queue.Queue()
        self._active: Dict[int, GenRequest] = {}
        self._free_slots = list(range(num_slots))
        # dispatched-but-unfetched steps: (tokens_dev, {slot: req} snapshot)
        self._unfetched: List[tuple] = []
        self._stop = False
        self._wake = threading.Event()
        # steady-state metrics
        self.steps = 0
        self.tokens_out = 0
        # admission accounting (padding waste = padded rows the fixed-size
        # prefill batch shipped for nothing; bench_llm reads these)
        self.admit_batches = 0
        self.admit_rows_real = 0
        self.admit_rows_padded = 0
        self._obs_dep = "-"  # deployment tag, learned from first request
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="llm-engine")
        self._thread.start()
        if warmup_buckets:
            for b in self.buckets:
                self.warmup(b)

    # ----------------------------------------------------------- public

    def submit(self, tokens: List[int], max_tokens: int = 64,
               temperature: float = 0.0, top_k: int = 0,
               eos_id: Optional[int] = None) -> GenRequest:
        if len(tokens) >= self.max_len:
            raise ValueError(f"prompt length {len(tokens)} >= max_len "
                             f"{self.max_len}")
        req = GenRequest(list(map(int, tokens)), max_tokens, temperature,
                         top_k, eos_id)
        if obs.enabled():
            # caller-thread capture: the replica set both before invoking
            # user code, so engine-side spans/metrics carry the request's
            # deployment tag and chain into its trace
            req.deployment = obs.current_deployment()
            from ray_tpu.util import tracing
            req.trace_ctx = tracing.current_context()
            if req.trace_ctx is None:
                # standalone engine use (no serve request context): mint
                # ONE trace per request so batch_wait -> prefill -> decode
                # still chain together instead of three orphan traces with
                # dangling cross-trace parent links
                req.trace_ctx = (tracing.new_id(), None)
            if req.deployment != "-":
                self._obs_dep = req.deployment
            obs.add_tokens(req.deployment, "in", req.prompt_len)
        self._pending.put(req)
        self._wake.set()
        return req

    def generate(self, tokens: List[int], **kw) -> List[int]:
        """Blocking convenience: full output token list."""
        return list(self.stream(tokens, **kw))

    def stream(self, tokens: List[int], **kw) -> Iterator[int]:
        req = self.submit(tokens, **kw)
        while True:
            item = req.out.get()
            if item is _FLUSH:
                break
            if isinstance(item, BaseException):
                raise item
            yield item

    def shutdown(self):
        self._stop = True
        self._wake.set()
        self._thread.join(timeout=10)

    def breakdown(self) -> dict:
        """Serving-picture rollup (bench_llm records this next to the
        per-request percentiles): admission batch occupancy + padding
        waste, KV page utilization, prefix-cache hit rate."""
        rows = self.admit_rows_real + self.admit_rows_padded
        out = {
            "admit_batches": self.admit_batches,
            "batch_occupancy": (self.admit_rows_real / rows) if rows else 0.0,
            "padding_fraction": (self.admit_rows_padded / rows) if rows
            else 0.0,
            "active_slots": len(self._active),
            "num_slots": self.num_slots,
        }
        if self.paged:
            # total = ALLOCATABLE pages (page 0 is the reserved null page),
            # so used/total equals the utilization field
            allocatable = max(self.num_pages - 1, 1)
            out["kv_pages"] = {
                "total": allocatable,
                "used": self.allocator.used(),
                "utilization": self.allocator.used() / allocatable,
            }
            out["prefix_cache"] = (self.prefix.stats()
                                   if self.prefix is not None else None)
        if self.spec_enabled:
            out["spec"] = {
                "k": self.spec_k,
                "draft_layers": self.spec_draft_layers,
                "rounds": self.spec_rounds,
                "tokens": self.spec_tokens,
                "drafted": self.spec_drafted,
                "accepted": self.spec_accepted,
                "acceptance_rate": (self.spec_accepted / self.spec_drafted
                                    if self.spec_drafted else 0.0),
                "rollback_tokens": self.spec_drafted - self.spec_accepted,
                "tokens_per_round": (self.spec_tokens / self.spec_rounds
                                     if self.spec_rounds else 0.0),
                "dispatch_k": dict(self.spec_dispatch_k),
                "draft_errors": self.spec_draft_errors,
            }
        return out

    def prefix_digest(self, cap: int = 32) -> Optional[dict]:
        """Bounded digest of this engine's hot first-page prefix chunks
        for cache-aware routing: ``{"page": page_size, "blocks": [8-hex
        truncated chunk hashes]}``.  None when the engine is dense or
        prefix caching is off — the router falls back to pure p2c."""
        if not self.paged or self.prefix is None:
            return None
        return {"page": self.page_size,
                "blocks": self.prefix.first_page_digest(cap)}

    def warmup(self, bucket: Optional[int] = None):
        """Compile prefill(bucket)+decode ahead of traffic."""
        b = bucket or self.buckets[0]
        req = self.submit([1] * min(4, b), max_tokens=2)
        while req.out.get() is not _FLUSH:
            pass

    # -------------------------------------------------------- tp sharding

    def _apply_tp_sharding(self, params, cache):
        """Place params + cache on the tp mesh: attention/MLP weights split
        megatron-style (column then row), KV heads split across chips,
        small/control tensors replicated.  jit then runs the unchanged
        programs SPMD (scaling-book recipe: annotate, let XLA do the rest)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = self.mesh

        def spec_for(path: str, arr) -> "P":
            dims = arr.ndim

            def at(axis):  # PartitionSpec with 'tp' at `axis`
                parts = [None] * dims
                parts[axis] = "tp"
                return P(*parts)

            # stacked block params carry a leading L dim (scan over layers)
            if "wq" in path or "wk" in path or "wv" in path \
                    or "w_in" in path or "w_gate" in path:
                return at(dims - 1)          # column parallel
            if "wo" in path or "w_out" in path:
                return at(dims - 2)          # row parallel
            if "bq" in path or "bk" in path or "bv" in path \
                    or "b_in" in path:
                return at(dims - 1)
            if path.endswith("/k") or path.endswith("/v"):
                return at(3)                 # [L, P|S, len, NKV, D]
            return P()                       # replicate

        def place(tree):
            flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
            placed = []
            for keypath, leaf in flat:
                path = "/".join(str(getattr(k, "key", k)) for k in keypath)
                placed.append(jax.device_put(
                    leaf, NamedSharding(mesh, spec_for("/" + path, leaf))))
            return jax.tree_util.tree_unflatten(treedef, placed)

        return place(params), place(cache)

    # ----------------------------------------------------- observability

    def _obs_admit(self, reqs: List[GenRequest]):
        """One successful admit batch: padding accounting, occupancy +
        queue-wait metrics, batch_wait span per request (chained under
        the request's trace), KV/slot gauges.  Engine-thread side; every
        metric call is a precomputed-key observe behind one enabled()
        check."""
        self.admit_batches += 1
        self.admit_rows_real += len(reqs)
        self.admit_rows_padded += self.prefill_batch - len(reqs)
        if not obs.enabled():
            return
        now = time.time()
        dep = self._obs_dep
        obs.record_batch(dep, len(reqs), self.prefill_batch,
                         waits_s=[now - r.submitted_wall for r in reqs])
        self._obs_gauges()
        for r in reqs:
            r.admitted_wall = now
            r.span_parent = obs.stamp_span(
                "batch_wait", r.submitted_wall, now - r.submitted_wall,
                trace_id=r.trace_ctx[0] if r.trace_ctx else None,
                parent_id=r.trace_ctx[1] if r.trace_ctx else None,
                deployment=r.deployment)

    def _obs_first_token(self, r: GenRequest, now_mono: float):
        """Prefill finished for one request: engine-level TTFT (the rolling
        SLO window takes the replica-level sample instead — one per
        request) + the ``prefill`` span, chained under batch_wait."""
        if not obs.enabled():
            return
        r.first_token_wall = time.time()
        obs.observe_ttft(r.deployment, now_mono - r.submitted_at,
                         stage="engine", window=False)
        t0 = r.admitted_wall or r.submitted_wall
        r.span_parent = obs.stamp_span(
            "prefill", t0, r.first_token_wall - t0,
            trace_id=r.trace_ctx[0] if r.trace_ctx else None,
            parent_id=r.span_parent,
            deployment=r.deployment, prompt_len=r.prompt_len)

    def _obs_retire(self, r: GenRequest):
        """Generation done: decode span (first token -> last), TPOT, token
        counters, refreshed slot/KV gauges."""
        if not obs.enabled():
            return
        obs.add_tokens(r.deployment, "out", r.generated)
        now = time.time()
        if r.first_token_wall is not None:
            obs.stamp_span(
                "decode", r.first_token_wall, now - r.first_token_wall,
                trace_id=r.trace_ctx[0] if r.trace_ctx else None,
                parent_id=r.span_parent,
                deployment=r.deployment, tokens=r.generated)
        if r.generated > 1 and r.first_token_at is not None:
            obs.observe_tpot(r.deployment,
                             (time.monotonic() - r.first_token_at)
                             / (r.generated - 1))
        self._obs_gauges()

    def _obs_gauges(self):
        obs.set_engine_gauges(
            self._obs_dep, len(self._active),
            kv_pages_used=self.allocator.used() if self.paged else None,
            kv_pages_total=(max(self.num_pages - 1, 1) if self.paged
                            else None))

    # -------------------------------------------------------- scheduler

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_len

    def _prefill_fn(self, bucket: int):
        fn = self._prefill_fns.get(bucket)
        if fn is None:
            cfg, dt, tk = self.cfg, self.compute_dtype, self.top_k
            dec = self._dec

            # Prefill + sample + merge into the decode state in ONE
            # fixed-shape program (a varying admit count would compile a
            # fresh program per batch size).  Admit batches arrive as plain
            # numpy arrays — transferred as part of the async dispatch, not
            # as per-array eager round trips.  Padding rows target the
            # scratch slot.
            if self.paged:
                pdec = self._pdec

                def admit_fn(p, c, st, t, ln, sl, start, bt, tmp, bud, eos,
                             real_mask):
                    return pdec.paged_prefill_admit(
                        p, c, st, t, ln, sl, start, bt, tmp, bud, eos,
                        real_mask, cfg, tk, dt)
            else:
                def admit_fn(p, c, st, t, ln, sl, tmp, bud, eos, real_mask):
                    return dec.prefill_admit(
                        p, c, st, t, ln, sl, tmp, bud, eos, real_mask, cfg,
                        tk, dt)

            fn = self._jax.jit(admit_fn, donate_argnums=(1, 2))
            self._prefill_fns[bucket] = fn
        return fn

    # ------------------------------------------------- speculative decode

    def _draft_prefill_fn(self, bucket: int):
        """Draft-cache prefill (KV only, logits discarded): the draft has
        no prefix cache, so it always ingests the FULL prompt from
        position 0 — one small compiled program per length bucket."""
        fn = self._draft_prefill_fns.get(bucket)
        if fn is None:
            dcfg, dt = self._spec_draft_cfg, self.compute_dtype
            dec = self._dec

            def f(p, c, t, ln, sl):
                return dec.prefill(p, c, t, ln, sl, dcfg, dt)[0]

            fn = self._jax.jit(f, donate_argnums=(1,))
            self._draft_prefill_fns[bucket] = fn
        return fn

    def _draft_prefill(self, reqs: List[GenRequest], slots: List[int]):
        """Ingest the admitted prompts into the draft cache.  Failure here
        never fails the requests: greedy acceptance keeps the OUTPUT exact
        even with a garbage draft (acceptance just collapses), so degrade
        and count instead of unwinding a half-done admit."""
        import numpy as np
        bucket = self._bucket_for(max(len(r.tokens) for r in reqs))
        n_pad = self.prefill_batch - len(reqs)
        toks = np.zeros((self.prefill_batch, bucket), np.int32)
        for i, r in enumerate(reqs):
            toks[i, :len(r.tokens)] = r.tokens
        lengths = np.asarray([len(r.tokens) for r in reqs] + [1] * n_pad,
                             np.int32)
        slots_arr = np.asarray(slots + [self._scratch_slot] * n_pad,
                               np.int32)
        try:
            self._draft_cache = self._draft_prefill_fn(bucket)(
                self._draft_params, self._draft_cache, toks, lengths,
                slots_arr)
        except BaseException:  # noqa: BLE001
            self.spec_draft_errors += 1

    def _spec_k_now(self) -> int:
        """Adaptive k: speculation pays when slots are idle (the verify
        matmul rides free on weight traffic the batch already pays for),
        so shrink the window as occupancy rises.  Never falls back to the
        plain decode program — that would stop feeding the draft cache
        and strand its KV behind the target's for every in-flight
        request."""
        if not self.spec_adaptive or len(self._spec_ks) == 1:
            return self.spec_k
        occ = len(self._active) / max(1, self.num_slots)
        if occ <= 0.5:
            return self._spec_ks[0]
        if occ <= 0.85:
            return self._spec_ks[min(1, len(self._spec_ks) - 1)]
        return self._spec_ks[-1]

    def _spec_fn(self, k: int):
        """One compiled spec-decode program per window size k (static
        shapes; rounds chosen so a dispatch emits at most about
        steps_per_dispatch tokens per slot, matching the plain path's
        readback cadence)."""
        ent = self._spec_fns.get(k)
        if ent is None:
            rounds = max(1, self.steps_per_dispatch // k)
            spec, cfg, dcfg = self._spec, self.cfg, self._spec_draft_cfg
            tk, dt, paged = self.top_k, self.compute_dtype, self.paged

            def run(tp, tc, dp, dc, st):
                return spec.spec_decode_state_loop(
                    tp, tc, dp, dc, st, k, rounds, cfg, dcfg, paged, tk, dt)

            ent = (self._jax.jit(run, donate_argnums=(1, 3, 4)), rounds)
            self._spec_fns[k] = ent
        return ent

    def _loop(self):
        while not self._stop:
            did_work = False
            # admit: batch pending prompts of the same bucket into one prefill
            admits: List[GenRequest] = []
            bucket = None
            while (len(admits) < len(self._free_slots)
                   and len(admits) < self.prefill_batch
                   and not self._pending.empty()):
                nxt = self._pending.queue[0]
                b = self._bucket_for(len(nxt.tokens))
                if bucket is None:
                    bucket = b
                if b != bucket:
                    break
                admits.append(self._pending.get())
            if admits:
                self._admit(admits, bucket)
                did_work = True
            if self._active:
                self._dispatch_step()
                did_work = True
            # fetch completed steps once the pipeline is `fetch_lag` deep
            # (device computes step N+1 while the host reads back step N)
            while len(self._unfetched) > (self.fetch_lag if self._active
                                          else 0):
                self._drain_one()
                did_work = True
            if not did_work:
                self._wake.wait(timeout=0.02)
                self._wake.clear()

    def _admit_arrays(self, reqs: List[GenRequest], bucket: int,
                      slots: List[int], starts: Optional[List[int]] = None):
        """Build one admit batch as plain numpy arrays (no device ops)."""
        import numpy as np
        n_pad = self.prefill_batch - len(reqs)
        starts = starts or [0] * len(reqs)
        rows = [r.tokens[st:] for r, st in zip(reqs, starts)]
        toks = np.zeros((self.prefill_batch, bucket), np.int32)
        for i, row in enumerate(rows):
            toks[i, :len(row)] = row
        lengths = np.asarray([len(row) for row in rows] + [1] * n_pad,
                             np.int32)
        slots_arr = np.asarray(slots + [self._scratch_slot] * n_pad,
                               np.int32)
        temps = np.asarray([r.temperature for r in reqs] + [0.0] * n_pad,
                           np.float32)
        # effective budget mirrors the host retire predicate:
        # min(max_tokens, room left before max_len)
        budgets = np.asarray(
            [min(r.max_tokens, self.max_len - len(r.tokens)) for r in reqs]
            + [1] * n_pad, np.int32)
        eos = np.asarray(
            [-1 if r.eos_id is None else int(r.eos_id) for r in reqs]
            + [-1] * n_pad, np.int32)
        real_mask = np.asarray([True] * len(reqs) + [False] * n_pad)
        return toks, lengths, slots_arr, temps, budgets, eos, real_mask

    def _admit(self, reqs: List[GenRequest], bucket: int):
        if self.paged:
            self._admit_paged(reqs, bucket)
            return
        slots = [self._free_slots.pop(0) for _ in reqs]
        (toks, lengths, slots_arr, temps, budgets, eos,
         real_mask) = self._admit_arrays(reqs, bucket, slots)
        try:
            self.cache, self._state, first = self._prefill_fn(bucket)(
                self.params, self.cache, self._state, toks, lengths,
                slots_arr, temps, budgets, eos, real_mask)
        except BaseException as e:  # noqa: BLE001
            for r, s in zip(reqs, slots):
                self._free_slots.append(s)
                r.out.put(e)
                r.out.put(_FLUSH)
            return
        snapshot = {}
        for r, s in zip(reqs, slots):
            r.slot = s
            self._active[s] = r
            snapshot[s] = r
        if self._spec is not None:
            self._draft_prefill(reqs, slots)
        self._unfetched.append((first, snapshot, slots))
        self.steps += 1
        self._obs_admit(reqs)

    def _plan_pages(self, r: GenRequest):
        """Reserve pages for one request: reuse cached prefix pages, allocate
        private pages for the rest of prompt + generation budget.  Returns
        (reused_tokens, page_row) or None when the arena is full."""
        page = self.page_size
        total = min(len(r.tokens) + r.max_tokens + 1, self.max_len)
        reused, rpages = 0, []
        if self.prefix is not None:
            # always leave >= 1 prompt token for the prefill (logits
            # needed) — capped inside the lookup so the counters below
            # match the reuse actually granted
            reused, rpages = self.prefix.match_prefix(
                r.tokens, max_pages=(len(r.tokens) - 1) // page)
        need = -(-total // page) - len(rpages)
        private = self.allocator.alloc(need)
        if private is None and self.prefix is not None:
            self.prefix.evict_some(need * 2)
            private = self.allocator.alloc(need)
        if private is None:
            self.allocator.release(rpages)
            return None
        if self.prefix is not None:
            # counted only on a SUCCESSFUL plan: an arena-full requeue
            # retries this whole function and must not double-count
            self.prefix.count_lookup(reused)
            obs.record_prefix_lookup(r.deployment, reused > 0, reused)
        return reused, rpages + private

    def _admit_paged(self, reqs: List[GenRequest], bucket: int):
        import numpy as np
        planned = []
        for r in reqs:
            plan = self._plan_pages(r)
            if plan is None:
                # arena full: requeue and stop admitting (backpressure)
                self._pending.put(r)
                continue
            planned.append((r, plan))
        if not planned:
            return
        # suffix bucket: longest uncached suffix, padded
        sbucket = self._bucket_for(max(
            len(r.tokens) - reused for r, (reused, _pages) in planned))
        n_pad = self.prefill_batch - len(planned)
        preqs = [r for r, _plan in planned]
        slots = [self._free_slots.pop(0) for _ in planned]
        starts = [reused for _r, (reused, _pages) in planned]
        bt_rows = np.zeros((self.prefill_batch, self.max_pages_per_slot),
                           np.int32)
        for i, (r, (_reused, pages)) in enumerate(planned):
            r.pages = pages
            bt_rows[i, :len(pages)] = pages[:self.max_pages_per_slot]
        (toks, lengths, slots_arr, temps, budgets, eos,
         real_mask) = self._admit_arrays(preqs, sbucket, slots, starts)
        starts_arr = np.asarray(starts + [0] * n_pad, np.int32)
        try:
            self.cache, self._state, first = self._prefill_fn(sbucket)(
                self.params, self.cache, self._state, toks, lengths,
                slots_arr, starts_arr, bt_rows, temps, budgets, eos,
                real_mask)
        except BaseException as e:  # noqa: BLE001
            for (r, (_reused, pages)), s in zip(planned, slots):
                self._free_slots.append(s)
                self.allocator.release(pages)
                r.out.put(e)
                r.out.put(_FLUSH)
            return
        snapshot = {}
        for (r, (_reused, _pages)), s in zip(planned, slots):
            r.slot = s
            self._active[s] = r
            snapshot[s] = r
            if self.prefix is not None:
                # register this prompt's full pages for future reuse
                self.prefix.insert(r.tokens,
                                   r.pages[:len(r.tokens) // self.page_size])
        if self._spec is not None:
            self._draft_prefill(preqs, slots)
        self._unfetched.append((first, snapshot, slots))
        self.steps += 1
        self._obs_admit(preqs)

    def _dispatch_step(self):
        if self._spec is not None:
            k = self._spec_k_now()
            fn, rounds = self._spec_fn(k)
            res = fn(self.params, self.cache, self._draft_params,
                     self._draft_cache, self._state)
            self.cache = res["target_cache"]
            self._draft_cache = res["draft_cache"]
            self._state = res["state"]
            self._unfetched.append(
                ((res["tokens"], res["counts"], res["emit_counts"], k),
                 dict(self._active), "spec"))
            self.steps += rounds
            self.spec_dispatch_k[k] = self.spec_dispatch_k.get(k, 0) + 1
            return
        self.cache, self._state, emitted = self._decode_fn(
            self.params, self.cache, self._state)
        self._unfetched.append((emitted, dict(self._active), None))
        self.steps += self.steps_per_dispatch

    def _drain_spec(self, payload, snapshot):
        """Fetch one speculative dispatch: emit each slot's accepted
        window and fold the per-round emit counts into the acceptance
        tallies (a round's emit_count e in 1..k means e-1 drafts accepted
        + one verified correction; the k-1-e rejected drafts are the
        rollback)."""
        import numpy as np
        tokens_dev, counts_dev, round_counts_dev, k = payload
        tokens = np.asarray(tokens_dev)   # blocks until the dispatch ran
        counts = np.asarray(counts_dev)
        rounds = np.asarray(round_counts_dev)  # [num_rounds, slots]
        d_tok = d_round = d_draft = d_acc = 0
        for row in rounds:
            act = int((row > 0).sum())
            if not act:
                continue
            d_round += act
            d_tok += int(row.sum())
            d_draft += (k - 1) * act
            d_acc += int(np.minimum(np.maximum(row - 1, 0), k - 1).sum())
        self.spec_rounds += d_round
        self.spec_tokens += d_tok
        self.spec_drafted += d_draft
        self.spec_accepted += d_acc
        if d_round:
            obs.record_spec_dispatch(self._obs_dep, d_round, d_tok,
                                     d_draft, d_acc)
        now = time.monotonic()
        for s, r in snapshot.items():
            if r.slot != s or self._active.get(s) is not r:
                continue
            for j in range(int(counts[s])):
                if self._active.get(s) is not r:
                    break
                if r.first_token_at is None:
                    r.first_token_at = now
                self._emit(r, int(tokens[s, j]))

    def _drain_one(self):
        import numpy as np
        tokens_dev, snapshot, prefill_slots = self._unfetched.pop(0)
        if prefill_slots == "spec":
            self._drain_spec(tokens_dev, snapshot)
            return
        tokens = np.asarray(tokens_dev)   # blocks until the step finished
        now = time.monotonic()
        if prefill_slots is not None:
            # prefill entry: tokens is [len(slots)] in admit order
            for i, s in enumerate(prefill_slots):
                r = snapshot[s]
                r.first_token_at = now
                self._obs_first_token(r, now)
                self._emit(r, int(tokens[i]))
        else:
            # decode entry: [steps_per_dispatch, slots]
            for k in range(tokens.shape[0]):
                for s, r in snapshot.items():
                    if r.slot == s and self._active.get(s) is r:
                        self._emit(r, int(tokens[k, s]))

    def _emit(self, r: GenRequest, token: int):
        r.tokens.append(token)
        r.generated += 1
        self.tokens_out += 1
        r.out.put(token)
        done = (r.generated >= r.max_tokens
                or (r.eos_id is not None and token == r.eos_id)
                or len(r.tokens) >= self.max_len)
        if done:
            self._retire(r)

    def _retire(self, r: GenRequest):
        # No device write: the decode program decays `active` on device by
        # the same budget/EOS predicate the host applies in _emit, so the
        # device copy is already False by the time the host sees the final
        # token.  (An eager .at[].set here cost a tunnel round trip per
        # retired request.)
        if r.slot in self._active and self._active[r.slot] is r:
            del self._active[r.slot]
            self._free_slots.append(r.slot)
            self._obs_retire(r)
            if self.paged and r.pages:
                # refcounted: shared prefix pages survive on the prefix
                # cache's refs; private pages return to the free list.
                # In-flight decode steps may still write into released
                # pages, but every such position is re-written by its next
                # owner's prefill/decode before it becomes readable.
                self.allocator.release(r.pages)
                r.pages = []
        r.out.put(_FLUSH)


# ---------------------------------------------------------------------------
# Serve deployment
# ---------------------------------------------------------------------------

class LLMServer:
    """Streaming LLM endpoint: body {"tokens": [...], "max_tokens": N,
    "temperature": t} -> streamed token ids (one per chunk).

    Deploy via ``llm_deployment(...)``.
    """

    def __init__(self, preset: str = "tiny", num_slots: int = 8,
                 max_len: Optional[int] = None, seed: int = 0,
                 engine_kwargs: Optional[dict] = None):
        from ray_tpu.models import config as mcfg
        cfg = (mcfg.tiny() if preset == "tiny"
               else mcfg.PRESETS[preset]())
        self.engine = LLMEngine(cfg, num_slots=num_slots, max_len=max_len,
                                seed=seed, **(engine_kwargs or {}))

    async def __call__(self, request):
        """Async generator: polls the engine's token queue off-loop so one
        stream never blocks the replica's event loop (other streams, health
        checks and queue-length probes keep flowing)."""
        import asyncio

        body = request.json() if hasattr(request, "json") else request
        tokens = body["tokens"]
        req = self.engine.submit(
            tokens, max_tokens=int(body.get("max_tokens", 64)),
            temperature=float(body.get("temperature", 0.0)),
            eos_id=body.get("eos_id"))
        loop = asyncio.get_event_loop()
        while True:
            item = await loop.run_in_executor(None, req.out.get)
            if not isinstance(item, int):
                if isinstance(item, BaseException):
                    raise item
                return  # _FLUSH
            yield item

    def stats(self) -> dict:
        return {"steps": self.engine.steps,
                "tokens_out": self.engine.tokens_out,
                "active": len(self.engine._active),
                "free_slots": len(self.engine._free_slots),
                **self.engine.breakdown()}

    def prefix_digest(self) -> Optional[dict]:
        """Replica heartbeat hook (replica.py health_check attaches this
        next to the SLO snapshot): the engine's bounded first-page prefix
        digest for cache-aware routing.  Size-capped by the
        ``serve_prefix_digest_max`` knob; None (dense engine / prefix
        cache off) means the router uses pure p2c for this replica."""
        from ray_tpu.core.config import get_config
        cap = int(getattr(get_config(), "serve_prefix_digest_max", 32))
        return self.engine.prefix_digest(cap)


def llm_deployment(preset: str = "tiny", *, num_replicas: int = 1,
                   num_slots: int = 8, max_len: Optional[int] = None,
                   route_prefix: Optional[str] = None,
                   engine_kwargs: Optional[dict] = None, **options):
    """Build the Serve deployment for an LLM preset."""
    dep = serve_deployment(
        LLMServer, name=f"llm-{preset}", num_replicas=num_replicas,
        route_prefix=route_prefix, **options)
    return dep.bind(preset=preset, num_slots=num_slots, max_len=max_len,
                    engine_kwargs=engine_kwargs)
