"""Declarative Serve deploys: config file -> running applications.

Reference: ``python/ray/serve/schema.py`` (ServeDeploySchema /
ServeApplicationSchema) + ``serve/scripts.py`` (``serve deploy/run/status``).
A config is YAML or JSON:

.. code-block:: yaml

    applications:
      - name: adder
        import_path: my_pkg.apps:adder_app     # Deployment OR builder fn
        route_prefix: /adder
        args: {increment: 5}                    # kwargs for a builder fn
        deployments:                            # per-deployment overrides
          - name: Adder
            num_replicas: 2

``deploy_config`` builds each application (importing the target in-process,
like the reference's build step), applies overrides, and hands the result to
``serve.run``; re-deploying an updated config rolls deployments forward
through the controller's reconcile loop.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
from typing import Any, Dict, List, Optional

from .deployment import Deployment


def load_config(path: str) -> Dict[str, Any]:
    with open(path) as f:
        text = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml
        return yaml.safe_load(text)
    return json.loads(text)


def import_target(import_path: str):
    """``module.sub:attr`` -> the attribute (reference: import_attr)."""
    if ":" not in import_path:
        raise ValueError(
            f"import_path must look like 'module:attr', got {import_path!r}")
    mod_name, attr = import_path.split(":", 1)
    mod = importlib.import_module(mod_name)
    target = mod
    for part in attr.split("."):
        target = getattr(target, part)
    return target


def build_application(app_cfg: Dict[str, Any]) -> Deployment:
    """Resolve one application entry to a bound Deployment."""
    target = import_target(app_cfg["import_path"])
    if isinstance(target, Deployment):
        app = target
    elif callable(target):
        app = target(**(app_cfg.get("args") or {}))
        if not isinstance(app, Deployment):
            raise TypeError(
                f"builder {app_cfg['import_path']} returned "
                f"{type(app).__name__}, expected a Deployment")
    else:
        raise TypeError(f"{app_cfg['import_path']} is neither a Deployment "
                        "nor a builder callable")
    overrides = {d["name"]: d for d in app_cfg.get("deployments") or []}
    ov = overrides.get(app.name)
    cfg = app.config
    if ov:
        fields = {k: v for k, v in ov.items()
                  if k in {"num_replicas", "max_concurrent_queries",
                           "health_check_period_s",
                           "user_config"} and v is not None}
        # the DeploymentConfig field is `autoscaling`; the config-file key
        # keeps the reference's `autoscaling_config` spelling (a dict,
        # e.g. {policy: slo, ttft_p95_target_ms: 500})
        if ov.get("autoscaling_config") is not None:
            from .config import AutoscalingConfig
            ac = ov["autoscaling_config"]
            fields["autoscaling"] = (
                ac if isinstance(ac, AutoscalingConfig)
                else AutoscalingConfig(**ac))
        cfg = dataclasses.replace(cfg, **fields)
    if app_cfg.get("route_prefix"):
        cfg = dataclasses.replace(cfg, route_prefix=app_cfg["route_prefix"])
    return dataclasses.replace(app, config=cfg)


def deploy_config(config: Dict[str, Any], *, blocking: bool = True,
                  timeout_s: float = 120.0) -> List[str]:
    """Deploy every application in the config; returns deployed app names."""
    from . import api as serve_api

    apps = config.get("applications")
    if not apps:
        raise ValueError("config has no 'applications' list")
    names = []
    for app_cfg in apps:
        app = build_application(app_cfg)
        serve_api.run(app, route_prefix=app.config.route_prefix
                      or f"/{app.name}", timeout_s=timeout_s,
                      _blocking=blocking)
        names.append(app_cfg.get("name", app.name))
    return names


def status_summary() -> Dict[str, Any]:
    """Deployment-status map for `serve status` / GET /api/serve."""
    from . import api as serve_api
    try:
        return serve_api.status()
    except Exception:
        return {}
