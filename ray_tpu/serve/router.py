"""Router + DeploymentHandle: replica selection and the calling surface.

Reference: ``python/ray/serve/_private/router.py:1191`` (Router),
``:328`` (PowerOfTwoChoicesReplicaScheduler), ``serve/handle.py:305``
(RayServeHandle).  Scheduling is power-of-two-choices over (local in-flight
count + last-known replica queue length): pick two random replicas, route to
the less loaded.  Replica death triggers local eviction + a routing-table
refresh; calls retry on another replica.
"""

from __future__ import annotations

import asyncio
import hashlib
import random
import re
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Sequence, Tuple

import ray_tpu
from ray_tpu.core.common import (ActorDiedError, ActorUnavailableError,
                                 TaskError)

CONTROLLER_NAME = "serve:controller"


_DRAIN_REJECT = re.compile(r"^replica \S+ is draining$")


def is_retryable_failure(e: BaseException) -> bool:
    """A request may be transparently re-routed when the failure is about the
    *replica*, not the request: the replica died, became unreachable, or
    rejected the request because it is draining (rolling update / scale-down).

    Matching is deliberately narrow — application exceptions that merely
    *mention* draining or death must surface to the caller, not trigger a
    silent re-execution."""
    if isinstance(e, (ActorDiedError, ActorUnavailableError)):
        return True
    if isinstance(e, TaskError):
        cause = e.cause
        if isinstance(cause, (ActorDiedError, ActorUnavailableError)):
            return True
        # ReplicaActor's own drain rejection (replica.py raises exactly this)
        if isinstance(cause, RuntimeError) and _DRAIN_REJECT.match(str(cause)):
            return True
        # _strip_exc repackages unpicklable errors as
        # RuntimeError("<TypeName>: <msg>") — recognize repackaged death
        if isinstance(cause, RuntimeError) and str(cause).startswith(
                ("ActorDiedError:", "ActorUnavailableError:")):
            return True
    return False


def _controller():
    return ray_tpu.get_actor(CONTROLLER_NAME)


def _block_hash(tokens: Sequence[int], page: int) -> str:
    """First-page block hash, truncated exactly as the replica digest is:
    MUST stay in lockstep with PrefixCache._hash (4-byte-LE token stream,
    16-byte blake2b) + first_page_digest's hex[:8] — a drift here silently
    turns every routing decision into a miss."""
    return hashlib.blake2b(
        b"".join(int(t).to_bytes(4, "little") for t in tokens[:page]),
        digest_size=16).digest().hex()[:8]


def _hint_tokens(args: tuple, kwargs: dict) -> Optional[list]:
    """Prompt tokens for cache-aware routing, when the payload looks like
    an LLM request ({"tokens": [...]} first arg, or a tokens= kwarg).
    Anything else — HTTP Request objects, non-LLM deployments — yields no
    hint and the router stays pure p2c."""
    cand = None
    if args and isinstance(args[0], dict):
        cand = args[0].get("tokens")
    if cand is None:
        cand = kwargs.get("tokens")
    if isinstance(cand, (list, tuple)) and cand \
            and all(isinstance(t, int) for t in cand[:4]):
        return list(cand)
    return None


class Router:
    """Caches the controller's routing table; assigns requests to replicas."""

    def __init__(self, refresh_interval_s: float = 0.5):
        self.refresh_interval_s = refresh_interval_s
        self._table: Dict[str, List[str]] = {}       # deployment -> replica names
        self._handles: Dict[str, Any] = {}           # replica name -> handle
        self._inflight: Dict[str, int] = {}          # replica name -> local count
        self._dep_inflight: Dict[str, int] = {}      # queue-depth gauge feed
        #: replica name -> (page_size, frozenset of first-page block
        #: hashes) from the controller's heartbeat-fed digest view; absent
        #: entries (non-LLM replicas, stale heartbeats, routing disabled)
        #: fall back to pure p2c
        self._digests: Dict[str, Tuple[int, frozenset]] = {}
        self._last_refresh = 0.0
        self._table_version = -1
        self._lock = threading.Lock()

    def _track(self, deployment: str, delta: int):
        from . import observability as obs
        if not obs.enabled():  # kill switch sheds the lock + bookkeeping too
            return
        # under _lock, including the gauge publish: increments come from N
        # client threads while decrements run in as_future done-callbacks —
        # an unlocked RMW would lose updates, and publishing outside the
        # lock could land a stale value last and pin the gauge there
        with self._lock:
            n = max(0, self._dep_inflight.get(deployment, 0) + delta)
            self._dep_inflight[deployment] = n
            obs.set_router_queue_depth(deployment, n)

    # ------------------------------------------------------------ table

    def _refresh(self, force: bool = False):
        now = time.monotonic()
        if not force and now - self._last_refresh < self.refresh_interval_s:
            return
        ctrl = _controller()
        if self._prefix_routing_enabled():
            version, table, digests = ray_tpu.get(
                ctrl.get_routing_info.remote(), timeout=30)
        else:
            version, table = ray_tpu.get(
                ctrl.get_routing_table.remote(), timeout=30)
            digests = {}
        with self._lock:
            self._last_refresh = now
            # digests refresh every poll (they age independently of table
            # membership — a version check would freeze them)
            self._digests = {
                name: (int(d.get("page", 0)),
                       frozenset(d.get("blocks") or ()))
                for name, d in digests.items()
                if isinstance(d, dict) and d.get("page")}
            if version != self._table_version:
                self._table_version = version
                self._table = table
                live = {r for reps in table.values() for r in reps}
                self._handles = {k: v for k, v in self._handles.items()
                                 if k in live}

    @staticmethod
    def _prefix_routing_enabled() -> bool:
        from ray_tpu.core.config import get_config
        return bool(getattr(get_config(), "serve_prefix_routing_enabled",
                            True))

    def _replica_handle(self, replica_name: str):
        h = self._handles.get(replica_name)
        if h is None:
            h = ray_tpu.get_actor(replica_name)
            self._handles[replica_name] = h
        return h

    def _evict(self, deployment: str, replica_name: str):
        with self._lock:
            if replica_name in self._table.get(deployment, []):
                self._table[deployment].remove(replica_name)
            self._handles.pop(replica_name, None)

        def _report():
            try:
                _controller().report_replica_failure.remote(deployment,
                                                            replica_name)
            except Exception:
                pass

        # _evict also fires from ref done-callbacks, which run ON the IO
        # loop thread — get_actor's blocking GCS round-trip would raise in
        # run_async there (silently dropping the report).  Evictions are
        # rare; a short-lived thread keeps the report path thread-agnostic.
        if threading.current_thread().name == "raytpu-io":
            threading.Thread(target=_report, daemon=True,
                             name="router-evict-report").start()
        else:
            _report()

    # ------------------------------------------------------- p2c selection

    def choose_replica(self, deployment: str,
                       hint_tokens: Optional[Sequence[int]] = None) -> str:
        self._refresh()
        replicas = self._table.get(deployment)
        if not replicas:
            self._refresh(force=True)
            replicas = self._table.get(deployment)
            if not replicas:
                raise RuntimeError(f"no replicas for deployment "
                                   f"{deployment!r} (not deployed or scaled "
                                   f"to zero)")
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        la, lb = self._inflight.get(a, 0), self._inflight.get(b, 0)
        p2c = a if la <= lb else b
        if hint_tokens is None or not self._prefix_routing_enabled():
            return p2c
        return self._score_candidates(deployment, (a, la), (b, lb), p2c,
                                      hint_tokens)

    def _score_candidates(self, deployment: str, ca, cb, p2c: str,
                          hint_tokens: Sequence[int]) -> str:
        """Prefix-overlap x load scoring over the two p2c candidates:
        ``score = (inflight + 1) * (1 - weight * hit)`` where ``hit`` is
        membership of the request's first-page block hash in the
        candidate's heartbeat digest.  Absent digests on both candidates
        mean no signal — pure p2c, recorded as ``fallback``.  Ties keep
        the p2c pick so weight=0 degrades to exactly today's behavior."""
        from . import observability as obs
        from ray_tpu.core.config import get_config
        (a, la), (b, lb) = ca, cb
        da, db = self._digests.get(a), self._digests.get(b)
        if da is None and db is None:
            obs.record_prefix_route(deployment, "fallback")
            return p2c
        w = min(1.0, max(0.0, float(getattr(
            get_config(), "serve_prefix_routing_weight", 0.5))))
        hashes: Dict[int, str] = {}  # page size -> request block hash

        def hit(load_digest) -> bool:
            if load_digest is None:
                return False
            page, blocks = load_digest
            if len(hint_tokens) < page:
                return False  # no full first page -> nothing reusable
            if page not in hashes:
                hashes[page] = _block_hash(hint_tokens, page)
            return hashes[page] in blocks
        ha, hb = hit(da), hit(db)
        sa = (la + 1) * (1.0 - w * ha)
        sb = (lb + 1) * (1.0 - w * hb)
        if sa == sb:
            chosen, was_hit = p2c, (ha if p2c == a else hb)
        elif sa < sb:
            chosen, was_hit = a, ha
        else:
            chosen, was_hit = b, hb
        obs.record_prefix_route(deployment, "hit" if was_hit else "miss")
        return chosen

    # ------------------------------------------------------------- calling

    def assign(self, deployment: str, args: tuple, kwargs: dict,
               method: Optional[str] = None):
        """Route one request; returns (replica_name, result ObjectRef).

        A replica whose name no longer resolves (actor died and was
        deregistered) is evicted and the request re-routed."""
        last_err: Optional[Exception] = None
        hint = _hint_tokens(args, kwargs)
        for _ in range(5):
            name = self.choose_replica(deployment, hint_tokens=hint)
            try:
                h = self._replica_handle(name)
                ref = h.handle_request.remote(args, kwargs, method)
            except Exception as e:  # noqa: BLE001 — dead name, submit fail
                last_err = e
                self._evict(deployment, name)
                continue
            self._inflight[name] = self._inflight.get(name, 0) + 1
            self._track(deployment, +1)
            self._attach_done(ref, deployment, name)
            return name, ref
        raise last_err or RuntimeError("routing failed")

    def _attach_done(self, ref, deployment: str, name: str):
        fut = ray_tpu.as_future(ref)

        def _done(f):
            self._inflight[name] = max(0, self._inflight.get(name, 1) - 1)
            self._track(deployment, -1)
            exc = f.exception()
            if isinstance(exc, (ActorDiedError, ActorUnavailableError)):
                self._evict(deployment, name)

        fut.add_done_callback(_done)

    def start_stream(self, deployment: str, args: tuple, kwargs: dict,
                     method: Optional[str] = None) -> tuple:
        """Kick off a streaming request; returns (replica_name, stream_id,
        completion ref)."""
        last: Optional[Exception] = None
        hint = _hint_tokens(args, kwargs)
        for _ in range(5):
            name = self.choose_replica(deployment, hint_tokens=hint)
            stream_id = uuid.uuid4().hex
            try:
                h = self._replica_handle(name)
                ref = h.handle_request_streaming.remote(stream_id, args,
                                                        kwargs, method)
                # streams count toward p2c load + the queue-depth gauge
                # like unary calls — long-lived LLM streams are exactly
                # the traffic the SLO signal must see; the completion ref
                # resolves when the generator finishes, releasing both
                self._inflight[name] = self._inflight.get(name, 0) + 1
                self._track(deployment, +1)
                self._attach_done(ref, deployment, name)
                return name, stream_id, ref
            except Exception as e:  # noqa: BLE001
                last = e
                self._evict(deployment, name)
        raise last or RuntimeError("routing failed")


_router: Optional[Router] = None
_router_lock = threading.Lock()


def get_router() -> Router:
    global _router
    with _router_lock:
        if _router is None:
            _router = Router()
        return _router


def reset_router():
    global _router
    with _router_lock:
        _router = None


class DeploymentResponse:
    """The result of ``handle.remote(...)`` (reference: serve/handle.py
    DeploymentResponse).  Submission is eager; ``result()`` blocks and
    transparently re-routes to another replica if the assigned one died
    before/while executing (at-least-once on replica death)."""

    def __init__(self, deployment: str, args: tuple, kwargs: dict,
                 method: Optional[str]):
        self.deployment = deployment
        self._args = args
        self._kwargs = kwargs
        self._method = method
        self._replica, self._ref = get_router().assign(
            deployment, args, kwargs, method)

    def result(self, timeout_s: float = 60.0):
        deadline = time.monotonic() + timeout_s
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                return ray_tpu.get(self._ref,
                                   timeout=max(0.1, deadline -
                                               time.monotonic()))
            except BaseException as e:  # noqa: BLE001
                if not is_retryable_failure(e):
                    raise
                last = e
                get_router()._evict(self.deployment, self._replica)
                self._replica, self._ref = get_router().assign(
                    self.deployment, self._args, self._kwargs, self._method)
        raise last or TimeoutError(
            f"no result from {self.deployment} in {timeout_s}s")

    async def result_async(self, timeout_s: float = 60.0):
        """Awaitable result() — for deployment-to-deployment calls inside
        async replica code (blocking would starve the replica's loop).
        Resolution is scheduled on the worker's RPC loop via ``as_future``
        (the replica's actor loop must not touch loop-bound RPC state)."""
        import asyncio
        deadline = time.monotonic() + timeout_s
        last: Optional[BaseException] = None
        while time.monotonic() < deadline:
            try:
                fut = ray_tpu.as_future(self._ref)
                return await asyncio.wait_for(
                    asyncio.wrap_future(fut),
                    max(0.1, deadline - time.monotonic()))
            except BaseException as e:  # noqa: BLE001
                if not is_retryable_failure(e):
                    raise
                last = e
                get_router()._evict(self.deployment, self._replica)
                self._replica, self._ref = get_router().assign(
                    self.deployment, self._args, self._kwargs, self._method)
        raise last or TimeoutError(
            f"no result from {self.deployment} in {timeout_s}s")

    def _to_object_ref(self):
        """The underlying ObjectRef (no retry semantics)."""
        return self._ref


class DeploymentHandle:
    """Calling surface for a deployment (reference: serve/handle.py:305).

    ``h.remote(...)`` returns a DeploymentResponse (``.result()`` it);
    ``h.method.remote(...)`` routes to a named method;
    ``h.stream(...)`` yields chunks from a generator endpoint.
    """

    def __init__(self, deployment: str, method: Optional[str] = None):
        self.deployment = deployment
        self.method = method

    def __getattr__(self, item: str) -> "DeploymentHandle":
        if item.startswith("_"):
            raise AttributeError(item)
        return DeploymentHandle(self.deployment, item)

    def remote(self, *args, **kwargs) -> DeploymentResponse:
        return DeploymentResponse(self.deployment, args, kwargs, self.method)

    def stream(self, *args, timeout_s: Optional[float] = None, **kwargs):
        """Synchronous chunk iterator over a streaming endpoint.

        ``timeout_s`` bounds the WHOLE stream: a replica that stops
        yielding without erroring (wedged engine, lost stream buffer)
        would otherwise pin the consumer in the next_chunks long-poll
        forever — open-loop load harnesses pass this so one wedged
        request cannot hang a whole benchmark run."""
        deadline = (None if timeout_s is None
                    else time.monotonic() + timeout_s)
        router = get_router()
        name, stream_id, ref = router.start_stream(self.deployment, args,
                                                   kwargs, self.method)
        h = router._replica_handle(name)
        cursor, done = 0, False
        while not done:
            poll_timeout = 60.0
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    # abandon server-side too: an unclaimed buffer would
                    # block the replica's graceful drain forever
                    try:
                        h.cancel_stream.remote(stream_id)
                    except Exception:
                        pass
                    raise TimeoutError(f"stream from {self.deployment!r} "
                                       f"exceeded {timeout_s}s")
                poll_timeout = min(poll_timeout, remaining + 1.0)
            try:
                chunks, cursor, done = ray_tpu.get(
                    h.next_chunks.remote(stream_id, cursor),
                    timeout=poll_timeout)
            except Exception:
                # a WEDGED replica never returns the long-poll at all —
                # the bounded get converts that into the same abandon
                # path instead of overshooting the budget by 60s
                if deadline is not None and time.monotonic() >= deadline:
                    try:
                        h.cancel_stream.remote(stream_id)
                    except Exception:
                        pass
                    raise TimeoutError(
                        f"stream from {self.deployment!r} exceeded "
                        f"{timeout_s}s") from None
                raise
            yield from chunks
        # surface errors from the generator body
        ray_tpu.get(ref, timeout=60)

    async def stream_async(self, *args, **kwargs):
        router = get_router()
        name, stream_id, ref = router.start_stream(self.deployment, args,
                                                   kwargs, self.method)
        h = router._replica_handle(name)
        cursor, done = 0, False
        while not done:
            chunks, cursor, done = await asyncio.wrap_future(
                ray_tpu.as_future(h.next_chunks.remote(stream_id, cursor)))
            for c in chunks:
                yield c
        await asyncio.wrap_future(ray_tpu.as_future(ref))
