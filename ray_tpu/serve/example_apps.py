"""Example serve applications for config-file deploys and tests
(reference: serve/tests/test_config_files/*)."""

from __future__ import annotations

from .deployment import deployment


@deployment
class Echo:
    """Returns its input unchanged."""

    async def __call__(self, request):
        return request


echo_app = Echo.bind()


def adder_app(increment: int = 1):
    """Builder-function style application (``import_path`` with args)."""

    @deployment(name="Adder")
    class Adder:
        def __init__(self, inc: int):
            self.inc = inc

        async def __call__(self, request):
            return request + self.inc

    return Adder.bind(increment)
