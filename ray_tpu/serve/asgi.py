"""ASGI ingress: mount an existing web application on a deployment.

Reference: ``python/ray/serve/api.py:194`` (``@serve.ingress(app)``) — users
bring an app that owns routing/middleware/docs and Serve mounts it behind
the proxy at the deployment's route prefix.  The reference takes a FastAPI
object; here ``ingress`` accepts ANY ASGI-3 callable ``app(scope, receive,
send)`` (starlette/FastAPI are not in this image — the bundled ``ASGIApp``
mini-framework below provides decorator routing + middleware so apps can be
written offline, but anything speaking ASGI works).

How it plugs in: the decorated class's ``__call__`` becomes an async
GENERATOR that drives the ASGI app and yields an ``ASGIStart`` (status +
headers) followed by body chunks as the app ``send``s them.  The replica's
native streaming-generator path ships each chunk the moment it is yielded,
and the HTTP proxy applies ``ASGIStart`` before preparing the chunked
response — so ASGI streaming responses (SSE and friends) stream end to end.
The replica instance is exposed to the app as ``scope["state"]["replica"]``
(the reference exposes it via FastAPI dependency injection).
"""

from __future__ import annotations

import asyncio
import functools
import json as _json
import re
from typing import Any, Callable, Dict, List, Optional, Tuple
from urllib.parse import urlencode

from .replica import Request


class ASGIStart:
    """First item of a streamed ASGI response: status + headers."""

    __slots__ = ("status", "headers")

    def __init__(self, status: int, headers: List[Tuple[str, str]]):
        self.status = status
        self.headers = headers

    def __repr__(self):
        return f"ASGIStart({self.status}, {self.headers!r})"


def _scope_for(request: Request, state: Optional[dict]) -> dict:
    q = urlencode(request.query) if request.query else ""
    return {
        "type": "http",
        "asgi": {"version": "3.0", "spec_version": "2.3"},
        "http_version": "1.1",
        "method": request.method,
        "scheme": "http",
        "path": request.path,
        "raw_path": request.path.encode(),
        "root_path": "",
        "query_string": q.encode(),
        "headers": [(k.lower().encode(), str(v).encode())
                    for k, v in request.headers.items()],
        "client": ("127.0.0.1", 0),
        "server": ("127.0.0.1", 0),
        "state": dict(state or {}),
    }


async def run_asgi(app: Callable, request: Request,
                   state: Optional[dict] = None):
    """Drive ONE HTTP request through an ASGI app.

    Async generator: yields ``ASGIStart`` once, then body ``bytes`` chunks
    in ``send`` order.  The app runs concurrently so a streaming app's
    chunks flow out before it returns.
    """
    scope = _scope_for(request, state)
    body = request.body or b""
    delivered = False

    async def receive():
        nonlocal delivered
        if not delivered:
            delivered = True
            return {"type": "http.request", "body": body, "more_body": False}
        return {"type": "http.disconnect"}

    out: asyncio.Queue = asyncio.Queue()

    async def send(message):
        await out.put(message)

    loop = asyncio.get_event_loop()
    app_task = loop.create_task(app(scope, receive, send))
    try:
        finished = False
        while not finished:
            q_get = loop.create_task(out.get())
            done, _ = await asyncio.wait(
                {q_get, app_task}, return_when=asyncio.FIRST_COMPLETED)
            msgs = []
            if q_get in done:
                msgs.append(q_get.result())
            else:
                q_get.cancel()
                exc = app_task.exception()
                if exc is not None:
                    raise exc
                while not out.empty():
                    msgs.append(out.get_nowait())
                finished = True
            for msg in msgs:
                t = msg.get("type")
                if t == "http.response.start":
                    yield ASGIStart(
                        int(msg.get("status", 200)),
                        [(k.decode(), v.decode())
                         for k, v in msg.get("headers", [])])
                elif t == "http.response.body":
                    chunk = msg.get("body", b"")
                    if chunk:
                        yield chunk
                    if not msg.get("more_body", False):
                        await app_task
                        finished = True
                        break
    finally:
        if not app_task.done():
            app_task.cancel()


def ingress(asgi_app: Callable):
    """Class decorator mounting an ASGI app on a deployment.

    Usage (reference api.py:194 shape)::

        app = ASGIApp()          # or any ASGI callable

        @serve.deployment
        @serve.ingress(app)
        class Site:
            def __init__(self): self.hits = 0

    Every HTTP request routed to the deployment flows through ``asgi_app``;
    the instance is ``scope["state"]["replica"]``.
    """
    def decorator(cls: Optional[type] = None):
        if cls is None:
            cls = object

        class _ASGIIngress(cls):  # type: ignore[valid-type,misc]
            __serve_asgi_app__ = asgi_app

            async def __call__(self, request: Request):
                async for item in run_asgi(
                        asgi_app, request, {"replica": self}):
                    yield item

        functools.update_wrapper(_ASGIIngress, cls, updated=[])
        _ASGIIngress.__name__ = getattr(cls, "__name__", "ASGIIngress")
        _ASGIIngress.__qualname__ = _ASGIIngress.__name__
        return _ASGIIngress
    return decorator


# --------------------------------------------------------------------------
# Minimal ASGI application framework (offline stand-in for starlette).


class ASGIRequest:
    """What ASGIApp handlers receive: parsed scope + buffered body."""

    def __init__(self, scope: dict, body: bytes):
        self.scope = scope
        self.method = scope.get("method", "GET")
        self.path = scope.get("path", "/")
        self.headers = {k.decode(): v.decode()
                        for k, v in scope.get("headers", [])}
        self.query = {}
        qs = scope.get("query_string", b"").decode()
        if qs:
            from urllib.parse import parse_qsl
            self.query = dict(parse_qsl(qs))
        self.body = body
        self.path_params: Dict[str, str] = {}
        self.state = scope.get("state", {})

    def json(self):
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


class ASGIApp:
    """Tiny ASGI-3 app: decorator routing (with ``{param}`` segments),
    middleware chain, JSON/text/bytes/stream responses.

    Handlers: ``async def h(req: ASGIRequest)`` returning ``dict`` (JSON),
    ``str``/``bytes``, ``(status, payload)``, or an async generator
    (streamed chunks).  Middleware: ``async def mw(req, call_next)`` where
    ``await call_next(req)`` yields the downstream ``(status, headers,
    payload_or_gen)`` triple — it can short-circuit or mutate either side.
    """

    def __init__(self):
        self._routes: List[Tuple[set, re.Pattern, list, Callable]] = []
        self._middleware: List[Callable] = []

    def route(self, path: str, methods=("GET",)):
        # literal segments regex-escaped; only {param} groups match wild
        parts = re.split(r"{(\w+)}", path.rstrip("/") or "/")
        names = parts[1::2]
        pat = re.compile(
            "^" + "".join(re.escape(p) if i % 2 == 0 else r"([^/]+)"
                          for i, p in enumerate(parts)) + "$")

        def deco(fn):
            self._routes.append(
                ({m.upper() for m in methods}, pat, names, fn))
            return fn
        return deco

    def get(self, path: str):
        return self.route(path, ("GET",))

    def post(self, path: str):
        return self.route(path, ("POST",))

    def middleware(self, fn: Callable):
        self._middleware.append(fn)
        return fn

    # ------------------------------------------------------------ dispatch

    @staticmethod
    def _normalize(result: Any) -> Tuple[int, list, Any]:
        status, payload = 200, result
        if (isinstance(result, tuple) and len(result) == 2
                and isinstance(result[0], int)):
            status, payload = result
        if hasattr(payload, "__aiter__"):
            return status, [("content-type", "text/plain; charset=utf-8")], \
                payload
        if isinstance(payload, (dict, list)):
            return status, [("content-type", "application/json")], \
                _json.dumps(payload).encode()
        if isinstance(payload, str):
            return status, [("content-type", "text/plain; charset=utf-8")], \
                payload.encode()
        if payload is None:
            payload = b""
        return status, [("content-type", "application/octet-stream")], \
            payload

    async def _dispatch(self, req: ASGIRequest) -> Tuple[int, list, Any]:
        for methods, pat, names, fn in self._routes:
            m = pat.match(req.path.rstrip("/") or "/")
            if m and req.method.upper() in methods:
                req.path_params = dict(zip(names, m.groups()))
                out = fn(req)
                if asyncio.iscoroutine(out):
                    out = await out
                return self._normalize(out)
        return 404, [("content-type", "text/plain")], \
            f"no route for {req.method} {req.path}".encode()

    async def __call__(self, scope, receive, send):
        if scope["type"] != "http":  # lifespan etc.: ignore politely
            return
        chunks = []
        while True:
            msg = await receive()
            if msg["type"] != "http.request":
                break
            chunks.append(msg.get("body", b""))
            if not msg.get("more_body", False):
                break
        req = ASGIRequest(scope, b"".join(chunks))

        call = self._dispatch
        for mw in reversed(self._middleware):
            call = functools.partial(mw, call_next=call)
        try:
            status, headers, payload = await call(req)
        except Exception as e:  # noqa: BLE001 — app-level 500
            status, headers, payload = 500, \
                [("content-type", "text/plain")], repr(e).encode()
        await send({"type": "http.response.start", "status": status,
                    "headers": [(k.lower().encode(), str(v).encode())
                                for k, v in headers]})
        if hasattr(payload, "__aiter__"):
            async for chunk in payload:
                if isinstance(chunk, str):
                    chunk = chunk.encode()
                elif not isinstance(chunk, (bytes, bytearray)):
                    chunk = (_json.dumps(chunk) + "\n").encode()
                await send({"type": "http.response.body", "body": bytes(chunk),
                            "more_body": True})
            await send({"type": "http.response.body", "body": b"",
                        "more_body": False})
        else:
            await send({"type": "http.response.body", "body": payload,
                        "more_body": False})
