"""ray_tpu.serve — model serving on the actor substrate.

Capabilities of Ray Serve (reference: ``python/ray/serve/``): deployments as
reconciled replica actor sets, rolling updates, health-driven replacement,
queue-depth autoscaling, power-of-two-choices routing, dynamic batching,
streaming responses, an HTTP ingress with ASGI-app mounting
(``@serve.ingress`` — any ASGI-3 callable, routes/middleware/SSE), and a
gRPC ingress (``grpc_proxy.py``, schema in ``protos/serve.proto``) — plus
a TPU-first continuous-batching LLM deployment (``ray_tpu.serve.llm``).
"""

from .api import (autoscale_decisions, delete, get_deployment_handle,
                  grpc_config, http_config, run, shutdown, slo_signal, start,
                  status)
from .asgi import ASGIApp, ASGIRequest, ingress
from .batching import batch
from .multiplex import get_multiplexed_model_id, multiplexed
from .config import AutoscalingConfig, DeploymentConfig
from .deployment import Deployment, deployment
from .graph import DAGDriver
from .replica import Request
from .router import DeploymentHandle

__all__ = [
    "deployment", "Deployment", "DeploymentConfig", "AutoscalingConfig",
    "DeploymentHandle", "Request", "batch", "run", "start", "status",
    "delete", "shutdown", "get_deployment_handle", "http_config",
    "multiplexed", "get_multiplexed_model_id", "DAGDriver",
    "ingress", "ASGIApp", "ASGIRequest", "grpc_config", "slo_signal",
    "autoscale_decisions",
]

# Usage telemetry: which libraries a cluster actually uses (reference:
# usage_lib.record_library_usage at import time).  Never raises.
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("serve")
del _rlu
