"""Replica actor: wraps the user callable, serves requests, reports health +
queue depth, supports streaming and graceful drain.

Reference: ``python/ray/serve/_private/replica.py`` (RayServeReplica).  Runs as
an async actor with ``max_concurrency = max_concurrent_queries`` so requests
interleave on the replica's event loop; ``num_ongoing`` is both the router's
power-of-two-choices signal and the autoscaler's input.
"""

from __future__ import annotations

import asyncio
import inspect
import time
from typing import Any, Dict, Optional

import cloudpickle

from . import observability as obs


class Request:
    """Lightweight HTTP request container handed to deployments that take one
    (the reference hands a starlette Request; same role)."""

    __slots__ = ("method", "path", "query", "headers", "body")

    def __init__(self, method: str = "POST", path: str = "/", query=None,
                 headers=None, body: bytes = b""):
        self.method = method
        self.path = path
        self.query = dict(query or {})
        self.headers = dict(headers or {})
        self.body = body

    def json(self):
        import json
        return json.loads(self.body or b"null")

    def text(self) -> str:
        return (self.body or b"").decode()


class ReplicaActor:
    """The actor class every replica runs (created by the controller)."""

    def __init__(self, deployment_name: str, replica_id: str, app_blob: bytes,
                 user_config: Any = None):
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        func_or_class, init_args, init_kwargs = cloudpickle.loads(app_blob)
        if inspect.isclass(func_or_class):
            self.callable = func_or_class(*init_args, **init_kwargs)
            self._entry = None  # resolve per request (method or __call__)
        else:
            self.callable = None
            self._fn = func_or_class
        self.num_ongoing = 0
        self.num_processed = 0
        self._draining = False
        self.started_at = time.time()
        self._streams: Dict[str, list] = {}
        self._stream_done: Dict[str, bool] = {}
        #: streams the CLIENT abandoned (stream timeout) -> cancel ts:
        #: the generator stops buffering and the finally path must not
        #: resurrect the done-flag entry — an unclaimed buffer would
        #: block drain() forever and leak per-stream memory.  A dict
        #: (not a set) so tombstones that are never consumed (cancel
        #: raced a completed-and-popped stream; ids are fresh uuids) age
        #: out instead of accumulating for the replica's lifetime.
        self._cancelled_streams: Dict[str, float] = {}
        if user_config is not None:
            self._apply_user_config(user_config)

    # ------------------------------------------------------- observability

    def _obs_begin(self):
        """Per-request instrumentation entry: install the event-loop stall
        monitor once (this runs ON the actor loop — __init__ does not),
        publish queue depth, and tag downstream instrumentation
        (@serve.batch, the LLM engine) with this deployment's config
        name.  Returns (t0, ctx token) for _obs_end."""
        obs.ensure_loop_monitor(
            self, f"serve_replica:{self.deployment_name}")
        obs.set_replica_queue_depth(self.deployment_name, self.num_ongoing)
        return time.monotonic(), obs.set_current_deployment(
            self.deployment_name)

    def _obs_end(self, begin, first_token_at: Optional[float] = None,
                 ok: bool = True, window: bool = True):
        """Request done: one TTFT sample into the histogram + rolling SLO
        window (streaming requests pass their first-chunk time; unary
        requests' TTFT is their full latency — the first response byte).
        Failed requests don't feed anything (an instant exception is not a
        fast first token — it would drag the SLO percentiles DOWN exactly
        when the deployment is misbehaving), and named-method calls
        (``window=False``: h.stats.remote() and other introspection/
        control routes) skip the WINDOW so fast non-inference polls can't
        mask real serving degradation — they still land in the TTFT
        histogram under the same deployment tag."""
        t0, token = begin
        obs.set_replica_queue_depth(self.deployment_name, self.num_ongoing)
        if ok:
            obs.observe_ttft(self.deployment_name,
                             (first_token_at if first_token_at is not None
                              else time.monotonic()) - t0,
                             window=window)
        # last: the ctx reset is the one step that can be running inside
        # asyncgen finalization (foreign context) — nothing may depend on it
        obs.reset_current_deployment(token)

    # ------------------------------------------------------------- serving

    def _resolve(self, method: Optional[str]):
        if self.callable is None:
            return self._fn
        target = self.callable
        if method:
            return getattr(target, method)
        if callable(target):
            return target.__call__
        raise AttributeError(f"{type(target)} is not callable; specify method")

    async def handle_request(self, args: tuple, kwargs: dict,
                             method: Optional[str] = None) -> Any:
        if self._draining:
            raise RuntimeError(f"replica {self.replica_id} is draining")
        self.num_ongoing += 1
        begin = self._obs_begin()
        ok = False
        try:
            if args and isinstance(args[0], Request):
                from .multiplex import _set_current_model_id
                _set_current_model_id(args[0])
            fn = self._resolve(method)
            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            if inspect.isgenerator(out) or inspect.isasyncgen(out):
                raise TypeError(
                    "streaming responses go through handle_request_streaming")
            ok = True
            return out
        finally:
            self.num_ongoing -= 1
            self.num_processed += 1
            self._obs_end(begin, ok=ok, window=method is None)

    async def handle_request_streaming(self, stream_id: str, args: tuple,
                                       kwargs: dict,
                                       method: Optional[str] = None) -> None:
        """Run a (async) generator endpoint, buffering chunks for the caller
        to drain via next_chunks() — streaming over the actor RPC plane."""
        if stream_id in self._cancelled_streams:
            # cancel raced ahead of a queued start: never register (and
            # consume the tombstone BEFORE the draining check — either
            # refusal must not leave it behind)
            self._cancelled_streams.pop(stream_id, None)
            raise RuntimeError(f"stream {stream_id} cancelled before start")
        if self._draining:
            raise RuntimeError(f"replica {self.replica_id} is draining")
        self.num_ongoing += 1
        self._streams[stream_id] = []
        self._stream_done[stream_id] = False
        begin = self._obs_begin()
        first_at: Optional[float] = None
        ok = False
        try:
            fn = self._resolve(method)
            out = fn(*args, **kwargs)

            def buf():
                # None once the client cancelled (stream timeout): stop
                # generating instead of appending into a popped buffer
                return self._streams.get(stream_id)

            if inspect.isasyncgen(out):
                async for chunk in out:
                    if first_at is None:
                        first_at = time.monotonic()
                    b = buf()
                    if b is None:
                        break
                    b.append(chunk)
            elif inspect.isgenerator(out):
                for chunk in out:
                    if first_at is None:
                        first_at = time.monotonic()
                    b = buf()
                    if b is None:
                        break
                    b.append(chunk)
                    await asyncio.sleep(0)  # let pollers interleave
            else:
                if inspect.iscoroutine(out):
                    out = await out
                b = buf()
                if b is not None:
                    b.append(out)
            ok = True
        finally:
            if stream_id in self._cancelled_streams:
                # abandoned: every trace of the stream is already gone —
                # resurrecting the done flag would leak an entry forever
                self._cancelled_streams.pop(stream_id, None)
                self._streams.pop(stream_id, None)
                self._stream_done.pop(stream_id, None)
            else:
                self._stream_done[stream_id] = True
            self.num_ongoing -= 1
            self.num_processed += 1
            self._obs_end(begin, first_token_at=first_at, ok=ok,
                          window=method is None)

    async def handle_request_gen(self, args: tuple, kwargs: dict,
                                 method: Optional[str] = None):
        """Streaming endpoint as a native streaming-generator actor method
        (called with ``num_returns="streaming"``): each chunk ships to the
        caller the moment it is yielded — no next_chunks long-poll round
        trips (that path remains for deployment handles that want the
        buffered protocol)."""
        if self._draining:
            raise RuntimeError(f"replica {self.replica_id} is draining")
        self.num_ongoing += 1
        begin = self._obs_begin()
        first_at: Optional[float] = None
        ok = False
        try:
            fn = self._resolve(method)
            out = fn(*args, **kwargs)
            if inspect.iscoroutine(out):
                out = await out
            if inspect.isasyncgen(out):
                async for chunk in out:
                    if first_at is None:
                        first_at = time.monotonic()
                    yield chunk
            elif inspect.isgenerator(out):
                for chunk in out:
                    if first_at is None:
                        first_at = time.monotonic()
                    yield chunk
                    await asyncio.sleep(0)  # keep the actor loop responsive
            else:
                first_at = time.monotonic()
                yield out
            ok = True
        finally:
            self.num_ongoing -= 1
            self.num_processed += 1
            self._obs_end(begin, first_token_at=first_at, ok=ok,
                          window=method is None)

    async def cancel_stream(self, stream_id: str) -> bool:
        """Client abandoned the stream (``stream(timeout_s=...)`` hit its
        deadline): drop the buffer and stop the generator so drain()
        never waits on chunks nobody will claim.  The tombstone covers
        both orderings — a still-running handler consumes it in its
        finally, a not-yet-started one at registration; a finished
        stream (done flag True) needs only the pops."""
        now = time.monotonic()
        # prune tombstones nobody consumed (cancel raced a stream that
        # had already completed and been popped — its fresh-uuid id will
        # never be seen again); 120s far exceeds any legitimate gap
        # between a cancel and the handler's finally
        for sid, ts in list(self._cancelled_streams.items()):
            if now - ts > 120.0:
                self._cancelled_streams.pop(sid, None)
        done = self._stream_done.get(stream_id)
        self._streams.pop(stream_id, None)
        self._stream_done.pop(stream_id, None)
        if done is not True:
            self._cancelled_streams[stream_id] = now
        return True

    async def next_chunks(self, stream_id: str, cursor: int) -> tuple:
        """Poll a stream: returns (new_chunks, next_cursor, done)."""
        for _ in range(200):  # long-poll up to ~2s per call
            buf = self._streams.get(stream_id)
            if buf is None:
                raise KeyError(f"unknown stream {stream_id}")
            if len(buf) > cursor:
                chunks = buf[cursor:]
                done = self._stream_done.get(stream_id, False)
                nxt = cursor + len(chunks)
                if done and nxt == len(buf):
                    self._streams.pop(stream_id, None)
                    self._stream_done.pop(stream_id, None)
                return chunks, nxt, done
            if self._stream_done.get(stream_id, False):
                self._streams.pop(stream_id, None)
                self._stream_done.pop(stream_id, None)
                return [], cursor, True
            await asyncio.sleep(0.01)
        return [], cursor, False

    # ------------------------------------------------------------ lifecycle

    def _apply_user_config(self, user_config: Any):
        target = self.callable if self.callable is not None else None
        if target is not None and hasattr(target, "reconfigure"):
            target.reconfigure(user_config)

    async def reconfigure(self, user_config: Any) -> bool:
        self._apply_user_config(user_config)
        return True

    async def health_check(self) -> Dict[str, Any]:
        # User-defined health check hooks in when present (reference:
        # replica.py check_health).
        target = self.callable
        if target is not None and hasattr(target, "check_health"):
            res = target.check_health()
            if inspect.iscoroutine(res):
                await res
        # SLO heartbeat piggyback: the rolling TTFT percentiles + queue
        # depth ride the health check the controller already runs — no
        # extra RPC, and the controller aggregates per deployment.
        out = {"ongoing": self.num_ongoing, "processed": self.num_processed,
               "draining": self._draining,
               "slo": obs.slo_snapshot(self.deployment_name,
                                       self.num_ongoing)}
        # Prefix-cache digest piggyback (cache-aware routing): deployments
        # exposing prefix_digest() (LLMServer over a paged engine) ship a
        # bounded set of first-page block hashes the router can score
        # candidates against.  A broken hook must not fail the health
        # check — routing just falls back to pure p2c for this replica.
        if target is not None and hasattr(target, "prefix_digest"):
            try:
                out["prefix"] = target.prefix_digest()
            except Exception:
                out["prefix"] = None
        return out

    async def queue_len(self) -> int:
        return self.num_ongoing

    async def drain(self, timeout_s: float = 10.0) -> bool:
        """Stop accepting new requests; wait for ongoing ones to finish AND
        for buffered streaming chunks to be fully claimed — killing a replica
        whose client is still polling next_chunks() would truncate the
        stream mid-flight."""
        self._draining = True
        deadline = time.monotonic() + timeout_s
        while ((self.num_ongoing > 0 or self._streams)
               and time.monotonic() < deadline):
            await asyncio.sleep(0.05)
        return self.num_ongoing == 0 and not self._streams
