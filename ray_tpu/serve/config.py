"""Serve configs: deployment + autoscaling schemas.

Reference: ``python/ray/serve/config.py`` (DeploymentConfig/AutoscalingConfig
pydantic models) — re-expressed as plain dataclasses; and
``python/ray/serve/_private/common.py`` status enums.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


#: autoscaling policies: "ongoing" is the original ongoing-requests
#: heuristic; "slo" is the serve/slo_autoscaler.py control loop driven by
#: the serve.slo_signal() contract (TTFT-p95 vs target + queue depth per
#: replica, hysteresis, capacity-aware clamping, drain-aware scale-down)
POLICY_ONGOING = "ongoing"
POLICY_SLO = "slo"


@dataclasses.dataclass
class AutoscalingConfig:
    """Replica autoscaling.  ``policy="ongoing"`` is the queue-depth
    heuristic (reference: _private/autoscaling_policy.py — target ongoing
    requests per replica drives the count); ``policy="slo"`` drives the
    count from the ``serve.slo_signal()`` contract instead (see
    serve/slo_autoscaler.py): scale up fast when TTFT-p95 breaches
    ``ttft_p95_target_ms`` or queue depth per replica exceeds
    ``target_ongoing_requests``, scale down slowly (one replica at a time,
    emptiest first, through the graceful-drain path) once the signal has
    stayed under ``downscale_low_water`` of both targets for
    ``downscale_delay_s``."""
    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    # smoothing factor applied to the raw desired count
    smoothing_factor: float = 1.0
    policy: str = POLICY_ONGOING
    #: SLO policy: TTFT-p95 above this is a breach (None = queue-only)
    ttft_p95_target_ms: Optional[float] = None
    #: SLO policy: don't trust TTFT percentiles computed over fewer
    #: rolling-window samples than this (a single slow request must not
    #: trigger a surge)
    min_window_n: int = 4
    #: SLO policy: downscale only when queue/replica AND TTFT-p95 sit
    #: below this fraction of their targets — the deadband between the
    #: upscale and downscale thresholds is the anti-flap hysteresis
    downscale_low_water: float = 0.5
    #: SLO policy: per-decision surge cap — one upscale step may at most
    #: multiply the replica count by this (breach ratio beyond it waits
    #: for the next control period, after the new replicas report in)
    upscale_surge_max: float = 2.0

    def __post_init__(self):
        if self.min_replicas < 1:
            # Scale-to-zero needs a pending-request signal at the controller
            # (requests route directly to replicas here, so a zero-replica
            # deployment could never wake up).  Reject rather than brick.
            raise ValueError("min_replicas must be >= 1 (scale-to-zero is "
                             "not supported: routing is direct-to-replica)")
        if self.policy not in (POLICY_ONGOING, POLICY_SLO):
            raise ValueError(f"unknown autoscaling policy {self.policy!r} "
                             f"(choose {POLICY_ONGOING!r} or {POLICY_SLO!r})")
        if not 0.0 < self.downscale_low_water < 1.0:
            raise ValueError("downscale_low_water must be in (0, 1) — it is "
                             "the hysteresis deadband's lower edge")


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Any = None
    autoscaling: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 10.0
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    route_prefix: Optional[str] = None  # default: f"/{name}"

    def initial_replicas(self) -> int:
        if self.autoscaling is not None:
            return max(self.autoscaling.min_replicas, 1)
        return self.num_replicas


# Deployment status values (reference: _private/common.py DeploymentStatus)
DEPLOYING = "DEPLOYING"
HEALTHY = "HEALTHY"
UNHEALTHY = "UNHEALTHY"
UPDATING = "UPDATING"
DELETING = "DELETING"
