"""Serve configs: deployment + autoscaling schemas.

Reference: ``python/ray/serve/config.py`` (DeploymentConfig/AutoscalingConfig
pydantic models) — re-expressed as plain dataclasses; and
``python/ray/serve/_private/common.py`` status enums.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional


@dataclasses.dataclass
class AutoscalingConfig:
    """Queue-depth autoscaling (reference: _private/autoscaling_policy.py):
    target ongoing requests per replica drives the replica count."""
    min_replicas: int = 1
    max_replicas: int = 8
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 3.0
    downscale_delay_s: float = 30.0
    # smoothing factor applied to the raw desired count
    smoothing_factor: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 1:
            # Scale-to-zero needs a pending-request signal at the controller
            # (requests route directly to replicas here, so a zero-replica
            # deployment could never wake up).  Reject rather than brick.
            raise ValueError("min_replicas must be >= 1 (scale-to-zero is "
                             "not supported: routing is direct-to-replica)")


@dataclasses.dataclass
class DeploymentConfig:
    num_replicas: int = 1
    max_concurrent_queries: int = 100
    user_config: Any = None
    autoscaling: Optional[AutoscalingConfig] = None
    health_check_period_s: float = 2.0
    health_check_timeout_s: float = 10.0
    graceful_shutdown_timeout_s: float = 10.0
    ray_actor_options: Dict[str, Any] = dataclasses.field(default_factory=dict)
    route_prefix: Optional[str] = None  # default: f"/{name}"

    def initial_replicas(self) -> int:
        if self.autoscaling is not None:
            return max(self.autoscaling.min_replicas, 1)
        return self.num_replicas


# Deployment status values (reference: _private/common.py DeploymentStatus)
DEPLOYING = "DEPLOYING"
HEALTHY = "HEALTHY"
UNHEALTHY = "UNHEALTHY"
UPDATING = "UPDATING"
DELETING = "DELETING"
