"""Serve-plane observability: ``raytpu_serve_*`` metrics, request-scoped
stage spans, and the rolling SLO window the controller aggregates.

The runtime core got its instrumentation plane in PR 2 (task stage
histograms, RPC metrics, node telemetry); this module is the serve-side
counterpart — the path that must carry production traffic.  Three
surfaces, one kill switch (``serve_metrics_enabled``):

* **Metrics** on the shared registry (util/metrics.py), exported through
  the same per-node agent ``/metrics`` endpoint: request latency / TTFT /
  TPOT histograms, token counters, router + replica queue-depth gauges,
  batch occupancy + padding waste, KV page utilization and prefix-cache
  hit rate.  Tag values are BOUNDED: ``deployment`` and ``route`` come
  from deployment config (never raw request paths — enforced by the
  test_metric_naming.py serve lint), ``status`` is an HTTP status string.
* **Stage spans** into the task-event stream (util/tracing.py): the proxy
  stamps ``proxy_recv``/``router_queue``/``stream_write``, ``@serve.batch``
  stamps ``batch_wait``, the LLM engine stamps ``batch_wait``/``prefill``/
  ``decode`` — all chained to the request's trace context so ``raytpu
  timeline --breakdown`` renders one connected cross-process trace per
  request.
* **SLO window**: each replica process keeps a rolling window of TTFT
  samples; ``slo_snapshot`` rolls it into p50/p95/p99 + queue depth, which
  rides the health-check heartbeat to the controller — the per-deployment
  signal ``serve.status()`` / ``raytpu serve status`` / ``/api/serve``
  report and the ``policy="slo"`` autoscaler (serve/slo_autoscaler.py)
  consumes.

Hot-path discipline follows PR 2: metrics are lazy-constructed once, tag
keys are precomputed per (deployment, ...) and cached, and every record
call early-outs on one boolean when the kill switch is off.
"""

from __future__ import annotations

import contextvars
import threading
import time
from collections import deque
from typing import Deque, Dict, Optional, Tuple

from ray_tpu.util.metrics import Counter, Gauge, Histogram, lazy

#: Deployment whose request is currently being handled on this
#: task/coroutine — set by the replica around user-code invocation so
#: downstream instrumentation (``@serve.batch``, the LLM engine's
#: ``submit``) can tag metrics with a config-derived deployment name
#: without threading it through every call signature.
_deployment_ctx: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("raytpu_serve_deployment", default=None)


def set_current_deployment(name: Optional[str]):
    return _deployment_ctx.set(name)


def reset_current_deployment(token):
    try:
        _deployment_ctx.reset(token)
    except ValueError:
        # an abandoned async generator's finally can run during asyncgen
        # finalization in a FRESH context (loop.call_soon) where the token
        # was never set — clear instead of raising out of cleanup
        _deployment_ctx.set(None)


def current_deployment(default: str = "-") -> str:
    return _deployment_ctx.get() or default


#: (config object, its serve_metrics_enabled) — the flag is static per
#: Config instance, so cache by identity: the hot path pays one call +
#: one `is` check instead of import + getattr per record, while
#: set_config/reset_config (tests, reinit) still take effect because they
#: install a NEW Config object.
_enabled_cache: tuple = (None, True)
_get_config = None


def enabled() -> bool:
    global _get_config, _enabled_cache
    if _get_config is None:  # deferred: avoids an import cycle at load
        from ray_tpu.core.config import get_config
        _get_config = get_config
    cfg = _get_config()
    cached = _enabled_cache
    if cached[0] is cfg:
        return cached[1]
    v = bool(getattr(cfg, "serve_metrics_enabled", True))
    _enabled_cache = (cfg, v)
    return v


# --------------------------------------------------------------- metrics

#: request latencies span sub-ms cache hits to multi-minute generations
_LATENCY_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)
#: per-output-token time: ms-scale on chips, 100s of ms on CPU CI
_TPOT_BOUNDS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                1.0, 2.5)
#: batch occupancy fraction (0..1]
_FRACTION_BOUNDS = (0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0)


def _build():
    return {
        "requests": Counter(
            "raytpu_serve_requests_total",
            "serve requests by deployment/route/status",
            tag_keys=("deployment", "route", "status")),
        "latency": Histogram(
            "raytpu_serve_request_latency_seconds",
            "end-to-end serve request latency at the ingress",
            boundaries=_LATENCY_BOUNDS,
            tag_keys=("deployment", "route", "status")),
        "ttft": Histogram(
            "raytpu_serve_ttft_seconds",
            "time to first token/chunk (stage=replica|engine)",
            boundaries=_LATENCY_BOUNDS, tag_keys=("deployment", "stage")),
        "tpot": Histogram(
            "raytpu_serve_tpot_seconds",
            "time per output token after the first",
            boundaries=_TPOT_BOUNDS, tag_keys=("deployment",)),
        "tokens": Counter(
            "raytpu_serve_tokens_total",
            "prompt (in) and generated (out) tokens",
            tag_keys=("deployment", "direction")),
        "router_depth": Gauge(
            "raytpu_serve_router_queue_depth",
            "in-flight requests this router has routed, per deployment",
            tag_keys=("deployment",)),
        "replica_depth": Gauge(
            "raytpu_serve_replica_queue_depth",
            "requests in flight on this replica",
            tag_keys=("deployment",)),
        "batch_size": Histogram(
            "raytpu_serve_batch_size",
            "requests flushed per @serve.batch / engine admit batch",
            boundaries=(1, 2, 4, 8, 16, 32, 64, 128, 256),
            tag_keys=("deployment",)),
        "batch_occupancy": Histogram(
            "raytpu_serve_batch_occupancy",
            "filled fraction of the batch (1 - padding waste)",
            boundaries=_FRACTION_BOUNDS, tag_keys=("deployment",)),
        "batch_wait": Histogram(
            "raytpu_serve_batch_wait_seconds",
            "time a request waited for its batch to flush",
            boundaries=_LATENCY_BOUNDS, tag_keys=("deployment",)),
        "engine_slots": Gauge(
            "raytpu_serve_engine_active_slots",
            "LLM engine decode slots currently generating",
            tag_keys=("deployment",)),
        "kv_util": Gauge(
            "raytpu_serve_kv_page_utilization",
            "fraction of paged-KV pages in use",
            tag_keys=("deployment",)),
        "prefix_lookups": Counter(
            "raytpu_serve_prefix_cache_lookups_total",
            "prefix-cache lookups by result",
            tag_keys=("deployment", "result")),
        "prefix_tokens": Counter(
            "raytpu_serve_prefix_cache_tokens_reused_total",
            "prompt tokens whose KV was served from the prefix cache",
            tag_keys=("deployment",)),
        "spec_accept": Histogram(
            "raytpu_serve_spec_acceptance_rate",
            "draft-token acceptance fraction per speculative dispatch",
            boundaries=_FRACTION_BOUNDS, tag_keys=("deployment",)),
        "spec_tokens_round": Histogram(
            "raytpu_serve_spec_tokens_per_round",
            "tokens emitted per speculative round (1..k)",
            boundaries=(1, 2, 3, 4, 6, 8, 12, 16),
            tag_keys=("deployment",)),
        "spec_rollbacks": Counter(
            "raytpu_serve_spec_rollback_tokens_total",
            "draft tokens rejected by verification and rolled back",
            tag_keys=("deployment",)),
        "prefix_route": Counter(
            "raytpu_serve_prefix_route_total",
            "cache-aware routing decisions by result (hit|miss|fallback)",
            tag_keys=("deployment", "result")),
    }


_metrics = lazy(_build)

#: precomputed sorted tags keys, interned so hot paths hand the SAME tuple
#: to inc_key/observe_key every call (PR-2 discipline).  Bounded:
#: deployments x routes x statuses, all config/enumeration-derived.
_key_cache: Dict[tuple, tuple] = {}


def _key(**tags: str) -> tuple:
    ck = tuple(sorted(tags.items()))
    return _key_cache.setdefault(ck, ck)


# ------------------------------------------------------ record helpers

def record_request(deployment: str, route: str, status: str, dur_s: float):
    """Ingress-side: one completed HTTP request.  ``route`` is the matched
    route PREFIX from deployment config (bounded), never the raw path."""
    if not enabled():
        return
    m = _metrics()
    if m is None:
        return
    k = _key(deployment=deployment, route=route, status=status)
    m["requests"].inc_key(k)
    m["latency"].observe_key(k, dur_s)


def observe_ttft(deployment: str, seconds: float, stage: str = "replica",
                 window: bool = True):
    """First token/chunk latency; ``window=True`` also feeds the rolling
    SLO window (exactly one window sample per request — the replica-level
    observation — so engine-level TTFT doesn't double-count)."""
    if not enabled():
        return
    m = _metrics()
    if m is not None:
        m["ttft"].observe_key(_key(deployment=deployment, stage=stage),
                              seconds)
    if window:
        slo_window(deployment).observe(seconds)


def observe_tpot(deployment: str, seconds_per_token: float):
    if not enabled():
        return
    m = _metrics()
    if m is not None:
        m["tpot"].observe_key(_key(deployment=deployment),
                              seconds_per_token)


def add_tokens(deployment: str, direction: str, n: int):
    if n <= 0 or not enabled():
        return
    m = _metrics()
    if m is not None:
        m["tokens"].inc_key(_key(deployment=deployment,
                                 direction=direction), n)


def set_router_queue_depth(deployment: str, depth: int):
    if not enabled():
        return
    m = _metrics()
    if m is not None:
        m["router_depth"].set_key(_key(deployment=deployment), depth)


def set_replica_queue_depth(deployment: str, depth: int):
    if not enabled():
        return
    m = _metrics()
    if m is not None:
        m["replica_depth"].set_key(_key(deployment=deployment), depth)


def record_batch(deployment: str, size: int, capacity: int,
                 waits_s: Optional[list] = None):
    """One flushed batch: size, occupancy (1 - padding waste), and each
    member's time-in-queue."""
    if not enabled():
        return
    m = _metrics()
    if m is None:
        return
    dk = _key(deployment=deployment)
    m["batch_size"].observe_key(dk, size)
    m["batch_occupancy"].observe_key(dk, size / max(capacity, 1))
    if waits_s:
        for w in waits_s:
            m["batch_wait"].observe_key(dk, w)


def set_engine_gauges(deployment: str, active_slots: int,
                      kv_pages_used: Optional[int] = None,
                      kv_pages_total: Optional[int] = None):
    if not enabled():
        return
    m = _metrics()
    if m is None:
        return
    m["engine_slots"].set_key(_key(deployment=deployment), active_slots)
    if kv_pages_total:
        m["kv_util"].set_key(_key(deployment=deployment),
                             (kv_pages_used or 0) / kv_pages_total)


def record_prefix_lookup(deployment: str, hit: bool, tokens_reused: int):
    if not enabled():
        return
    m = _metrics()
    if m is None:
        return
    m["prefix_lookups"].inc_key(
        _key(deployment=deployment, result="hit" if hit else "miss"))
    if tokens_reused > 0:
        m["prefix_tokens"].inc_key(_key(deployment=deployment),
                                   tokens_reused)


def record_spec_dispatch(deployment: str, rounds: int, tokens: int,
                         drafted: int, accepted: int):
    """One drained speculative dispatch: acceptance fraction, emitted
    tokens per round, and rejected (rolled-back) draft tokens."""
    if not enabled():
        return
    m = _metrics()
    if m is None:
        return
    dk = _key(deployment=deployment)
    if drafted > 0:
        m["spec_accept"].observe_key(dk, accepted / drafted)
    if rounds > 0:
        m["spec_tokens_round"].observe_key(dk, tokens / rounds)
    rolled = drafted - accepted
    if rolled > 0:
        m["spec_rollbacks"].inc_key(dk, rolled)


def record_prefix_route(deployment: str, result: str):
    """Cache-aware routing decision; result is hit|miss|fallback."""
    if not enabled():
        return
    m = _metrics()
    if m is None:
        return
    m["prefix_route"].inc_key(_key(deployment=deployment, result=result))


def stamp_span(name: str, t0: float, dur: float, *,
               trace_id: Optional[str] = None,
               span_id: Optional[str] = None,
               parent_id: Optional[str] = None, **attributes):
    """Serve stage span into the task-event stream, gated on the same kill
    switch as the metrics; returns the span id (or None when shed)."""
    if not enabled():
        return None
    from ray_tpu.util import tracing
    return tracing.record_span(name, t0, dur, trace_id=trace_id,
                               span_id=span_id, parent_id=parent_id,
                               **attributes)


# ------------------------------------------------------------ SLO window

class SLOWindow:
    """Rolling window of (monotonic ts, value) samples with age-out.

    ``summary()`` prunes everything older than ``window_s`` and returns
    nearest-rank percentiles over what remains — the replica-local rollup
    that piggybacks on health-check heartbeats.  Bounded two ways: by age
    and by ``max_samples`` (a flood drops oldest first), so the heartbeat
    payload and the percentile sort stay O(small)."""

    def __init__(self, window_s: float = 60.0, max_samples: int = 2048):
        self.window_s = float(window_s)
        self._samples: Deque[Tuple[float, float]] = deque(maxlen=max_samples)
        self._lock = threading.Lock()

    def observe(self, value: float, now: Optional[float] = None):
        with self._lock:
            self._samples.append((now if now is not None
                                  else time.monotonic(), float(value)))

    def _prune(self, now: float):
        horizon = now - self.window_s
        while self._samples and self._samples[0][0] < horizon:
            self._samples.popleft()

    def summary(self, now: Optional[float] = None) -> dict:
        now = now if now is not None else time.monotonic()
        with self._lock:
            self._prune(now)
            vals = sorted(v for _, v in self._samples)
        n = len(vals)
        if not n:
            return {"window_n": 0}

        def pct(p: float) -> float:
            return vals[min(n - 1, max(0, int(p * n + 0.5) - 1))]

        return {"window_n": n,
                "p50": pct(0.50), "p95": pct(0.95), "p99": pct(0.99)}


_windows: Dict[str, SLOWindow] = {}
_windows_lock = threading.Lock()


def slo_window(deployment: str) -> SLOWindow:
    w = _windows.get(deployment)
    if w is None:
        from ray_tpu.core.config import get_config
        with _windows_lock:
            w = _windows.setdefault(deployment, SLOWindow(
                getattr(get_config(), "serve_slo_window_s", 60.0)))
    return w


def slo_snapshot(deployment: str, queue_depth: int) -> dict:
    """The per-replica SLO signal that rides the health-check heartbeat:
    rolling TTFT percentiles (ms) + current queue depth.  With the kill
    switch off only queue depth ships (the autoscaler's minimum input —
    it predates this plane)."""
    out = {"queue_depth": int(queue_depth)}
    if not enabled():
        return out
    s = slo_window(deployment).summary()
    out["window_n"] = s.get("window_n", 0)
    for p in ("p50", "p95", "p99"):
        if p in s:
            out[f"ttft_{p}_ms"] = round(s[p] * 1000.0, 3)
    return out


# ------------------------------------------------------- loop monitor

def ensure_loop_monitor(holder, source: str):
    """Install the event-loop stall detector on the CURRENT (actor) event
    loop, once per holder object — serve replica / proxy / controller
    processes run their request handling on an actor loop distinct from
    the worker's RPC loop, so the core worker's monitor cannot see a
    decode step wedging THIS loop.  Config-gated like every other
    install (``loop_monitor_enabled``); stores the monitor on the holder
    so drain/shutdown paths can stop it."""
    if getattr(holder, "_serve_loop_monitor", None) is not None:
        return holder._serve_loop_monitor
    holder._serve_loop_monitor = False  # tried; don't retry every request
    try:
        import asyncio

        from ray_tpu.core.core_worker import global_worker_or_none
        from ray_tpu.util.loop_monitor import install

        w = global_worker_or_none()
        gcs_call = w.gcs.call if w is not None and w.gcs else None
        mon = install(asyncio.get_event_loop(), source, gcs_call=gcs_call)
        if mon is not None:
            holder._serve_loop_monitor = mon
        return mon
    except Exception:
        return None
