"""Model composition: deployment graphs.

Reference: ``python/ray/serve/dag.py`` + ``deployment_graph_build.py`` +
``drivers.py`` (DAGDriver) — deployments bind *other deployments* as init
args; ``serve.run(root)`` deploys the transitive closure and each replica
receives live :class:`DeploymentHandle`s where the graph had nested
deployments, so deployment-to-deployment calls route through the normal
handle path (power-of-two-choices, autoscaling, health checks all apply).

Example::

    @serve.deployment
    class Preprocess: ...

    @serve.deployment
    class Model:
        def __init__(self, pre):           # receives a DeploymentHandle
            self.pre = pre
        async def __call__(self, x):
            return model(await self.pre.remote(x).result_async())

    app = Model.bind(Preprocess.bind())
    serve.run(app)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Tuple

from .deployment import Deployment


def _walk(value, fn):
    """Structurally map ``fn`` over Deployments nested in containers."""
    if isinstance(value, Deployment):
        return fn(value)
    if isinstance(value, list):
        return [_walk(v, fn) for v in value]
    if isinstance(value, tuple):
        return tuple(_walk(v, fn) for v in value)
    if isinstance(value, dict):
        return {k: _walk(v, fn) for k, v in value.items()}
    return value


def collect_deployments(root: Deployment) -> List[Deployment]:
    """The transitive closure of ``root`` over bound-arg edges, dependencies
    first (so inner deployments are deployed before the ones calling them).
    Two bound copies with the same name must be the same deployment."""
    seen: Dict[str, Deployment] = {}
    order: List[Deployment] = []

    def visit(d: Deployment):
        if d.name in seen:
            if seen[d.name].version() != d.version():
                raise ValueError(
                    f"two different deployments named {d.name!r} in one "
                    "graph; give them distinct name= options")
            return
        seen[d.name] = d
        _walk(list(d.init_args) + list(d.init_kwargs.values()), visit)
        order.append(d)  # post-order: dependencies first

    visit(root)
    return order


def resolve_handles(d: Deployment) -> Deployment:
    """Replace nested Deployments in init args with DeploymentHandles
    (picklable name-only stubs resolved inside the replica)."""
    from .router import DeploymentHandle

    def to_handle(dep: Deployment):
        return DeploymentHandle(dep.name)

    args = tuple(_walk(a, to_handle) for a in d.init_args)
    kwargs = {k: _walk(v, to_handle) for k, v in d.init_kwargs.items()}
    return dataclasses.replace(d, init_args=args, init_kwargs=kwargs)


class _DAGDriver:
    """HTTP ingress for a deployment graph (reference: serve/drivers.py).

    Deploy as ``serve.run(DAGDriver.bind(root.bind(...)))`` — requests hit
    the driver, which forwards to the root handle and awaits the result.
    """

    def __init__(self, target):
        self.target = target  # a DeploymentHandle after graph resolution

    async def __call__(self, request=None):
        resp = self.target.remote(request)
        if hasattr(resp, "result_async"):
            return await resp.result_async()
        return resp.result()


def _make_dag_driver() -> Deployment:
    # DAGDriver ships pre-decorated (reference: drivers.py DAGDriver is
    # itself a @serve.deployment) so `DAGDriver.bind(app)` works directly.
    from .deployment import deployment as _deployment
    return _deployment(_DAGDriver, name="DAGDriver")


DAGDriver = _make_dag_driver()
