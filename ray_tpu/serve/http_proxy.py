"""HTTP proxy: the ingress edge of Serve.

Reference: ``python/ray/serve/_private/http_proxy.py:922`` (HTTPProxy /
HTTPProxyActor).  The reference speaks ASGI through uvicorn; here the proxy is
an async actor running an aiohttp server (aiohttp is in the base image;
uvicorn/starlette are not).  Everything on the request path is ``await``-based
— the actor's private event loop must never block on a synchronous
``ray_tpu.get`` or concurrent requests would serialize.

Routing: longest-prefix match on the controller's route table, then
power-of-two-choices replica selection (local in-flight counts), then a direct
actor call to the replica.  Streaming endpoints produce a chunked HTTP
response driven by the replica's ``next_chunks`` long-poll.
"""

from __future__ import annotations

import asyncio
import json
import random
import time
from typing import Any, Dict, List, Optional, Tuple

from . import observability as obs
from .replica import Request

PROXY_NAME = "serve:proxy"


class AsyncRouter:
    """Replica selection + table refresh with async-only control calls.

    Same policy as ``router.Router`` (p2c over local in-flight counts) but
    safe to use on an async actor's event loop; refreshes ride the
    controller's long-poll so table changes propagate in ~one RTT.
    """

    def __init__(self):
        self._table: Dict[str, List[str]] = {}
        self._routes: Dict[str, str] = {}
        self._handles: Dict[str, Any] = {}
        self._inflight: Dict[str, int] = {}
        self._dep_inflight: Dict[str, int] = {}  # queue-depth gauge feed
        self._version = -1
        self._poller: Optional[asyncio.Task] = None

    def _track(self, deployment: str, delta: int):
        if not obs.enabled():  # kill switch sheds the bookkeeping too
            return
        n = self._dep_inflight.get(deployment, 0) + delta
        self._dep_inflight[deployment] = max(0, n)
        obs.set_router_queue_depth(deployment, self._dep_inflight[deployment])

    def _acquire(self, name: str, deployment: str):
        """One in-flight request landed on replica ``name``: bump both the
        p2c per-replica count and the deployment queue-depth gauge.  The
        single copy of this invariant — unary calls and long-lived streams
        must load both the same way."""
        self._inflight[name] = self._inflight.get(name, 0) + 1
        self._track(deployment, +1)

    def _release(self, name: str, deployment: str):
        self._inflight[name] = max(0, self._inflight.get(name, 1) - 1)
        self._track(deployment, -1)

    @staticmethod
    def _traced_submit(submit, deployment: str, t_route: float):
        """Run ``submit()`` with the trace context pointing at a fresh
        ``router_queue`` span id (so the replica's task slice chains
        proxy -> router -> replica), and stamp the span only once the
        submit actually dispatched — a dead-name retry must not leave N
        cumulative router_queue slices for one request."""
        from ray_tpu.util import tracing
        parent = tracing.current_context()
        if parent is None or not obs.enabled():
            return submit()
        span_id = tracing.new_id()
        token = tracing.set_context((parent[0], span_id))
        try:
            out = submit()
        finally:
            tracing.reset_context(token)
        obs.stamp_span(
            "router_queue", t_route, time.time() - t_route,
            trace_id=parent[0], span_id=span_id, parent_id=parent[1],
            deployment=deployment)
        return out

    @staticmethod
    async def _aget(ref):
        import ray_tpu
        return await asyncio.wrap_future(ray_tpu.as_future(ref))

    def _controller(self):
        import ray_tpu
        from .controller import CONTROLLER_NAME
        return ray_tpu.get_actor(CONTROLLER_NAME)

    async def refresh(self, force: bool = False):
        if self._version >= 0 and not force:
            return
        ctrl = self._controller()
        self._version, self._table = await self._aget(
            ctrl.get_routing_table.remote())
        _, self._routes = await self._aget(ctrl.get_http_routes.remote())
        live = {r for reps in self._table.values() for r in reps}
        self._handles = {k: v for k, v in self._handles.items() if k in live}

    def ensure_poller(self):
        if self._poller is None or self._poller.done():
            self._poller = asyncio.get_event_loop().create_task(
                self._poll_loop())

    async def _poll_loop(self):
        ctrl = self._controller()
        while True:
            try:
                self._version, self._table = await self._aget(
                    ctrl.wait_for_table_change.remote(self._version, 10.0))
                _, self._routes = await self._aget(
                    ctrl.get_http_routes.remote())
                live = {r for reps in self._table.values() for r in reps}
                self._handles = {k: v for k, v in self._handles.items()
                                 if k in live}
            except asyncio.CancelledError:
                raise
            except Exception:
                await asyncio.sleep(1.0)

    def match_route(self, path: str) -> Optional[Tuple[str, str]]:
        """Longest-prefix route match -> (deployment, route_prefix)."""
        best = None
        for prefix, dep in self._routes.items():
            if path == prefix or path.startswith(
                    prefix if prefix.endswith("/") else prefix + "/"):
                if best is None or len(prefix) > len(best[1]):
                    best = (dep, prefix)
        return best

    def _handle_for(self, name: str):
        import ray_tpu
        h = self._handles.get(name)
        if h is None:
            h = ray_tpu.get_actor(name)
            self._handles[name] = h
        return h

    async def choose(self, deployment: str, wait_s: float = 5.0) -> str:
        await self.refresh()
        deadline = asyncio.get_event_loop().time() + wait_s
        while True:
            replicas = self._table.get(deployment)
            if replicas:
                break
            if asyncio.get_event_loop().time() > deadline:
                raise LookupError(
                    f"no running replicas for deployment {deployment!r}")
            await self.refresh(force=True)
            await asyncio.sleep(0.1)
        if len(replicas) == 1:
            return replicas[0]
        a, b = random.sample(replicas, 2)
        return (a if self._inflight.get(a, 0) <= self._inflight.get(b, 0)
                else b)

    async def call(self, deployment: str, args: tuple, kwargs: dict,
                   method: Optional[str] = None) -> Any:
        """Route + call + retry-on-dead/draining-replica."""
        from .router import is_retryable_failure
        last: Optional[BaseException] = None
        for _ in range(5):
            # per-attempt stamp: a retry after a replica died mid-request
            # measures ITS OWN routing time, not the failed attempt's
            # execution (each genuine dispatch gets one router_queue span)
            t_route = time.time()
            name = await self.choose(deployment)
            try:
                h = self._handle_for(name)
                ref = self._traced_submit(
                    lambda: h.handle_request.remote(args, kwargs, method),
                    deployment, t_route)
            except Exception as e:  # noqa: BLE001 — dead name
                last = e
                self._evict(deployment, name)
                continue
            self._acquire(name, deployment)
            try:
                return await self._aget(ref)
            except BaseException as e:  # noqa: BLE001
                if not is_retryable_failure(e):
                    raise
                last = e
                self._evict(deployment, name)
            finally:
                self._release(name, deployment)
        raise last  # type: ignore[misc]

    def _evict(self, deployment: str, name: str):
        if name in self._table.get(deployment, []):
            self._table[deployment].remove(name)
        self._handles.pop(name, None)
        try:
            self._controller().report_replica_failure.remote(deployment, name)
        except Exception:
            pass


class HTTPProxyActor:
    """Async actor hosting the aiohttp server (one per ingress node)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8000):
        self.host = host
        self.port = port
        self.router = AsyncRouter()
        self._runner = None
        self._streaming_deployments: set = set()

    async def ready(self) -> int:
        """Start the server; returns the bound port."""
        if self._runner is not None:
            return self.port
        from aiohttp import web
        # a wedged proxy loop surfaces as
        # raytpu_event_loop_lag_seconds{process="serve_proxy"}
        obs.ensure_loop_monitor(self, "serve_proxy")
        self.router.ensure_poller()
        app = web.Application()
        app.router.add_route("GET", "/-/healthz", self._healthz)
        app.router.add_route("GET", "/-/routes", self._routes)
        app.router.add_route("*", "/{tail:.*}", self._handle)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        srv = list(self._runner.sites)[0]._server  # bound socket
        if self.port == 0:
            self.port = srv.sockets[0].getsockname()[1]
        return self.port

    async def _healthz(self, request):
        from aiohttp import web
        return web.Response(text="ok")

    async def _routes(self, request):
        from aiohttp import web
        await self.router.refresh(force=True)
        return web.json_response(self.router._routes)

    async def _handle(self, request):
        from aiohttp import web
        t0 = time.time()
        await self.router.refresh()
        match = self.router.match_route(request.path)
        if match is None:
            # bounded tags: an unmatched path must NOT become a label value
            obs.record_request("_unmatched", "_unmatched", "404",
                               time.time() - t0)
            return web.Response(status=404,
                                text=f"no deployment at {request.path}")
        deployment, prefix = match
        body = await request.read()
        req = Request(method=request.method,
                      path=request.path[len(prefix):] or "/",
                      query=dict(request.query),
                      headers=dict(request.headers),
                      body=body)
        # Request-scoped trace root: everything below — the router_queue
        # span, the replica's task slice, the engine's batch_wait/prefill/
        # decode — chains under this (trace_id, span_id), so `raytpu
        # timeline --breakdown` renders one connected trace per request.
        trace_id = span_id = token = None
        if obs.enabled():
            from ray_tpu.util import tracing
            trace_id, span_id = tracing.new_id(), tracing.new_id()
            token = tracing.set_context((trace_id, span_id))
        status = "500"
        try:
            resp = await self._dispatch(request, deployment, req)
            status = str(resp.status)
            return resp
        except asyncio.CancelledError:
            # aiohttp cancels the handler when the client disconnects —
            # that is not a server error; recording it as 500 would inflate
            # the error rate exactly during client-timeout storms.  499 =
            # client closed request (nginx convention).
            status = "499"
            raise
        except (ConnectionResetError, BrokenPipeError):
            # mid-stream disconnect surfaces as a transport write error,
            # not CancelledError — same classification: the client left
            status = "499"
            raise
        except LookupError as e:
            status = "503"
            return web.Response(status=503, text=str(e))
        except Exception as e:  # noqa: BLE001
            status = "500"
            return web.Response(status=500, text=repr(e))
        finally:
            if token is not None:
                from ray_tpu.util import tracing
                tracing.reset_context(token)
                obs.stamp_span("proxy_recv", t0, time.time() - t0,
                               trace_id=trace_id, span_id=span_id,
                               parent_id=None, deployment=deployment,
                               route=prefix, status=status)
            # `prefix` is the matched route from deployment config — the
            # raw request path never becomes a tag value
            obs.record_request(deployment, prefix, status, time.time() - t0)

    async def _dispatch(self, request, deployment: str, req: Request):
        """Route one matched request (unary or chunked-streaming)."""
        if deployment in self._streaming_deployments:
            return await self._stream_response(request, deployment, req)
        try:
            result = await self.router.call(deployment, (req,), {})
        except Exception as e:
            # A generator endpoint rejects the unary path with a
            # TypeError (TaskError-wrapped): remember it as streaming
            # and re-route through the chunked path.
            cause = getattr(e, "cause", e)
            if isinstance(cause, TypeError) and "streaming" in str(cause):
                self._streaming_deployments.add(deployment)
                return await self._stream_response(request, deployment, req)
            raise
        return self._pack(result)

    async def _stream_response(self, http_request, deployment: str,
                               req: Request):
        """Chunked HTTP response over a native streaming-generator actor call:
        each chunk the replica yields arrives as its own owner-side object
        push — no next_chunks long-poll round trips (the buffered
        handle_request_streaming/next_chunks protocol remains for deployment
        handles that poll)."""
        from .asgi import ASGIStart
        from aiohttp import web
        t_route = time.time()
        name = await self.router.choose(deployment)
        h = self.router._handle_for(name)
        gen = self.router._traced_submit(
            lambda: h.handle_request_gen.options(
                num_returns="streaming", generator_backpressure=256).remote(
                (req,), {}, None),
            deployment, t_route)
        # long-lived streams must load BOTH the queue-depth gauge and the
        # per-replica p2c count — otherwise choose() assigns multi-minute
        # LLM streams blind to each replica's open-stream load
        self.router._acquire(name, deployment)
        resp = web.StreamResponse()
        resp.headers["Content-Type"] = "text/plain; charset=utf-8"
        prepared = False
        t_write = None  # first-chunk write -> eof = the stream_write stage
        try:
            async for ref in gen:
                # Surfaces generator errors too: a raise lands as the
                # stream's final ref and re-raises here (truncating the
                # chunked body).
                c = await self.router._aget(ref)
                if not prepared and isinstance(c, ASGIStart):
                    # ASGI ingress streams (ASGIStart, *body chunks): apply
                    # the app's status/headers before the response is
                    # prepared.  Length/framing headers are dropped — this
                    # path chunks.
                    resp.set_status(c.status)
                    keep = [(k, v) for k, v in c.headers
                            if k.lower() not in ("content-length",
                                                 "transfer-encoding")]
                    for k in {k for k, _ in keep}:
                        resp.headers.popall(k, None)
                    for k, v in keep:  # add() preserves repeats (Set-Cookie)
                        resp.headers.add(k, v)
                    continue
                if not prepared:
                    await resp.prepare(http_request)
                    prepared = True
                    t_write = time.time()
                await resp.write(self._chunk_bytes(c))
            if not prepared:
                await resp.prepare(http_request)
            await resp.write_eof()
        finally:
            self.router._release(name, deployment)
            if t_write is not None:
                obs.stamp_span("stream_write", t_write,
                               time.time() - t_write, deployment=deployment)
        return resp

    @staticmethod
    def _chunk_bytes(c: Any) -> bytes:
        if isinstance(c, bytes):
            return c
        if isinstance(c, str):
            return c.encode()
        return (json.dumps(c) + "\n").encode()

    def _pack(self, result: Any):
        from aiohttp import web
        if isinstance(result, web.Response):
            return result
        if isinstance(result, bytes):
            return web.Response(body=result,
                                content_type="application/octet-stream")
        if isinstance(result, str):
            return web.Response(text=result)
        return web.json_response(result)

    async def drain(self) -> bool:
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None
        return True
