"""gRPC ingress: the second protocol through the Serve edge.

Reference: ``python/ray/serve/_private/grpc_util.py`` (gRPCServer) and the
gRPC proxy half of ``_private/http_proxy.py`` — a grpc.aio server routing to
the same replica plane as HTTP.  Schema: ``protos/serve.proto``
(rayserve.ServeAPI).  The server registers with grpc's generic-handler API
and (de)serializes the two single-``bytes``-field messages with a
hand-rolled proto3 wire reader, so protoc-compiled clients interoperate
with zero generated code in the framework.

Routing rides invocation metadata ("deployment", optional "method"), the
replica call plane is shared with the HTTP proxy (AsyncRouter: p2c +
retries + table long-poll), and PredictStream uses the replica's native
streaming generator — every chunk ships as a separate gRPC message.
"""

from __future__ import annotations

import json
from typing import Any, AsyncIterator

from .http_proxy import AsyncRouter
from .replica import Request

GRPC_PROXY_NAME = "serve:grpc_proxy"
SERVICE_NAME = "rayserve.ServeAPI"


# ------------------------------------------------------- proto3 wire codec
# ServeRequest/ServeResponse/HealthzResponse each carry ONE length-delimited
# field (#1); the codec below is the full wire format for that shape.

def _varint_decode(buf: bytes, i: int):
    shift = result = 0
    while True:
        b = buf[i]
        i += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, i
        shift += 7


def _varint_encode(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            return bytes(out)


def decode_payload(buf: bytes) -> bytes:
    """Field 1 (length-delimited) of a proto3 message; b'' if absent."""
    i, n, payload = 0, len(buf), b""
    while i < n:
        tag, i = _varint_decode(buf, i)
        wire = tag & 7
        if wire == 2:
            ln, i = _varint_decode(buf, i)
            val = bytes(buf[i:i + ln])
            i += ln
            if tag >> 3 == 1:
                payload = val
        elif wire == 0:
            _, i = _varint_decode(buf, i)
        elif wire == 5:
            i += 4
        elif wire == 1:
            i += 8
        else:
            raise ValueError(f"unsupported proto wire type {wire}")
    return payload


def encode_payload(data: bytes) -> bytes:
    if not data:
        return b""  # proto3 default field is omitted
    return b"\x0a" + _varint_encode(len(data)) + data


def _result_bytes(result: Any) -> bytes:
    if isinstance(result, (bytes, bytearray)):
        return bytes(result)
    if isinstance(result, str):
        return result.encode()
    return json.dumps(result).encode()


class GrpcProxyActor:
    """Async actor hosting the grpc.aio ingress (one per edge node)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self.router = AsyncRouter()
        self._server = None

    async def ready(self) -> int:
        import grpc

        if self._server is not None:
            return self.port
        self.router.ensure_poller()
        server = grpc.aio.server()
        ident = bytes
        handlers = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                self._predict,
                request_deserializer=decode_payload,
                response_serializer=encode_payload),
            "PredictStream": grpc.unary_stream_rpc_method_handler(
                self._predict_stream,
                request_deserializer=decode_payload,
                response_serializer=encode_payload),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                self._healthz,
                request_deserializer=ident,
                response_serializer=encode_payload),
            "ListDeployments": grpc.unary_unary_rpc_method_handler(
                self._list_deployments,
                request_deserializer=ident,
                response_serializer=encode_payload),
        }
        server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))
        self.port = server.add_insecure_port(f"{self.host}:{self.port}")
        await server.start()
        self._server = server
        return self.port

    async def get_config(self) -> dict:
        return {"host": self.host, "port": self.port}

    # ------------------------------------------------------------ handlers

    @staticmethod
    def _route_metadata(context):
        md = {k: v for k, v in (context.invocation_metadata() or ())}
        return md

    async def _target(self, context):
        import grpc

        md = self._route_metadata(context)
        deployment = md.get("deployment")
        if not deployment:
            await context.abort(grpc.StatusCode.INVALID_ARGUMENT,
                                "missing 'deployment' metadata key")
        return deployment, md.get("method") or None, md

    async def _predict(self, payload: bytes, context) -> bytes:
        import grpc

        deployment, method, md = await self._target(context)
        req = Request(method="GRPC", path="/", headers=md, body=payload)
        try:
            result = await self.router.call(deployment, (req,), {},
                                            method=method)
        except LookupError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:  # noqa: BLE001 — replica-side error
            await context.abort(grpc.StatusCode.INTERNAL, repr(e))
        return _result_bytes(result)

    async def _predict_stream(self, payload: bytes,
                              context) -> AsyncIterator[bytes]:
        import grpc

        deployment, method, md = await self._target(context)
        req = Request(method="GRPC", path="/", headers=md, body=payload)
        try:
            name = await self.router.choose(deployment)
            h = self.router._handle_for(name)
            gen = h.handle_request_gen.options(
                num_returns="streaming", generator_backpressure=256).remote(
                (req,), {}, method)
            from .asgi import ASGIStart
            async for ref in gen:
                chunk = await self.router._aget(ref)
                if isinstance(chunk, ASGIStart):
                    continue  # HTTP framing has no gRPC equivalent
                yield _result_bytes(chunk)
        except LookupError as e:
            await context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except Exception as e:  # noqa: BLE001 — same contract as _predict
            await context.abort(grpc.StatusCode.INTERNAL, repr(e))

    async def _healthz(self, _request: bytes, _context) -> bytes:
        return b"ok"

    async def _list_deployments(self, _request: bytes, _context) -> bytes:
        await self.router.refresh(force=True)
        return json.dumps(self.router._routes).encode()

    async def drain(self) -> bool:
        if self._server is not None:
            await self._server.stop(grace=1.0)
            self._server = None
        return True
