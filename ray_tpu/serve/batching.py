"""Dynamic request batching: ``@serve.batch``.

Reference: ``python/ray/serve/batching.py:65`` (_BatchQueue) / ``@serve.batch``
:337-351.  An async method decorated with ``@batch`` receives *lists* of its
arguments; concurrent callers are queued and flushed together when either
``max_batch_size`` requests are waiting or ``batch_wait_timeout_s`` elapses.
On TPU replicas this is what keeps the MXU fed: one forward pass over a padded
batch instead of N singleton passes.
"""

from __future__ import annotations

import asyncio
import functools
import time
from typing import Any, Callable, List, Optional

from . import observability as obs


class _BatchQueue:
    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.queue: asyncio.Queue = asyncio.Queue()
        self._flusher: Optional[asyncio.Task] = None

    def _ensure_flusher(self):
        if self._flusher is None or self._flusher.done():
            self._flusher = asyncio.get_event_loop().create_task(
                self._flush_loop())

    async def submit(self, instance, args, kwargs) -> Any:
        fut = asyncio.get_event_loop().create_future()
        # enqueue stamp: (deployment tag, trace ctx, wall clock) ride the
        # item so _run_batch can account each member's batch_wait and chain
        # its span under the request that queued it
        from ray_tpu.util import tracing
        item_obs = (obs.current_deployment(), tracing.current_context(),
                    time.time()) if obs.enabled() else None
        await self.queue.put((instance, args, kwargs, fut, item_obs))
        self._ensure_flusher()
        return await fut

    async def _flush_loop(self):
        while True:
            batch = [await self.queue.get()]
            deadline = asyncio.get_event_loop().time() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - asyncio.get_event_loop().time()
                if remaining <= 0:
                    break
                try:
                    batch.append(await asyncio.wait_for(self.queue.get(),
                                                        remaining))
                except asyncio.TimeoutError:
                    break
            await self._run_batch(batch)
            if self.queue.empty():
                return  # flusher exits when idle; resurrected on next submit

    async def _run_batch(self, batch: List[tuple]):
        instance = batch[0][0]
        # Batch each positional/keyword argument into a list.
        n_args = len(batch[0][1])
        arg_lists = [[item[1][i] for item in batch] for i in range(n_args)]
        kw_lists = {k: [item[2][k] for item in batch]
                    for k in batch[0][2]}
        futs = [item[3] for item in batch]
        self._record_flush(batch)
        try:
            if instance is not None:
                results = self.fn(instance, *arg_lists, **kw_lists)
            else:
                results = self.fn(*arg_lists, **kw_lists)
            if asyncio.iscoroutine(results):
                results = await results
            if len(results) != len(futs):
                raise ValueError(
                    f"@serve.batch function returned {len(results)} results "
                    f"for a batch of {len(futs)}")
            for fut, res in zip(futs, results):
                if not fut.done():
                    fut.set_result(res)
        except BaseException as e:  # noqa: BLE001
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)

    def _record_flush(self, batch: List[tuple]):
        """Observability for one flushed batch: occupancy (how full vs
        max_batch_size — padding waste is the complement), each member's
        queue wait, and a ``batch_wait`` span per member chained under the
        request that queued it."""
        stamps = [item[4] for item in batch if item[4] is not None]
        if not stamps or not obs.enabled():
            return
        now = time.time()
        deployment = stamps[0][0]
        obs.record_batch(deployment, len(batch), self.max_batch_size,
                         waits_s=[now - t0 for _d, _c, t0 in stamps])
        for _dep, ctx, t0 in stamps:
            if ctx is None:
                # no request trace: skip rather than let record_span fall
                # back to the flusher TASK's inherited context (which is
                # whatever request created the flusher — the span would
                # chain into an unrelated request's trace)
                continue
            obs.stamp_span("batch_wait", t0, now - t0,
                           trace_id=ctx[0], parent_id=ctx[1],
                           deployment=deployment)


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01):
    """Decorator: turn ``async def method(self, x)`` into a dynamically
    batched ``async def method(self, [x1, x2, ...])`` callee."""

    def wrap(fn: Callable):
        queues: dict = {}  # per-instance (or per-function) queue

        @functools.wraps(fn)
        async def wrapper(*args, **kwargs):
            # Method vs free function: heuristic matching the reference —
            # if the first arg owns the wrapped attr, treat it as self.
            instance = None
            call_args = args
            if args and getattr(type(args[0]), fn.__name__, None) is not None:
                instance = args[0]
                call_args = args[1:]
            key = id(instance)
            q = queues.get(key)
            if q is None:
                q = queues[key] = _BatchQueue(fn, max_batch_size,
                                              batch_wait_timeout_s)
            return await q.submit(instance, call_args, kwargs)

        wrapper._is_serve_batch = True
        return wrapper

    if _fn is not None:
        return wrap(_fn)
    return wrap
