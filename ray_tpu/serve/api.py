"""Serve public API: start/run/status/delete/shutdown + handles.

Reference: ``python/ray/serve/api.py`` (:68 serve.start, :480 serve.run) — the
user surface over the controller.  ``serve.run`` ships Deployments to the
controller actor and blocks until every deployment reports HEALTHY.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Union

import ray_tpu

from .config import HEALTHY
from .controller import CONTROLLER_NAME, ServeController
from .deployment import Deployment
from .http_proxy import PROXY_NAME, HTTPProxyActor
from .router import DeploymentHandle, reset_router


def _get_controller(create: bool = False, http: bool = False,
                    http_host: str = "127.0.0.1", http_port: int = 0,
                    grpc: bool = False, grpc_port: int = 0):
    ctrl = None
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        if not create:
            raise RuntimeError(
                "Serve is not running; call serve.start() or serve.run()")
    if ctrl is None:
        ctrl = ray_tpu.remote(ServeController).options(
            name=CONTROLLER_NAME, lifetime="detached", max_concurrency=1000,
            num_cpus=0.1, get_if_exists=True).remote()
        ray_tpu.get(ctrl.startup.remote(), timeout=30)
    if http and ray_tpu.get(ctrl.get_http_config.remote(), timeout=30) is None:
        proxy = ray_tpu.remote(HTTPProxyActor).options(
            name=PROXY_NAME, lifetime="detached", max_concurrency=1000,
            num_cpus=0.1, get_if_exists=True).remote(http_host, http_port)
        port = ray_tpu.get(proxy.ready.remote(), timeout=30)
        ray_tpu.get(ctrl.set_http_config.remote(
            {"host": http_host, "port": port}), timeout=30)
    if grpc:
        from .grpc_proxy import GRPC_PROXY_NAME, GrpcProxyActor
        gproxy = ray_tpu.remote(GrpcProxyActor).options(
            name=GRPC_PROXY_NAME, lifetime="detached", max_concurrency=1000,
            num_cpus=0.1, get_if_exists=True).remote(http_host, grpc_port)
        ray_tpu.get(gproxy.ready.remote(), timeout=30)
    return ctrl


def start(detached: bool = True, http_options: Optional[dict] = None,
          grpc_options: Optional[dict] = None):
    """Start the Serve control plane: controller + optional HTTP proxy +
    optional gRPC proxy (reference serve.start's gRPCOptions)."""
    http_options = http_options or {}
    return _get_controller(
        create=True, http=bool(http_options),
        http_host=http_options.get("host", "127.0.0.1"),
        http_port=http_options.get("port", 0),
        grpc=grpc_options is not None,
        grpc_port=(grpc_options or {}).get("port", 0))


def run(target: Union[Deployment, Dict[str, Deployment]], *,
        route_prefix: Optional[str] = "/__auto__",
        http: bool = False, timeout_s: float = 60.0,
        _blocking: bool = True) -> DeploymentHandle:
    """Deploy and wait until healthy; returns a handle to the (first)
    deployment (reference: serve.run returns the app handle)."""
    from .graph import collect_deployments, resolve_handles
    # expand deployment graphs: nested Deployments in bound init args
    # become DeploymentHandles; dependencies deploy first so the root
    # never routes to a missing deployment (reference:
    # deployment_graph_build.py).  Dict targets expand each value's graph.
    roots = [target] if isinstance(target, Deployment) \
        else list(target.values())
    seen: Dict[str, Deployment] = {}
    for r in roots:
        for d in collect_deployments(r):
            prev = seen.get(d.name)
            if prev is not None and prev.version() != d.version():
                raise ValueError(
                    f"two different deployments named {d.name!r}; "
                    "give them distinct name= options")
            seen.setdefault(d.name, d)
    deployments = [resolve_handles(d) for d in seen.values()]
    root_name = roots[0].name if roots else None
    if not deployments:
        raise ValueError("nothing to deploy")
    if route_prefix != "/__auto__" and isinstance(target, Deployment):
        import dataclasses
        deployments = [
            dataclasses.replace(d, config=dataclasses.replace(
                d.config, route_prefix=route_prefix))
            if d.name == root_name else d
            for d in deployments]
    ctrl = _get_controller(create=True, http=http)
    for d in deployments:
        ray_tpu.get(ctrl.deploy.remote(d), timeout=30)
    if _blocking:
        _wait_healthy(ctrl, [d.name for d in deployments], timeout_s)
    return DeploymentHandle(root_name)


def _wait_healthy(ctrl, names, timeout_s: float):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        status = ray_tpu.get(ctrl.get_status.remote(), timeout=30)
        if all(status.get(n, {}).get("status") == HEALTHY for n in names):
            return
        time.sleep(0.1)
    raise TimeoutError(
        f"deployments {names} not healthy after {timeout_s}s: "
        f"{ray_tpu.get(ctrl.get_status.remote(), timeout=30)}")


def get_deployment_handle(name: str) -> DeploymentHandle:
    return DeploymentHandle(name)


def status() -> dict:
    ctrl = _get_controller()
    return ray_tpu.get(ctrl.get_status.remote(), timeout=30)


def slo_signal() -> dict:
    """Per-deployment SLO signal (queue depth + rolling p50/p95/p99 TTFT
    from the replicas' heartbeat windows, with stale snapshots dropped
    and counted as ``stale_replicas``) — the documented input contract
    for SLO-driven autoscaling, consumed by the ``policy="slo"``
    autoscaler (serve/slo_autoscaler.py).  Same data ``raytpu serve
    status`` tables and ``/api/serve`` embed."""
    ctrl = _get_controller()
    return ray_tpu.get(ctrl.get_serve_signal.remote(), timeout=30)


def autoscale_decisions(deployment: Optional[str] = None,
                        limit: int = 50) -> list:
    """Tail of the autoscaler's bounded decision ring (newest last): one
    record per scale event — {ts, deployment, policy, direction, reason,
    from_replicas, to_replicas, wanted, capped, signal} — including
    capacity-capped asks ("wanted N, cluster capped at M").  Also
    surfaced by ``raytpu serve status`` and ``GET /api/serve/autoscale``."""
    ctrl = _get_controller()
    return ray_tpu.get(ctrl.get_autoscale_decisions.remote(
        deployment=deployment, limit=limit), timeout=30)


def http_config() -> Optional[dict]:
    ctrl = _get_controller()
    return ray_tpu.get(ctrl.get_http_config.remote(), timeout=30)


def grpc_config() -> Optional[dict]:
    from .grpc_proxy import GRPC_PROXY_NAME
    try:
        gproxy = ray_tpu.get_actor(GRPC_PROXY_NAME)
    except Exception:
        return None
    return ray_tpu.get(gproxy.get_config.remote(), timeout=30)


def delete(name: str, timeout_s: float = 30.0):
    ctrl = _get_controller()
    ray_tpu.get(ctrl.delete_deployment.remote(name), timeout=30)
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if name not in ray_tpu.get(ctrl.get_status.remote(), timeout=30):
            return
        time.sleep(0.1)
    raise TimeoutError(f"deployment {name} still present after {timeout_s}s")


def shutdown():
    """Tear down the control plane: drain replicas, stop proxy + controller."""
    try:
        ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
    except Exception:
        reset_router()
        return
    try:
        proxy = ray_tpu.get_actor(PROXY_NAME)
        ray_tpu.get(proxy.drain.remote(), timeout=10)
        ray_tpu.kill(proxy)
    except Exception:
        pass
    try:
        from .grpc_proxy import GRPC_PROXY_NAME
        gproxy = ray_tpu.get_actor(GRPC_PROXY_NAME)
        ray_tpu.get(gproxy.drain.remote(), timeout=10)
        ray_tpu.kill(gproxy)
    except Exception:
        pass
    try:
        ray_tpu.get(ctrl.graceful_shutdown.remote(), timeout=60)
    except Exception:
        pass
    try:
        ray_tpu.kill(ctrl)
    except Exception:
        pass
    reset_router()
