"""ServeController: the reconciliation control loop.

Reference: ``python/ray/serve/controller.py:82`` (ServeController actor) and
``_private/deployment_state.py:1156`` (DeploymentState replica state machine).
One detached named actor owns the desired state (deployments shipped by
``serve.run``) and continuously reconciles the live replica set against it:

* scale up: start replica actors until the target count of the target version
  is running;
* rolling update: when a deployment's code/config version changes, surge new
  replicas first, then drain+stop outdated ones once enough new ones are
  healthy (no request ever has zero healthy replicas to land on);
* health: periodic ``health_check`` pings per replica; 3 consecutive failures
  (or actor death) removes the replica, and the next reconcile pass replaces
  it;
* autoscaling: queue-depth driven (reference: _private/autoscaling_policy.py)
  — desired = ceil(total ongoing / target_ongoing_requests) clamped to
  [min, max], with upscale/downscale decision delays.

Routers and proxies pull the routing table with a version tag and long-poll
``wait_for_table_change`` (reference: _private/long_poll.py).
"""

from __future__ import annotations

import asyncio
import math
import time
import uuid
from typing import Any, Dict, List, Optional

from .config import (DEPLOYING, DELETING, HEALTHY, POLICY_SLO, UNHEALTHY,
                     UPDATING, DeploymentConfig)
from .deployment import Deployment
from .slo_autoscaler import (AutoscaleLedger, SLOPolicy,
                             capacity_max_replicas)

CONTROLLER_NAME = "serve:controller"

# replica lifecycle states (reference: _private/common.py ReplicaState)
STARTING = "STARTING"
RUNNING = "RUNNING"
DRAINING = "DRAINING"

HEALTH_FAILURE_THRESHOLD = 3


class _Replica:
    __slots__ = ("name", "handle", "version", "state", "failures",
                 "started_at", "last_ongoing", "code_hash", "last_probe",
                 "last_slo", "last_slo_ts", "last_prefix")

    def __init__(self, name: str, handle, version: str,
                 code_hash: Optional[str] = None):
        self.name = name
        self.handle = handle
        self.version = version
        self.state = STARTING
        self.failures = 0
        self.started_at = time.monotonic()
        self.last_ongoing = 0
        self.code_hash = code_hash
        self.last_probe = 0.0
        #: rolling SLO snapshot the replica piggybacks on health checks
        #: ({queue_depth, ttft_p50/p95/p99_ms, window_n} — serve/
        #: observability.slo_snapshot)
        self.last_slo: dict = {}
        #: monotonic stamp of the last SUCCESSFUL snapshot delivery — the
        #: staleness guard drops snapshots older than 3x the heartbeat
        #: period from the deployment rollup (a wedged replica's frozen
        #: p95 must not pollute the aggregate forever)
        self.last_slo_ts = 0.0
        #: prefix-cache digest piggybacked on the same heartbeat
        #: ({page, blocks: [hex block hashes]} — LLMServer.prefix_digest);
        #: None when the deployment doesn't expose one.  Shares
        #: last_slo_ts as its freshness stamp.
        self.last_prefix: Optional[dict] = None


class _DeploymentState:
    def __init__(self, deployment: Deployment):
        self.deployment = deployment
        self.version = deployment.version()
        self.app_blob = deployment.app_blob()
        self.replicas: List[_Replica] = []
        self.deleting = False
        # autoscale bookkeeping
        self.autoscale_target: Optional[int] = None
        self._scale_pending_since: Optional[float] = None
        self._scale_pending_dir = 0
        #: SLO-policy control state (serve/slo_autoscaler.SLOPolicy),
        #: created lazily on the first slo-policy reconcile tick and
        #: replaced when the deployment's autoscaling config changes
        self.slo_policy: Optional[SLOPolicy] = None
        self.last_decision: Optional[dict] = None

    @property
    def config(self) -> DeploymentConfig:
        return self.deployment.config

    def target_count(self) -> int:
        if self.deleting:
            return 0
        if self.config.autoscaling is not None:
            if self.autoscale_target is None:
                self.autoscale_target = self.config.initial_replicas()
            return self.autoscale_target
        return self.config.num_replicas

    def running(self, version: Optional[str] = None) -> List[_Replica]:
        return [r for r in self.replicas
                if r.state == RUNNING
                and (version is None or r.version == version)]

    def slo_rollup(self, now: Optional[float] = None) -> dict:
        """Deployment-level SLO signal from the replicas' heartbeat
        snapshots: total queue depth, and the WORST replica's rolling TTFT
        percentiles (the conservative scaling signal — one hot replica is
        exactly what an SLO autoscaler must react to).

        Staleness guard: snapshots older than 3x the heartbeat period are
        dropped from the rollup and counted as ``stale_replicas`` — a
        wedged replica's frozen p95 would otherwise pollute the aggregate
        (and hold the worst-replica percentile) forever.  The horizon
        never undercuts a legitimately slow ping: one probe is in flight
        per replica, so the worst honest gap between stamps is a full
        ``health_check_timeout_s`` plus a period — a busy-but-healthy
        replica must not be counted stale for a ping it is still allowed
        to be answering."""
        now = time.monotonic() if now is None else now
        cfg = self.config
        horizon = now - max(3.0 * cfg.health_check_period_s,
                            cfg.health_check_timeout_s
                            + cfg.health_check_period_s)
        running = self.running()
        fresh = [r for r in running if r.last_slo_ts >= horizon]
        out = {
            "queue_depth": sum(
                int(r.last_slo.get("queue_depth", r.last_ongoing))
                for r in fresh),
            "window_n": sum(int(r.last_slo.get("window_n", 0))
                            for r in fresh),
            "stale_replicas": len(running) - len(fresh),
        }
        for p in ("p50", "p95", "p99"):
            key = f"ttft_{p}_ms"
            vals = [(r.last_slo[key], int(r.last_slo.get("window_n", 0)))
                    for r in fresh if key in r.last_slo]
            if vals:
                v, wn = max(vals)
                out[key] = v
                if p == "p95":
                    # the autoscaler's min_window_n gate must judge the
                    # WINDOW that produced the worst p95, not the
                    # deployment-wide sample sum — one replica's single
                    # slow request would otherwise read as a surge-worthy
                    # percentile backed by everyone else's samples
                    out["ttft_p95_window_n"] = wn
        return out

    def status(self) -> str:
        if self.deleting:
            return DELETING
        target = self.target_count()
        current = self.running(self.version)
        if len(current) >= target and all(
                r.version == self.version for r in self.replicas
                if r.state != DRAINING):
            return HEALTHY
        if any(r.version != self.version for r in self.replicas):
            return UPDATING
        if any(r.failures > 0 for r in self.replicas):
            return UNHEALTHY if not current else DEPLOYING
        return DEPLOYING


class ServeController:
    """The singleton control-loop actor (name: ``serve:controller``)."""

    def __init__(self, reconcile_period_s: float = 0.25):
        self.reconcile_period_s = reconcile_period_s
        self._deployments: Dict[str, _DeploymentState] = {}
        self._table_version = 0
        self._table_event: Optional[asyncio.Event] = None
        self._loop_task: Optional[asyncio.Task] = None
        self._shutting_down = False
        self._http_config: Optional[dict] = None
        # strong refs to in-flight drain_then_kill tasks: keeps them alive,
        # and graceful_shutdown awaits them so detached replicas are never
        # orphaned past controller death
        self._drain_tasks: set = set()
        #: bounded ring of autoscale decision records (every scale event,
        #: incl. capacity-capped asks) + raytpu_autoscale_* metric stamps
        self._autoscale_ledger = AutoscaleLedger()
        #: one in-flight health ping per replica name (background tasks —
        #: a wedged ping must not stall the other replicas' heartbeats)
        self._probe_tasks: Dict[str, asyncio.Task] = {}
        # cluster-view cache for capacity-aware scale-up (refreshed at
        # most once a second — the reconcile loop must not hammer the GCS)
        self._capacity_view: Optional[dict] = None
        self._capacity_view_ts = 0.0

    # ------------------------------------------------------------ lifecycle

    async def startup(self) -> bool:
        """Idempotent: spawn the reconcile loop on the actor's event loop."""
        from . import observability as obs
        # a wedged reconcile loop surfaces as
        # raytpu_event_loop_lag_seconds{process="serve_controller"}
        obs.ensure_loop_monitor(self, "serve_controller")
        if self._loop_task is None or self._loop_task.done():
            self._table_event = asyncio.Event()
            self._loop_task = asyncio.get_event_loop().create_task(
                self._reconcile_loop())
        return True

    async def graceful_shutdown(self) -> bool:
        """Drain and stop every replica; used by serve.shutdown().  Blocks
        until every drain task finished — the caller kills this controller
        right after, and an unfinished background drain would orphan the
        detached replica actors."""
        self._shutting_down = True
        if self._loop_task is not None:
            self._loop_task.cancel()
        for t in list(self._probe_tasks.values()):
            t.cancel()
        self._probe_tasks.clear()
        for ds in self._deployments.values():
            for r in list(ds.replicas):
                await self._stop_replica(ds, r, graceful=True)
        if self._drain_tasks:
            await asyncio.gather(*list(self._drain_tasks),
                                 return_exceptions=True)
        for ds in self._deployments.values():
            ds.replicas.clear()
        self._deployments.clear()
        self._bump_table()
        return True

    # ------------------------------------------------------------- deploy

    async def deploy(self, deployment: Deployment) -> str:
        """Register/refresh a deployment; reconciliation does the rest.
        Returns the target version."""
        ds = self._deployments.get(deployment.name)
        if ds is None:
            self._deployments[deployment.name] = _DeploymentState(deployment)
        else:
            old_version = ds.version
            ds.deployment = deployment
            ds.version = deployment.version()
            ds.app_blob = deployment.app_blob()
            ds.deleting = False
            if old_version != ds.version:
                # user_config-only change: reconfigure in place, no restart
                if self._only_user_config_changed(ds, old_version):
                    await self._reconfigure_all(ds)
        return self._deployments[deployment.name].version

    def _only_user_config_changed(self, ds: _DeploymentState,
                                  old_version: str) -> bool:
        # Replicas of the old version whose code blob matches the new one can
        # be reconfigured in place (reference: deployment_state lightweight
        # config updates).  Compare code-only hash.
        import hashlib
        code_hash = hashlib.sha256(ds.app_blob).hexdigest()
        return bool(ds.replicas) and all(r.code_hash == code_hash
                                         for r in ds.replicas)

    async def _reconfigure_all(self, ds: _DeploymentState):
        cfg = ds.config
        for r in ds.replicas:
            try:
                await self._aget(r.handle.reconfigure.remote(cfg.user_config))
                r.version = ds.version
            except Exception:
                r.failures = HEALTH_FAILURE_THRESHOLD  # replace it

    async def delete_deployment(self, name: str) -> bool:
        ds = self._deployments.get(name)
        if ds is None:
            return False
        ds.deleting = True
        return True

    # ------------------------------------------------------- table queries

    def _bump_table(self):
        self._table_version += 1
        if self._table_event is not None:
            self._table_event.set()
            self._table_event = asyncio.Event()

    async def get_routing_table(self):
        """(version, {deployment -> [replica actor names]}) — RUNNING only."""
        table = {name: [r.name for r in ds.running()]
                 for name, ds in self._deployments.items() if not ds.deleting}
        return self._table_version, table

    async def get_routing_info(self):
        """(version, table, digests) — the routing table plus each running
        replica's last heartbeat prefix-cache digest, for cache-aware
        routing.  Digests ride the SAME freshness stamp as the SLO
        snapshot and share slo_rollup's staleness horizon: a wedged
        replica's frozen digest would otherwise keep attracting the
        prefixes it can no longer serve quickly.  Replicas without a
        digest (non-LLM deployments, prefix cache off) simply don't
        appear — the router falls back to pure p2c for them."""
        now = time.monotonic()
        table: Dict[str, List[str]] = {}
        digests: Dict[str, dict] = {}
        for name, ds in self._deployments.items():
            if ds.deleting:
                continue
            running = ds.running()
            table[name] = [r.name for r in running]
            cfg = ds.config
            horizon = now - max(3.0 * cfg.health_check_period_s,
                                cfg.health_check_timeout_s
                                + cfg.health_check_period_s)
            for r in running:
                if r.last_prefix and r.last_slo_ts >= horizon:
                    digests[r.name] = r.last_prefix
        return self._table_version, table, digests

    async def wait_for_table_change(self, known_version: int,
                                    timeout_s: float = 10.0):
        """Long-poll: return as soon as the table moves past known_version
        (reference: _private/long_poll.py LongPollHost)."""
        if self._table_version != known_version:
            return await self.get_routing_table()
        ev = self._table_event
        if ev is not None:
            try:
                await asyncio.wait_for(ev.wait(), timeout_s)
            except asyncio.TimeoutError:
                pass
        return await self.get_routing_table()

    async def get_http_routes(self):
        """{route_prefix -> deployment name} for the proxies."""
        routes = {}
        for name, ds in self._deployments.items():
            if ds.deleting:
                continue
            prefix = ds.config.route_prefix
            if prefix is None:
                prefix = f"/{name}"
            if prefix:
                routes[prefix] = name
        return self._table_version, routes

    async def get_status(self):
        out = {}
        for name, ds in self._deployments.items():
            out[name] = {
                "status": ds.status(),
                "version": ds.version,
                "target_replicas": ds.target_count(),
                "slo": ds.slo_rollup(),
                "replicas": [
                    {"name": r.name, "state": r.state, "version": r.version,
                     "ongoing": r.last_ongoing, "slo": r.last_slo}
                    for r in ds.replicas],
            }
            if ds.config.autoscaling is not None:
                out[name]["autoscale"] = {
                    "policy": ds.config.autoscaling.policy,
                    "target": ds.target_count(),
                    "min_replicas": ds.config.autoscaling.min_replicas,
                    "max_replicas": ds.config.autoscaling.max_replicas,
                    "last_decision": ds.last_decision,
                }
        return out

    async def get_autoscale_decisions(self, deployment: Optional[str] = None,
                                      limit: int = 50):
        """Tail of the bounded autoscale decision ring (newest last):
        every scale event — direction, reason, from/to replica counts,
        the signal snapshot it acted on, and capacity caps ("wanted N,
        cluster capped at M")."""
        return self._autoscale_ledger.tail(limit=limit,
                                           deployment=deployment)

    async def get_serve_signal(self):
        """The SLO autoscaler input contract, one row per deployment:
        ``{deployment: {queue_depth, ttft_p50_ms?, ttft_p95_ms?,
        ttft_p99_ms?, window_n, stale_replicas, running_replicas,
        target_replicas, ts}}``.  Queue depth is the live total across
        RUNNING replicas with a FRESH heartbeat snapshot (stale ones —
        older than 3x the heartbeat period — are dropped and counted in
        ``stale_replicas``); TTFT percentiles are the worst fresh
        replica's rolling window (absent until a replica has served a
        request inside the window).  Consumed by the SLO autoscaling
        policy (serve/slo_autoscaler.py), ``raytpu serve status``, and
        ``/api/serve`` dashboards."""
        now = time.time()
        out = {}
        for name, ds in self._deployments.items():
            if ds.deleting:
                continue
            out[name] = {
                **ds.slo_rollup(),
                "running_replicas": len(ds.running()),
                "target_replicas": ds.target_count(),
                "ts": now,
            }
            # declared SLO rides the signal so consumers (the health
            # plane's TTFT_BREACH rule, dashboards) can judge the
            # percentiles without digging into deployment config
            auto = ds.config.autoscaling
            if auto is not None and auto.ttft_p95_target_ms is not None:
                out[name]["ttft_p95_target_ms"] = auto.ttft_p95_target_ms
        return out

    async def set_http_config(self, config: dict):
        self._http_config = config
        return True

    async def get_http_config(self):
        return self._http_config

    # ------------------------------------------------- failure reporting

    async def report_replica_failure(self, deployment: str, replica: str):
        """Routers report dead replicas they hit; drop them immediately so the
        table converges faster than the next health-check period."""
        ds = self._deployments.get(deployment)
        if ds is None:
            return False
        for r in list(ds.replicas):
            if r.name == replica:
                ds.replicas.remove(r)
                t = self._probe_tasks.pop(r.name, None)
                if t is not None:
                    t.cancel()
                self._bump_table()
                await self._kill_replica(r)
                return True
        return False

    # --------------------------------------------------------- reconcile

    async def _reconcile_loop(self):
        while not self._shutting_down:
            try:
                changed = False
                for name in list(self._deployments):
                    ds = self._deployments[name]
                    changed |= await self._reconcile_one(ds)
                    if ds.deleting and not ds.replicas:
                        del self._deployments[name]
                        changed = True
                if changed:
                    self._bump_table()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                import traceback
                traceback.print_exc()
            await asyncio.sleep(self.reconcile_period_s)

    async def _reconcile_one(self, ds: _DeploymentState) -> bool:
        changed = await self._probe_health(ds)
        if ds.config.autoscaling is not None and not ds.deleting:
            if ds.config.autoscaling.policy == POLICY_SLO:
                await self._autoscale_slo(ds)
            else:
                self._autoscale(ds)
        target = ds.target_count()
        current = [r for r in ds.replicas if r.version == ds.version
                   and r.state in (STARTING, RUNNING)]
        outdated = [r for r in ds.replicas if r.version != ds.version
                    and r.state in (STARTING, RUNNING)]

        # Scale up new-version replicas toward the target.
        for _ in range(target - len(current)):
            self._start_replica(ds)
            changed = True

        # Rolling update: once enough new-version replicas serve traffic,
        # retire outdated ones (one batch per pass keeps it gradual).
        if outdated and len(ds.running(ds.version)) >= min(
                target, max(1, target - len(outdated) + 1)):
            victim = outdated[0]
            await self._stop_replica(ds, victim, graceful=True)
            changed = True

        # Scale down (autoscaling or lowered num_replicas / deletion):
        # drain-aware victim order — STARTING replicas first (nothing in
        # flight to drain), then the EMPTIEST running replica (fewest
        # ongoing requests = shortest graceful drain, newest breaks
        # ties); every victim rides the graceful path (stop accepting,
        # finish in-flight, then kill — never mid-request).
        excess = len(current) - target
        victims = sorted(
            current,
            key=lambda r: (0 if r.state == STARTING else 1,
                           int(r.last_slo.get("queue_depth", r.last_ongoing)),
                           -r.started_at))
        for r in victims[:max(0, excess)]:
            await self._stop_replica(ds, r, graceful=True)
            changed = True
        return changed

    async def _probe_health(self, ds: _DeploymentState) -> bool:
        """Ping replicas; promote STARTING->RUNNING, cull repeated failures.

        Pings run as INDEPENDENT background tasks (one in flight per
        replica), not a gathered pass: a dead replica's ping rides out the
        full health_check_timeout_s, and awaiting it inline would stall
        every healthy replica's heartbeat stamp behind it — exactly when a
        node dies mid-storm, the survivors' SLO snapshots would all go
        stale and the autoscaler would fly blind (observed in the storm
        bench before this went background)."""
        import ray_tpu
        changed = False
        now = time.monotonic()
        # STARTING replicas are probed every pass (fast promotion); RUNNING
        # ones at the configured cadence — user check_health hooks can be
        # expensive (reference honors health_check_period_s the same way)
        due = [r for r in ds.replicas
               if (r.state == STARTING
                   or (r.state == RUNNING and now - r.last_probe
                       >= ds.config.health_check_period_s))
               and r.name not in self._probe_tasks]

        async def ping(r: _Replica):
            try:
                res = await asyncio.wait_for(
                    self._aget(r.handle.health_check.remote()),
                    ds.config.health_check_timeout_s)
                r.failures = 0
                r.last_ongoing = int(res.get("ongoing", 0))
                r.last_slo = res.get("slo") or {}
                r.last_slo_ts = time.monotonic()
                r.last_prefix = res.get("prefix")
                if r.state == STARTING:
                    r.state = RUNNING
                    self._bump_table()
            except (ray_tpu.ActorDiedError, ray_tpu.WorkerCrashedError):
                r.failures = HEALTH_FAILURE_THRESHOLD  # dead: cull now
            except asyncio.CancelledError:
                raise
            except Exception:
                r.failures += 1
            finally:
                self._probe_tasks.pop(r.name, None)

        for r in due:
            r.last_probe = now
            self._probe_tasks[r.name] = \
                asyncio.get_event_loop().create_task(ping(r))
        for r in list(ds.replicas):
            if r.failures >= HEALTH_FAILURE_THRESHOLD:
                ds.replicas.remove(r)
                t = self._probe_tasks.pop(r.name, None)
                if t is not None:
                    t.cancel()
                await self._kill_replica(r)
                changed = True
        return changed

    # ------------------------------------------------------- autoscaling

    def _autoscale(self, ds: _DeploymentState):
        cfg = ds.config.autoscaling
        running = ds.running()
        if not running:
            # Scale-up-from-zero: an empty running set used to bail here,
            # so a deployment whose replicas all died (or whose
            # min_replicas floor was freshly breached) never recovered —
            # there is no ongoing-request signal without a replica to
            # carry it.  Treat zero running as desired=max(min_replicas,1)
            # immediately (no decision delay: waiting out a timer on a
            # dead deployment is deadlock-by-policy).
            desired = max(cfg.min_replicas, 1)
            if (ds.autoscale_target or 0) < desired:
                ds.autoscale_target = desired
                ds._scale_pending_since = None
                ds._scale_pending_dir = 0
            return
        total_ongoing = sum(r.last_ongoing for r in running)
        raw = total_ongoing / max(cfg.target_ongoing_requests, 1e-9)
        desired = math.ceil(raw * cfg.smoothing_factor)
        desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))
        current = ds.autoscale_target or len(running)
        if desired == current:
            ds._scale_pending_since = None
            ds._scale_pending_dir = 0
            return
        direction = 1 if desired > current else -1
        now = time.monotonic()
        if ds._scale_pending_dir != direction:
            ds._scale_pending_dir = direction
            ds._scale_pending_since = now
        delay = (cfg.upscale_delay_s if direction > 0
                 else cfg.downscale_delay_s)
        if now - (ds._scale_pending_since or now) >= delay:
            ds.autoscale_target = desired
            ds._scale_pending_since = None
            ds._scale_pending_dir = 0

    async def _autoscale_slo(self, ds: _DeploymentState):
        """One SLO-policy control tick: staleness-guarded signal in,
        (possibly) a new ``autoscale_target`` + a decision record out."""
        cfg = ds.config.autoscaling
        if ds.slo_policy is None or ds.slo_policy.cfg is not cfg:
            ds.slo_policy = SLOPolicy(cfg)
        signal = ds.slo_rollup()
        signal["running_replicas"] = len(ds.running())
        current = ds.target_count()
        # capacity-aware clamp: desired replicas the scheduler cannot
        # place would park STARTING forever while the record claims the
        # storm was handled — ask the cluster view what fits, and stamp
        # "wanted N, cluster capped at M" when it caps the ask
        alive = len([r for r in ds.replicas
                     if r.state in (STARTING, RUNNING)])
        cpus = float(ds.config.ray_actor_options.get("num_cpus", 1) or 1)
        cap = capacity_max_replicas(await self._cluster_view(), alive, cpus)
        dec = ds.slo_policy.decide(signal, current, time.monotonic(),
                                   capacity_max=cap)
        if dec is None:
            return
        last = ds.last_decision
        if (dec.desired == current and dec.capped and last is not None
                and last.get("capped")
                and last.get("to_replicas") == dec.desired
                and last.get("wanted") == dec.wanted
                and last.get("reason") == dec.reason):
            # an ONGOING identical capacity cap: one record per episode —
            # re-recording every delay period would flood the shared ring
            # and evict every other deployment's real scale history
            return
        ds.last_decision = self._autoscale_ledger.record(
            ds.deployment.name, dec, current, signal, cfg.policy)
        if dec.desired != current:
            ds.autoscale_target = dec.desired

    async def _cluster_view(self) -> Optional[dict]:
        """Cached GCS cluster view for capacity-aware scale-up (refreshed
        at most once a second; None — don't clamp — when unavailable)."""
        now = time.monotonic()
        if now - self._capacity_view_ts < 1.0:
            return self._capacity_view
        self._capacity_view_ts = now
        try:
            from ray_tpu.core import rpc
            from ray_tpu.core.core_worker import global_worker
            w = global_worker()
            fut = asyncio.run_coroutine_threadsafe(
                w.gcs.call("get_cluster_view"), rpc.get_loop())
            self._capacity_view = await asyncio.wait_for(
                asyncio.wrap_future(fut), 5.0)
        except Exception:  # view unavailable: scale decisions go unclamped
            self._capacity_view = None
        return self._capacity_view

    # ------------------------------------------------- replica start/stop

    def _start_replica(self, ds: _DeploymentState):
        import hashlib

        import ray_tpu
        from .replica import ReplicaActor

        name = f"serve:{ds.deployment.name}:{uuid.uuid4().hex[:8]}"
        opts = dict(ds.config.ray_actor_options)
        opts.setdefault("num_cpus", 1)
        handle = ray_tpu.remote(ReplicaActor).options(
            name=name, lifetime="detached",
            max_concurrency=ds.config.max_concurrent_queries, **opts,
        ).remote(ds.deployment.name, name, ds.app_blob,
                 ds.config.user_config)
        ds.replicas.append(_Replica(
            name, handle, ds.version,
            code_hash=hashlib.sha256(ds.app_blob).hexdigest()))

    async def _stop_replica(self, ds: _DeploymentState, r: _Replica,
                            graceful: bool):
        """Mark DRAINING (drops it from the routing table) and retire it.

        The graceful drain (wait for in-flight requests + unclaimed stream
        buffers) runs as a background task — awaiting it inline would stall
        the reconcile loop for every other deployment for up to
        graceful_shutdown_timeout_s per replica."""
        if r in ds.replicas:
            r.state = DRAINING
        self._bump_table()
        if not graceful:
            if r in ds.replicas:
                ds.replicas.remove(r)
            await self._kill_replica(r)
            return

        async def drain_then_kill():
            try:
                await asyncio.wait_for(
                    self._aget(r.handle.drain.remote(
                        ds.config.graceful_shutdown_timeout_s)),
                    ds.config.graceful_shutdown_timeout_s + 5)
            except Exception:
                pass
            if r in ds.replicas:
                ds.replicas.remove(r)
            await self._kill_replica(r)

        task = asyncio.get_event_loop().create_task(drain_then_kill())
        self._drain_tasks.add(task)
        task.add_done_callback(self._drain_tasks.discard)

    async def _kill_replica(self, r: _Replica):
        import ray_tpu
        try:
            ray_tpu.kill(r.handle)
        except Exception:
            pass

    # --------------------------------------------------------------- util

    @staticmethod
    async def _aget(ref):
        import ray_tpu
        return await asyncio.wrap_future(ray_tpu.as_future(ref))
