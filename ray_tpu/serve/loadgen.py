"""Open-loop load-storm harness: arrival schedules, heavy-tailed request
shapes, and per-request capture for serve benchmarks and the autoscaler
acceptance tests.

Open-loop is the load-bearing property: arrivals fire on a PRECOMPUTED
schedule regardless of how fast the system answers, so queueing delay is
*measured* instead of hidden (a closed-loop client slows its own arrival
rate exactly when the server degrades — the coordinated-omission trap).
Every sample's TTFT/latency is measured from the request's SCHEDULED
arrival time: client-side dispatch lag and server queueing both count.

Three schedule shapes (all seeded -> deterministic):

* :func:`poisson_arrivals` — steady open-loop traffic at a target rate
  (exponential inter-arrivals).
* :func:`ramp_arrivals` — linear rate ramp (inhomogeneous Poisson via
  thinning against the peak rate).
* :func:`burst_arrivals` — the storm: base rate with a ``spike_mult``x
  window in the middle (the 10x arrival spike of the acceptance test).

:class:`StormRunner` walks a schedule on a dispatch thread and fires each
request on a worker pool; :class:`SignalSampler` concurrently samples
``serve.slo_signal()`` into the {arrival rate, TTFT-p95, replica count}
time series the storm benchmarks commit.  Rollups reuse
``bench_llm.request_rollup`` (same schema as the headline LLM numbers) —
the callers own that import; this module only produces samples.
"""

from __future__ import annotations

import dataclasses
import math
import random
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional, Sequence


# ------------------------------------------------------ arrival schedules

def poisson_arrivals(rate_per_s: float, duration_s: float,
                     rng: random.Random) -> List[float]:
    """Exponential inter-arrival times at ``rate_per_s`` for
    ``duration_s`` seconds; returns sorted arrival offsets."""
    out, t = [], 0.0
    if rate_per_s <= 0:
        return out
    while True:
        t += rng.expovariate(rate_per_s)
        if t >= duration_s:
            return out
        out.append(t)


def ramp_arrivals(rate0_per_s: float, rate1_per_s: float, duration_s: float,
                  rng: random.Random) -> List[float]:
    """Linear rate ramp from ``rate0`` to ``rate1`` over ``duration_s``
    (inhomogeneous Poisson by thinning against the peak rate)."""
    peak = max(rate0_per_s, rate1_per_s, 1e-9)
    out = []
    for t in poisson_arrivals(peak, duration_s, rng):
        rate_t = rate0_per_s + (rate1_per_s - rate0_per_s) * (t / duration_s)
        if rng.random() < rate_t / peak:
            out.append(t)
    return out


def burst_arrivals(base_rate_per_s: float, spike_mult: float,
                   spike_start_s: float, spike_end_s: float,
                   duration_s: float, rng: random.Random) -> List[float]:
    """The storm shape: ``base_rate`` everywhere, ``base_rate *
    spike_mult`` inside [spike_start, spike_end) — piecewise-homogeneous
    Poisson, one sorted offset list."""
    out = list(poisson_arrivals(base_rate_per_s, duration_s, rng))
    extra_rate = base_rate_per_s * max(spike_mult - 1.0, 0.0)
    spike_len = max(spike_end_s - spike_start_s, 0.0)
    out.extend(spike_start_s + t for t in
               poisson_arrivals(extra_rate, spike_len, rng))
    out.sort()
    return out


def heavy_tail_len(rng: random.Random, median: int, sigma: float = 0.8,
                   lo: int = 1, hi: int = 1 << 16) -> int:
    """Heavy-tailed (lognormal) length sample clamped to [lo, hi] —
    prompt/decode lengths in production LLM traffic are long-tailed, and
    the tail is what fills batches unevenly and stresses paged KV."""
    n = int(round(median * math.exp(rng.gauss(0.0, sigma))))
    return max(lo, min(hi, n))


def llm_payload(seed: int, idx: int, *, prompt_median: int, prompt_lo: int,
                prompt_hi: int, decode_median: int, decode_lo: int = 4,
                decode_hi: int = 64, vocab: int = 1000,
                prefix_pool: int = 0, prefix_len: int = 0) -> dict:
    """One LLM storm request — heavy-tailed prompt + decode lengths as a
    PURE function of (seed, idx), so per-request shapes are reproducible
    no matter how the firing pool's threads interleave (int-derived
    seed: tuple seeding is a TypeError from Python 3.11).

    ``prefix_pool``/``prefix_len`` model multi-turn / system-prompt
    traffic: each request draws one of ``prefix_pool`` shared prefixes
    (``prefix_len`` tokens, a pure function of seed + pool index) and
    appends its unique heavy-tailed tail.  Requests sharing a prefix hit
    the paged prefix cache — and give cache-aware routing something to
    route ON (the storm A/B's hit-rate lift comes from exactly this)."""
    rng = random.Random(seed * 1_000_003 + idx)
    head: list = []
    if prefix_pool > 0 and prefix_len > 0:
        prng = random.Random(seed * 7_368_787 + rng.randrange(prefix_pool))
        head = [prng.randint(1, vocab) for _ in range(prefix_len)]
    return {
        "tokens": head + [rng.randint(1, vocab) for _ in range(
            heavy_tail_len(rng, prompt_median, lo=prompt_lo,
                           hi=prompt_hi))],
        "max_tokens": heavy_tail_len(rng, decode_median, lo=decode_lo,
                                     hi=decode_hi),
    }


# ------------------------------------------------------- request capture

@dataclasses.dataclass
class RequestSample:
    """One completed (or failed) request, all times relative to the run's
    epoch.  ``ttft_s``/``latency_s`` are measured from ``t_sched`` — the
    scheduled arrival — so dispatch lag and queueing both count."""
    t_sched: float
    t_fired: float
    ttft_s: Optional[float]
    latency_s: float
    ntokens: int
    ok: bool
    error: str = ""

    def rollup_tuple(self):
        """(ttft_s, latency_s, ntokens) — the bench_llm.request_rollup
        input shape."""
        return (self.ttft_s if self.ttft_s is not None else self.latency_s,
                self.latency_s, self.ntokens)


class StormRunner:
    """Open-loop driver: a dispatch thread walks the arrival schedule and
    fires each request on a worker pool, never blocking an arrival on a
    completion.  ``fire(epoch, t_sched, idx) -> RequestSample`` owns the
    request (submit, stream, measure); ``idx`` is the arrival's schedule
    index, so payload generation can be a pure function of (seed, idx)
    even with hundreds of pool threads racing (a shared RNG would make
    per-request shapes run-order-dependent).  The pool is sized for the
    worst concurrent-outstanding burst — an exhausted pool queues the
    fire and the sample's from-schedule timing charges that delay
    honestly."""

    def __init__(self, fire: Callable[[float, float, int], RequestSample],
                 max_outstanding: int = 512):
        self._fire = fire
        self._pool = ThreadPoolExecutor(max_workers=max_outstanding,
                                        thread_name_prefix="loadgen")
        self.samples: List[RequestSample] = []
        self._lock = threading.Lock()
        self.fired = 0
        self.epoch: Optional[float] = None

    def _one(self, epoch: float, t_sched: float, idx: int):
        try:
            s = self._fire(epoch, t_sched, idx)
        except Exception as e:  # noqa: BLE001 — a failed request is a sample
            s = RequestSample(t_sched, time.monotonic() - epoch, None,
                              time.monotonic() - epoch - t_sched, 0,
                              ok=False, error=repr(e))
        with self._lock:
            self.samples.append(s)

    def run(self, arrivals: Sequence[float],
            epoch: Optional[float] = None) -> List[RequestSample]:
        """Fire the whole schedule, wait for every request to finish,
        return the samples sorted by scheduled arrival."""
        epoch = time.monotonic() if epoch is None else epoch
        self.epoch = epoch
        futs = []
        for i, t in enumerate(arrivals):
            delay = epoch + t - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            futs.append(self._pool.submit(self._one, epoch, t, i))
            self.fired += 1
        for f in futs:
            f.result()
        with self._lock:
            return sorted(self.samples, key=lambda s: s.t_sched)

    def shutdown(self):
        self._pool.shutdown(wait=False)


def unary_fire(handle, make_payload: Callable[[int], object],
               timeout_s: float = 120.0) -> Callable[[float, float, int],
                                                     RequestSample]:
    """Fire one unary handle request per arrival; TTFT == full latency
    (the first response byte IS the response).  ``make_payload(idx)``
    must be a pure function of the arrival index (determinism under
    concurrent fires)."""

    def fire(epoch: float, t_sched: float, idx: int) -> RequestSample:
        t_fired = time.monotonic() - epoch
        try:
            handle.remote(make_payload(idx)).result(timeout_s=timeout_s)
            dt = time.monotonic() - epoch - t_sched
            return RequestSample(t_sched, t_fired, dt, dt, 1, ok=True)
        except Exception as e:  # noqa: BLE001
            return RequestSample(t_sched, t_fired, None,
                                 time.monotonic() - epoch - t_sched, 0,
                                 ok=False, error=repr(e))

    return fire


def stream_fire(handle, make_payload: Callable[[int], dict],
                timeout_s: float = 600.0) -> \
        Callable[[float, float, int], RequestSample]:
    """Fire one streaming request per arrival (the LLM path): TTFT at the
    first chunk, one token per chunk.  ``timeout_s`` bounds the whole
    stream (a replica that stops yielding without erroring must fail the
    sample, not hang the storm run past its checkpoints)."""

    def fire(epoch: float, t_sched: float, idx: int) -> RequestSample:
        t_fired = time.monotonic() - epoch
        first, n = None, 0
        try:
            for _chunk in handle.stream(make_payload(idx),
                                        timeout_s=timeout_s):
                if first is None:
                    first = time.monotonic() - epoch - t_sched
                n += 1
            return RequestSample(t_sched, t_fired, first,
                                 time.monotonic() - epoch - t_sched, n,
                                 ok=True)
        except Exception as e:  # noqa: BLE001
            return RequestSample(t_sched, t_fired, first,
                                 time.monotonic() - epoch - t_sched, n,
                                 ok=False, error=repr(e))

    return fire


# ------------------------------------------------------ signal timeline

class SignalSampler(threading.Thread):
    """Samples ``serve.slo_signal()`` every ``period_s`` into the storm
    time series: per tick {t, queue_depth, ttft_p95_ms, running/target
    replicas, stale_replicas, fired-so-far}.  A sampling FAILURE is
    recorded as a gap tick ({"gap": ...}) — the chaos acceptance test
    asserts there are none while a node dies mid-storm."""

    def __init__(self, deployment: str, period_s: float = 0.25,
                 runner: Optional[StormRunner] = None):
        super().__init__(daemon=True, name="loadgen-signal-sampler")
        self.deployment = deployment
        self.period_s = period_s
        self.runner = runner
        self.series: List[dict] = []
        self._stop_ev = threading.Event()
        self._t0: Optional[float] = None

    def run(self):
        from ray_tpu import serve
        self._t0 = time.monotonic()
        while not self._stop_ev.is_set():
            t = round(time.monotonic() - self._t0, 3)
            tick = {"t": t}
            if self.runner is not None:
                tick["fired"] = self.runner.fired
            try:
                row = serve.slo_signal().get(self.deployment)
                if row is None:
                    tick["gap"] = "deployment missing from slo_signal"
                else:
                    tick.update(
                        queue_depth=row.get("queue_depth", 0),
                        ttft_p95_ms=row.get("ttft_p95_ms"),
                        running=row.get("running_replicas"),
                        target=row.get("target_replicas"),
                        stale_replicas=row.get("stale_replicas", 0))
            except Exception as e:  # noqa: BLE001 — a gap IS the finding
                tick["gap"] = repr(e)
            self.series.append(tick)
            self._stop_ev.wait(self.period_s)

    def stop(self) -> List[dict]:
        self._stop_ev.set()
        self.join(timeout=10)
        return self.series

    def gaps(self) -> List[dict]:
        return [s for s in self.series if "gap" in s]


def arrival_rate_series(arrivals: Sequence[float], bucket_s: float = 1.0) \
        -> List[dict]:
    """Arrivals/s per time bucket — the committed storm shape."""
    if not len(arrivals):
        return []
    buckets: dict = {}
    for t in arrivals:
        buckets[int(t // bucket_s)] = buckets.get(int(t // bucket_s), 0) + 1
    return [{"t": b * bucket_s, "arrivals_per_s": n / bucket_s}
            for b, n in sorted(buckets.items())]


def windowed_p95_series(samples: Sequence[RequestSample],
                        window_s: float = 2.0) -> List[dict]:
    """TTFT-p95 over sliding completion windows — how the latency tail
    moved THROUGH the storm (the phase rollup hides the recovery)."""
    done = sorted((s for s in samples if s.ok and s.ttft_s is not None),
                  key=lambda s: s.t_sched + s.latency_s)
    if not done:
        return []
    out = []
    end = done[0].t_sched + done[0].latency_s + window_s
    horizon = done[-1].t_sched + done[-1].latency_s
    while end <= horizon + window_s:
        w = [s.ttft_s for s in done
             if end - window_s <= s.t_sched + s.latency_s < end]
        if w:
            w.sort()
            out.append({"t": round(end, 3),
                        "ttft_p95_ms": round(
                            w[min(len(w) - 1, int(len(w) * 0.95))] * 1000, 2),
                        "n": len(w)})
        end += window_s
    return out
