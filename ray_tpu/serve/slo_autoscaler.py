"""SLO-driven replica autoscaler: the consumer of ``serve.slo_signal()``.

The serve plane has carried the producer side since PR 6 — every replica
heartbeats a rolling TTFT window + queue depth to the controller, which
aggregates it into the documented ``serve.slo_signal()`` contract.  This
module closes the loop (Podracer's pattern: keep the chips saturated with
a cheap control plane that reacts to load):

* :class:`SLOPolicy` — the PURE per-deployment control function.  One
  call per reconcile tick maps ``{TTFT-p95 vs target, queue depth per
  replica, running/target replicas}`` to a desired replica count with
  hysteresis: upscale FAST when the SLO breaches or the queue grows
  (sustained ``upscale_delay_s``, surge capped per decision), downscale
  SLOWLY (one replica per decision, only after the signal has sat below
  ``downscale_low_water`` of both targets for ``downscale_delay_s``), a
  deadband between the two thresholds so a noisy signal cannot flap, and
  immediate recovery when the running set hits zero.  Pure state machine
  — the table-driven unit tests drive it with signal fixtures, no cluster.
* :class:`AutoscaleLedger` — the bounded decision ring (the PR-10
  sched-decision pattern): EVERY scale event — including "wanted N,
  cluster capped at M" — lands as a queryable record surfaced through
  ``serve.status()`` / ``serve.autoscale_decisions()`` / ``raytpu serve
  status`` / ``GET /api/serve/autoscale``, and as ``raytpu_autoscale_*``
  metrics (tag keys bounded to deployment/direction/reason — enforced by
  the test_metric_naming lint).

The controller owns the impure half: it feeds each policy the staleness-
guarded deployment rollup, clamps scale-up against the live cluster view
(capacity-aware: a decision the scheduler cannot place would park
STARTING replicas forever while the record claims success), and retires
scale-down victims emptiest-first through the graceful-drain path (stop
accepting, finish in-flight, then kill — never mid-request).
"""

from __future__ import annotations

import dataclasses
import math
import threading
import time
from collections import deque
from typing import Deque, Dict, List, Optional

from ray_tpu.util.metrics import Counter, Gauge, lazy

from .config import AutoscalingConfig

# closed reason vocabulary — these become metric tag values and decision-
# record fields, so the set must stay bounded (and the allowlist lint in
# tests/test_metric_naming.py pins the tag KEYS to deployment/direction/
# reason)
REASON_SLO_BREACH = "slo_breach"        # TTFT-p95 over target
REASON_QUEUE_DEPTH = "queue_depth"      # queue/replica over target
REASON_RECOVERY = "recovery"            # sustained quiet -> scale down
REASON_ZERO_RUNNING = "zero_running"    # running set hit zero
ALL_REASONS = (REASON_SLO_BREACH, REASON_QUEUE_DEPTH, REASON_RECOVERY,
               REASON_ZERO_RUNNING)

DIR_UP = "up"
DIR_DOWN = "down"


@dataclasses.dataclass
class Decision:
    """One scale event.  ``wanted`` is the policy's unclamped ask;
    ``desired`` is what the controller will reconcile toward; they differ
    exactly when the cluster (or ``max_replicas``) capped the ask —
    ``capped`` marks the capacity case so "wanted N, cluster capped at M"
    is queryable, not silent."""
    desired: int
    direction: str
    reason: str
    wanted: int
    capped: bool = False


class SLOPolicy:
    """Pure hysteresis control function over the slo_signal contract.

    State is only the pending-direction timer and the last-event stamp;
    everything else comes in through ``decide(signal, current, now)``.
    Determinism: same signal sequence + same clock -> same decisions.
    """

    def __init__(self, cfg: AutoscalingConfig):
        self.cfg = cfg
        self._pending_dir = 0
        self._pending_since: Optional[float] = None
        self._last_event_ts: Optional[float] = None
        self._last_event_dir = 0

    # ------------------------------------------------------------ breach

    def _breaches(self, signal: dict, running: int):
        """-> (slo_breach, queue_breach, quiet) for this tick."""
        cfg = self.cfg
        queue = float(signal.get("queue_depth", 0))
        # queue_depth in the rollup sums FRESH replicas only — divide by
        # the fresh count too, or partial staleness silently understates
        # per-replica load (3 of 4 stale: the one reporting replica's
        # queue would be spread over all four)
        fresh = max(running - int(signal.get("stale_replicas", 0)), 1)
        q_per = queue / fresh
        q_target = max(cfg.target_ongoing_requests, 1e-9)
        queue_breach = q_per > q_target

        ttft = signal.get("ttft_p95_ms")
        # gate on the window that PRODUCED the worst p95 when the rollup
        # reports it (ttft_p95_window_n) — the deployment-wide sample sum
        # would let one replica's single slow request read as a percentile
        # backed by everyone else's windows
        window_n = int(signal.get("ttft_p95_window_n",
                                  signal.get("window_n", 0)))
        slo_breach = (cfg.ttft_p95_target_ms is not None
                      and ttft is not None
                      and window_n >= cfg.min_window_n
                      and ttft > cfg.ttft_p95_target_ms)

        # the downscale condition is NOT "no breach": the signal must sit
        # below the low-water fraction of BOTH targets — the deadband in
        # between holds the current count (anti-flap hysteresis)
        low = cfg.downscale_low_water
        quiet = q_per <= q_target * low and (
            cfg.ttft_p95_target_ms is None or ttft is None
            or ttft <= cfg.ttft_p95_target_ms * low)
        return slo_breach, queue_breach, quiet

    def _wanted_up(self, signal: dict, running: int, slo_breach: bool) -> int:
        """The unclamped scale-up ask: enough replicas to absorb the live
        queue at the per-replica target, surged by the TTFT breach ratio
        (capped per decision so one noisy window cannot 10x the fleet)."""
        cfg = self.cfg
        queue = float(signal.get("queue_depth", 0))
        want = math.ceil(queue / max(cfg.target_ongoing_requests, 1e-9))
        if slo_breach:
            ratio = min(signal["ttft_p95_ms"] / cfg.ttft_p95_target_ms,
                        cfg.upscale_surge_max)
            want = max(want, running + 1, math.ceil(running * ratio))
        return max(want, running + 1)

    # ------------------------------------------------------------ decide

    def decide(self, signal: dict, current: int, now: float,
               capacity_max: Optional[int] = None) -> Optional[Decision]:
        """One control tick: ``signal`` is the (staleness-guarded)
        deployment slo_signal row, ``current`` the present target,
        ``capacity_max`` the cluster's placement ceiling (None = don't
        clamp).  Returns a Decision on a scale event, else None."""
        cfg = self.cfg
        running = int(signal.get("running_replicas", 0))

        # zero-running recovery bypasses hysteresis entirely: a deployment
        # with no live replica cannot produce the signal that would scale
        # it, so waiting out a delay would be a deadlock-by-policy
        if running == 0:
            desired = max(cfg.min_replicas, 1)
            if current < desired:
                self._reset_pending()
                return self._event(Decision(desired, DIR_UP,
                                            REASON_ZERO_RUNNING, desired),
                                   now)
            return None

        # all snapshots stale = the controller is flying blind, not idle:
        # the rollup reads queue_depth=0 / no percentiles, which the quiet
        # check would mistake for recovery and shrink the fleet exactly
        # while the real queue is deepest.  Hold until data returns.
        if int(signal.get("stale_replicas", 0)) >= running:
            self._reset_pending()
            return None

        slo_breach, queue_breach, quiet = self._breaches(signal, running)

        if slo_breach or queue_breach:
            wanted = self._wanted_up(signal, running, slo_breach)
            desired = min(wanted, cfg.max_replicas)
            capped = False
            if capacity_max is not None and desired > capacity_max:
                desired = max(capacity_max, current)
                capped = True
            if desired <= current and not capped:
                self._reset_pending()
                return None
            reason = REASON_SLO_BREACH if slo_breach else REASON_QUEUE_DEPTH
            # upscale "fast" still means SUSTAINED for upscale_delay_s —
            # and because every emitted event resets the timer, successive
            # surges are naturally spaced one delay apart (new replicas
            # get a chance to report in before the next surge)
            if not self._sustained(+1, now, cfg.upscale_delay_s):
                return None
            # capped down to where we already are: not a scale event, but
            # "wanted N, cluster capped at M" must still be recorded (the
            # event stamp rate-limits the record to once per delay period)
            return self._event(
                Decision(desired, DIR_UP, reason, wanted, capped=capped), now)

        if quiet and current > cfg.min_replicas:
            # downscale slowly: one replica per decision, and never below
            # what the live queue still needs
            floor = math.ceil(float(signal.get("queue_depth", 0))
                              / max(cfg.target_ongoing_requests, 1e-9))
            desired = max(current - 1, floor, cfg.min_replicas)
            if desired >= current:
                self._reset_pending()
                return None
            # flap guard: a fresh upscale blocks downscale for a full
            # downscale delay measured from the EVENT, not from when the
            # signal first went quiet
            if (self._last_event_dir > 0 and self._last_event_ts is not None
                    and now - self._last_event_ts < cfg.downscale_delay_s):
                return None
            if not self._sustained(-1, now, cfg.downscale_delay_s):
                return None
            return self._event(
                Decision(desired, DIR_DOWN, REASON_RECOVERY, desired), now)

        # deadband (or already at the clamp): hold, and reset the timer so
        # a later excursion must re-earn its full delay
        self._reset_pending()
        return None

    # ------------------------------------------------------------- state

    def _sustained(self, direction: int, now: float, delay: float) -> bool:
        if self._pending_dir != direction or self._pending_since is None:
            self._pending_dir = direction
            self._pending_since = now
        return now - self._pending_since >= delay

    def _reset_pending(self):
        self._pending_dir = 0
        self._pending_since = None

    def _event(self, dec: Decision, now: float) -> Decision:
        self._reset_pending()
        self._last_event_ts = now
        self._last_event_dir = 1 if dec.direction == DIR_UP else -1
        return dec


# ----------------------------------------------------------- decision ring

#: ring length: autoscale events are rare (hysteresis-limited), so a small
#: ring holds hours of history; bounded so the controller's memory is too
DECISION_RING_LEN = 256


class AutoscaleLedger:
    """Bounded ring of autoscale decision records + the raytpu_autoscale_*
    metric stamps.  Records survive the kill switch (they ARE the control
    plane's own audit trail and rare by construction); only the metric
    series are shed with serve_metrics_enabled."""

    def __init__(self, ring_len: int = DECISION_RING_LEN):
        self._ring: Deque[dict] = deque(maxlen=ring_len)
        self._lock = threading.Lock()

    def record(self, deployment: str, dec: Decision, current: int,
               signal: dict, policy: str) -> dict:
        rec = {
            "ts": time.time(),
            "deployment": deployment,
            "policy": policy,
            "direction": dec.direction,
            "reason": dec.reason,
            "from_replicas": current,
            "to_replicas": dec.desired,
            "wanted": dec.wanted,
            "capped": dec.capped,
            # compact signal snapshot: what the policy saw when it decided
            "signal": {k: signal[k] for k in
                       ("queue_depth", "ttft_p95_ms", "window_n",
                        "running_replicas", "stale_replicas")
                       if k in signal},
        }
        with self._lock:
            self._ring.append(rec)
        _stamp_metrics(deployment, dec)
        return rec

    def tail(self, limit: int = 50,
             deployment: Optional[str] = None) -> List[dict]:
        with self._lock:
            recs = list(self._ring)
        if deployment is not None:
            recs = [r for r in recs if r["deployment"] == deployment]
        return recs[-limit:]


# ----------------------------------------------------------------- metrics

def _build_metrics():
    return {
        "decisions": Counter(
            "raytpu_autoscale_decisions_total",
            "autoscale scale events by deployment/direction/reason",
            tag_keys=("deployment", "direction", "reason")),
        "target": Gauge(
            "raytpu_autoscale_target_replicas",
            "current autoscaler replica target per deployment",
            tag_keys=("deployment",)),
        "capped": Gauge(
            "raytpu_autoscale_capped_replicas",
            "replicas the last scale-up wanted but the cluster could not "
            "place (0 when uncapped)",
            tag_keys=("deployment",)),
    }


_metrics = lazy(_build_metrics)


def _stamp_metrics(deployment: str, dec: Decision):
    from . import observability as obs
    if not obs.enabled():
        return
    m = _metrics()
    if m is None:
        return
    m["decisions"].inc_key((("deployment", deployment),
                            ("direction", dec.direction),
                            ("reason", dec.reason)))
    m["target"].set_key((("deployment", deployment),), dec.desired)
    m["capped"].set_key((("deployment", deployment),),
                        max(0, dec.wanted - dec.desired) if dec.capped else 0)


# ------------------------------------------------------------ capacity view

def capacity_max_replicas(cluster_view: Optional[Dict[str, dict]],
                          alive_replicas: int, cpus_per_replica: float) -> \
        Optional[int]:
    """The placement ceiling for one deployment: replicas already alive
    plus how many more the cluster's free CPUs can take — draining and
    dead nodes contribute nothing (the PR-8 drain path routes around
    them, so the autoscaler must not count capacity a drain is about to
    remove).  None when the view is unavailable (don't clamp on a blind
    tick)."""
    if cluster_view is None:
        return None
    free = 0.0
    for info in cluster_view.values():
        if not info.get("alive") or info.get("draining"):
            continue
        free += max(0.0, float(info.get("available", {}).get("CPU", 0.0)))
    return alive_replicas + int(free // max(cpus_per_replica, 1e-9))
