"""Deployment: the user-facing unit of Serve.

Reference: ``python/ray/serve/deployment.py:102`` (Deployment dataclass) and
``api.py:266`` (@serve.deployment).  A Deployment wraps a class (or function),
its init args, and a DeploymentConfig; ``serve.run`` ships it to the
controller which reconciles replica actors.
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import Any, Callable, Dict, Optional, Tuple

import cloudpickle

from .config import AutoscalingConfig, DeploymentConfig


@dataclasses.dataclass
class Deployment:
    func_or_class: Callable
    name: str
    config: DeploymentConfig
    init_args: Tuple = ()
    init_kwargs: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def options(self, **kwargs) -> "Deployment":
        cfg = dataclasses.replace(self.config)
        name = kwargs.pop("name", self.name)
        for k, v in kwargs.items():
            if k == "autoscaling_config":
                cfg.autoscaling = (v if isinstance(v, (AutoscalingConfig,
                                                      type(None)))
                                   else AutoscalingConfig(**v))
            elif hasattr(cfg, k):
                setattr(cfg, k, v)
            else:
                raise TypeError(f"unknown deployment option {k!r}")
        return dataclasses.replace(self, name=name, config=cfg)

    def bind(self, *args, **kwargs) -> "Deployment":
        """Fix init args (reference: deployment DAG .bind)."""
        return dataclasses.replace(self, init_args=args, init_kwargs=kwargs)

    def app_blob(self) -> bytes:
        """Serialized (callable, init_args, init_kwargs) shipped to replicas."""
        return cloudpickle.dumps(
            (self.func_or_class, self.init_args, self.init_kwargs))

    def version(self) -> str:
        """Code+config hash driving rolling updates: replicas whose version
        differs from the target version get replaced (reference:
        _private/deployment_state.py version tracking)."""
        h = hashlib.sha256(self.app_blob())
        h.update(repr(dataclasses.asdict(self.config)).encode())
        return h.hexdigest()[:12]


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None, num_replicas: int = 1,
               max_concurrent_queries: int = 100,
               user_config: Any = None,
               autoscaling_config: Optional[Any] = None,
               health_check_period_s: float = 2.0,
               health_check_timeout_s: float = 10.0,
               graceful_shutdown_timeout_s: float = 10.0,
               route_prefix: Optional[str] = None,
               ray_actor_options: Optional[Dict[str, Any]] = None):
    """``@serve.deployment`` decorator (reference: serve/api.py:266)."""

    def wrap(func_or_class: Callable) -> Deployment:
        auto = autoscaling_config
        if auto is not None and not isinstance(auto, AutoscalingConfig):
            auto = AutoscalingConfig(**auto)
        cfg = DeploymentConfig(
            num_replicas=num_replicas,
            max_concurrent_queries=max_concurrent_queries,
            user_config=user_config,
            autoscaling=auto,
            health_check_period_s=health_check_period_s,
            health_check_timeout_s=health_check_timeout_s,
            graceful_shutdown_timeout_s=graceful_shutdown_timeout_s,
            ray_actor_options=dict(ray_actor_options or {}),
            route_prefix=route_prefix,
        )
        return Deployment(func_or_class=func_or_class,
                          name=name or getattr(func_or_class, "__name__",
                                               "deployment"),
                          config=cfg)

    if _func_or_class is not None:
        return wrap(_func_or_class)
    return wrap
