"""Learner + LearnerGroup: the compiled PPO update.

Reference: ``rllib/core/learner/learner.py:229`` (Learner),
``learner_group.py:61`` (LearnerGroup — multi-GPU updates with NCCL
allreduce).  TPU-first difference: there is no worker-per-accelerator and no
out-of-band allreduce — the whole update (GAE, advantage normalization,
minibatch epochs, clipped loss, Adam) is ONE jitted program, data-parallel
over a device mesh; XLA inserts the gradient psum over ICI.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import numpy as np


class Learner:
    """Owns params + optimizer state; update() is a single pjit'd program."""

    def __init__(self, model, config: Dict[str, Any],
                 mesh: Optional[Any] = None, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        self.model = model
        self.cfg = dict(config)
        self.params = model.init(jax.random.PRNGKey(seed))
        self.opt = optax.chain(
            optax.clip_by_global_norm(self.cfg.get("grad_clip", 0.5)),
            optax.adam(self.cfg.get("lr", 3e-4)),
        )
        self.opt_state = self.opt.init(self.params)
        self.mesh = mesh
        # Does the mesh span >1 process (learner actors under
        # jax.distributed)?  Then host-local batches must be assembled into
        # global jax.Arrays before the jitted call.
        self._multiprocess = (
            mesh is not None
            and len({d.process_index for d in mesh.devices.flat}) > 1)
        self._state_placed = False
        self._update_fn = self._build_update()
        self._key = jax.random.PRNGKey(seed + 1)
        self._jax = jax
        self._jnp = jnp

    # ------------------------------------------------------------- the math

    def _gae(self, rewards, values, dones, last_values):
        """Generalized advantage estimation as a reverse scan.
        rewards/values/dones: [T, B]; last_values: [B]."""
        import jax
        import jax.numpy as jnp

        gamma = self.cfg.get("gamma", 0.99)
        lam = self.cfg.get("lambda", 0.95)
        nonterm = 1.0 - dones

        def step(carry, xs):
            adv_next, v_next = carry
            r, v, nt = xs
            delta = r + gamma * v_next * nt - v
            adv = delta + gamma * lam * nt * adv_next
            return (adv, v), adv

        (_, _), advs = jax.lax.scan(
            step, (jnp.zeros_like(last_values), last_values),
            (rewards, values, nonterm), reverse=True)
        return advs

    def _loss(self, params, batch, key):
        import jax.numpy as jnp

        cfg = self.cfg
        pi_out, value = self.model.apply(params, batch["obs"])
        logp = self.model.log_prob(pi_out, batch["actions"])
        ratio = jnp.exp(logp - batch["logp"])
        clip = cfg.get("clip_param", 0.2)
        adv = batch["advantages"]
        surr = jnp.minimum(
            ratio * adv,
            jnp.clip(ratio, 1 - clip, 1 + clip) * adv)
        pi_loss = -surr.mean()
        vf_clip = cfg.get("vf_clip_param", 10.0)
        vf_err = jnp.clip(value - batch["returns"], -vf_clip, vf_clip)
        vf_loss = (vf_err ** 2).mean()
        ent = self.model.entropy(pi_out).mean()
        total = (pi_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                 - cfg.get("entropy_coeff", 0.0) * ent)
        return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                       "entropy": ent,
                       "kl": (batch["logp"] - logp).mean()}

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        epochs = cfg.get("num_epochs", 4)
        minibatches = cfg.get("num_minibatches", 4)

        def update(params, opt_state, rollout, key):
            # ---- GAE + flatten [T, B, ...] -> [T*B, ...]
            advs = self._gae(rollout["rewards"], rollout["values"],
                             rollout["dones"], rollout["last_values"])
            returns = advs + rollout["values"]
            flat = {
                "obs": rollout["obs"].reshape(-1, *rollout["obs"].shape[2:]),
                "actions": rollout["actions"].reshape(
                    -1, *rollout["actions"].shape[2:]),
                "logp": rollout["logp"].reshape(-1),
                "advantages": advs.reshape(-1),
                "returns": returns.reshape(-1),
            }
            n = flat["logp"].shape[0]
            adv = flat["advantages"]
            flat["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)

            def epoch_body(carry, ekey):
                params, opt_state = carry
                perm = jax.random.permutation(ekey, n)

                def mb_body(carry, idx):
                    params, opt_state = carry
                    mb = {k: v[idx] for k, v in flat.items()}
                    (_, aux), grads = jax.value_and_grad(
                        self._loss, has_aux=True)(params, mb, ekey)
                    updates, opt_state = self.opt.update(grads, opt_state,
                                                         params)
                    params = jax.tree_util.tree_map(
                        lambda p, u: p + u, params, updates)
                    return (params, opt_state), aux

                mb_size = n // minibatches
                idxs = perm[:mb_size * minibatches].reshape(minibatches,
                                                            mb_size)
                (params, opt_state), aux = jax.lax.scan(
                    mb_body, (params, opt_state), idxs)
                return (params, opt_state), aux

            ekeys = jax.random.split(key, epochs)
            (params, opt_state), aux = jax.lax.scan(
                epoch_body, (params, opt_state), ekeys)
            metrics = {k: v[-1, -1] for k, v in aux.items()}
            return params, opt_state, metrics

        return self._compile(update)

    # --------------------------------------------------- mesh + multihost

    def _compile(self, update):
        """Jit the update.  On a mesh: params/opt replicated out; batch
        shardings come from the committed input arrays (``_place``), which
        is what lets the SAME compiled program serve both the local
        multi-device mesh and a jax.distributed mesh spanning learner-actor
        processes (reference learner_group.py:61's NCCL allreduce becomes
        XLA's gradient psum over dp)."""
        import jax

        if self.mesh is None:
            return jax.jit(update)
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        return jax.jit(update, out_shardings=(repl, repl, repl))

    def _batch_spec(self, name: str, ndim: int):
        """Batch axis: axis 1 of [T, B, ...] arrays, axis 0 of 1-D
        last_values."""
        from jax.sharding import PartitionSpec as P

        if ndim <= 1:
            return P("dp")
        return P(None, "dp")

    def _place_batch(self, rollout):
        import jax
        from jax.sharding import NamedSharding

        out = {}
        for k, v in rollout.items():
            v = np.asarray(v)
            sh = NamedSharding(self.mesh, self._batch_spec(k, v.ndim))
            if self._multiprocess:
                # v is THIS process's slice of the batch; assemble the
                # global array (dp is process-major, so each process owns a
                # contiguous block of the batch axis).
                out[k] = jax.make_array_from_process_local_data(sh, v)
            else:
                out[k] = jax.device_put(v, sh)
        return out

    def _place_repl(self, tree):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        repl = NamedSharding(self.mesh, P())
        if self._multiprocess:
            return jax.tree_util.tree_map(
                lambda x: jax.make_array_from_process_local_data(
                    repl, np.asarray(x)), tree)
        return jax.device_put(tree, repl)

    # -------------------------------------------------------------- public

    def update(self, rollout: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax.numpy as jnp

        self._key, sub = self._jax.random.split(self._key)
        if self.mesh is not None:
            rollout = self._place_batch(rollout)
            if not self._state_placed:
                self.params = self._place_repl(self.params)
                self.opt_state = self._place_repl(self.opt_state)
                self._state_placed = True
            sub = self._place_repl(sub)
        else:
            rollout = {k: jnp.asarray(v) for k, v in rollout.items()}
        self.params, self.opt_state, metrics = self._update_fn(
            self.params, self.opt_state, rollout, sub)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self) -> Dict[str, np.ndarray]:
        return {k: np.asarray(v) for k, v in self.params.items()}


class LearnerGroup:
    """Data-parallel learner over a device mesh.

    The reference fans out to learner *workers* (one per GPU) and allreduces
    with NCCL; here one process drives all local devices through a mesh and
    the allreduce is compiled (ICI on TPU, shared memory on the CPU test
    mesh).  Multi-host scale-out = the same program under
    ``jax.distributed`` (train/backend.py), not a different code path."""

    def __init__(self, model, config: Dict[str, Any],
                 num_learners: int = 1, seed: int = 0):
        import jax

        self.mesh = None
        if num_learners > 1:
            devs = jax.devices()[:num_learners]
            self.mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
        self.learner = Learner(model, config, mesh=self.mesh, seed=seed)

    def update(self, rollout: Dict[str, np.ndarray]) -> Dict[str, float]:
        return self.learner.update(rollout)

    def get_weights(self) -> Dict[str, np.ndarray]:
        return self.learner.get_weights()
