"""IMPALA / APPO: the asynchronous off-policy actor-learner architecture.

Reference: ``rllib/algorithms/impala/impala.py:68`` (decoupled sampling and
learning) and ``:552`` (the async request loop), v-trace from Espeholt et al.
2018 (PAPERS.md).  This is the pattern Ray actors are uniquely good at — and
the round-3 gap VERDICT item 6 named: every algorithm was synchronous
collect->update.

Architecture (TPU-first split):
* EnvRunner actors sample CONTINUOUSLY: the driver keeps one in-flight
  ``sample()`` per runner and re-submits the moment a fragment lands, so
  sampling overlaps the learner's compiled update instead of barriering on
  it (PPO's gather-all).  Weights ship by object-store broadcast every
  ``broadcast_interval`` updates; fragments therefore arrive 1-2 policy
  versions stale.
* The learner corrects that staleness with V-TRACE importance sampling
  (clipped rho/c), computed inside ONE jitted update — reverse ``lax.scan``
  for the vs targets, policy gradient on the corrected advantage, value MSE
  to vs, entropy bonus.
* APPO = same loop with the PPO-style clipped surrogate against the behavior
  policy instead of the plain rho-weighted PG (``use_appo_clip``).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .learner import Learner


class IMPALAConfig:
    """Builder, same surface shape as PPOConfig."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: dict = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 1
        self.rollout_len = 64
        self.num_learners = 0
        self.num_devices_per_learner = 1
        self.seed = 0
        self.model: Dict[str, Any] = {"hidden": (64, 64)}
        self.train: Dict[str, Any] = {
            "lr": 5e-4, "gamma": 0.99, "grad_clip": 40.0,
            "vf_loss_coeff": 0.5, "entropy_coeff": 0.01,
            "vtrace_rho_clip": 1.0, "vtrace_c_clip": 1.0,
            "use_appo_clip": False, "clip_param": 0.3,
        }
        self.updates_per_iter = 8
        self.broadcast_interval = 1

    def environment(self, env: str, *, env_config: Optional[dict] = None):
        self.env_name = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 1,
                    rollout_fragment_length: int = 64):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_len = rollout_fragment_length
        return self

    def learners(self, num_learners: int = 0,
                 num_devices_per_learner: int = 1):
        """0 = driver-local learner; N >= 1 = N learner actors on one
        jax.distributed mesh (learner_group.py) — the decoupled
        actor/learner split the IMPALA paper describes."""
        self.num_learners = num_learners
        self.num_devices_per_learner = num_devices_per_learner
        return self

    def training(self, **kwargs):
        if "model" in kwargs:
            self.model.update(kwargs.pop("model"))
        if "updates_per_iter" in kwargs:
            self.updates_per_iter = kwargs.pop("updates_per_iter")
        if "broadcast_interval" in kwargs:
            self.broadcast_interval = kwargs.pop("broadcast_interval")
        self.train.update(kwargs)
        return self

    def debugging(self, seed: int = 0, worker_env: Optional[dict] = None):
        self.seed = seed
        return self

    def build(self) -> "IMPALA":
        return IMPALA(self)


class APPOConfig(IMPALAConfig):
    """APPO: IMPALA's async loop with the clipped PPO surrogate."""

    def __init__(self):
        super().__init__()
        self.train["use_appo_clip"] = True

    def build(self) -> "IMPALA":
        return IMPALA(self)


class ImpalaLearner(Learner):
    """V-trace actor-critic update: ONE pass per fragment, no epoch loop."""

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        gamma = cfg.get("gamma", 0.99)
        rho_clip = cfg.get("vtrace_rho_clip", 1.0)
        c_clip = cfg.get("vtrace_c_clip", 1.0)
        appo = bool(cfg.get("use_appo_clip", False))
        clip = cfg.get("clip_param", 0.3)

        def loss_fn(params, rollout):
            obs = rollout["obs"]                       # [T, B, ...]
            T, B = obs.shape[0], obs.shape[1]
            flat_obs = obs.reshape((T * B,) + obs.shape[2:])
            pi_out, values = self.model.apply(params, flat_obs)
            acts = rollout["actions"].reshape(
                (T * B,) + rollout["actions"].shape[2:])
            tgt_logp = self.model.log_prob(pi_out, acts).reshape(T, B)
            ent = self.model.entropy(pi_out).mean()
            values = values.reshape(T, B)

            behavior_logp = rollout["logp"]            # [T, B]
            log_rho = tgt_logp - behavior_logp
            rho = jnp.exp(log_rho)
            rho_cl = jnp.minimum(rho, rho_clip)
            c_cl = jnp.minimum(rho, c_clip)
            nt = 1.0 - rollout["dones"]                # [T, B]
            rew = rollout["rewards"]

            v = jax.lax.stop_gradient(values)
            v_next = jnp.concatenate([v[1:], rollout["last_values"][None]], 0)
            delta = rho_cl * (rew + gamma * nt * v_next - v)

            def vs_step(carry, xs):
                # vs_{t} - V_t = delta_t + gamma*nt*c_t*(vs_{t+1} - V_{t+1})
                acc = carry
                d, c, n = xs
                acc = d + gamma * n * c * acc
                return acc, acc

            _, vs_minus_v = jax.lax.scan(
                vs_step, jnp.zeros_like(rollout["last_values"]),
                (delta, c_cl, nt), reverse=True)
            vs = vs_minus_v + v                         # [T, B]
            vs_next = jnp.concatenate(
                [vs[1:], rollout["last_values"][None]], 0)
            pg_adv = jax.lax.stop_gradient(
                rho_cl * (rew + gamma * nt * vs_next - v))

            if appo:
                ratio = jnp.exp(tgt_logp - behavior_logp)
                surr = jnp.minimum(
                    ratio * pg_adv,
                    jnp.clip(ratio, 1 - clip, 1 + clip) * pg_adv)
                pi_loss = -surr.mean()
            else:
                pi_loss = -(tgt_logp * pg_adv).mean()
            vf_loss = ((values - jax.lax.stop_gradient(vs)) ** 2).mean()
            total = (pi_loss + cfg.get("vf_loss_coeff", 0.5) * vf_loss
                     - cfg.get("entropy_coeff", 0.0) * ent)
            return total, {"policy_loss": pi_loss, "vf_loss": vf_loss,
                           "entropy": ent, "mean_rho": rho.mean()}

        def update(params, opt_state, rollout, key):
            import jax as _jax
            (_, aux), grads = _jax.value_and_grad(loss_fn, has_aux=True)(
                params, rollout)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = _jax.tree_util.tree_map(lambda p, u: p + u,
                                             params, updates)
            return params, opt_state, aux

        return self._compile(update)


class IMPALA:
    """Async driver: one in-flight sample per runner, resubmit-on-arrival."""

    def __init__(self, config: IMPALAConfig):
        import gymnasium as gym

        import ray_tpu

        from .env_runner import EnvRunner as _ER
        from .models import build_model

        self.config = config
        probe = gym.make(config.env_name, **config.env_config)
        obs_shape = probe.observation_space.shape
        continuous = not hasattr(probe.action_space, "n")
        action_dim = (probe.action_space.shape[0] if continuous
                      else int(probe.action_space.n))
        probe.close()
        self.model_spec = dict(obs_dim=int(np.prod(obs_shape)),
                               action_dim=action_dim,
                               hidden=tuple(config.model["hidden"]),
                               continuous=continuous)
        if config.num_learners >= 1:
            from .learner_group import DistributedLearnerGroup

            self.learner = DistributedLearnerGroup(
                self.model_spec, config.train,
                num_learners=config.num_learners, seed=config.seed,
                learner_cls=ImpalaLearner,
                devices_per_learner=config.num_devices_per_learner)
        else:
            model = build_model(self.model_spec)
            mesh = None
            if config.num_devices_per_learner > 1:
                import jax
                devs = jax.devices()[:config.num_devices_per_learner]
                mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
            self.learner = ImpalaLearner(model, config.train, mesh=mesh,
                                         seed=config.seed)
        runner_cls = ray_tpu.remote(_ER)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, self.model_spec,
                num_envs=config.num_envs_per_runner,
                seed=config.seed + 1000 * i,
                env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self._iteration = 0
        self._recent_returns: List[float] = []
        self.policy_version = 0
        self._weights_ref = None
        self._weights_version = -1
        #: ref -> (runner, version the fragment was sampled under)
        self._in_flight: Dict[Any, tuple] = {}
        #: diagnostic: version lag of consumed fragments (proof of async)
        self.version_lags: List[int] = []

    def _fresh_weights_ref(self):
        import ray_tpu
        if (self._weights_ref is None
                or self.policy_version - self._weights_version
                >= self.config.broadcast_interval):
            self._weights_ref = ray_tpu.put(self.learner.get_weights())
            self._weights_version = self.policy_version
        return self._weights_ref

    def _submit(self, runner):
        ref = runner.sample.remote(self._fresh_weights_ref(),
                                   self.config.rollout_len)
        self._in_flight[ref] = (runner, self._weights_version)

    def train(self) -> Dict[str, Any]:
        """One iteration = updates_per_iter learner steps, each consuming the
        first fragment to land; its runner is resubmitted IMMEDIATELY, so
        sampling continues while the learner's jitted update runs."""
        import ray_tpu

        t0 = time.time()
        for r in self.runners:
            if not any(rn is r for rn, _ in self._in_flight.values()):
                self._submit(r)
        metrics: Dict[str, float] = {}
        for _ in range(self.config.updates_per_iter):
            ready, _ = ray_tpu.wait(list(self._in_flight), num_returns=1,
                                    timeout=600)
            runner, version = self._in_flight.pop(ready[0])
            batch = ray_tpu.get(ready[0])
            # resubmit BEFORE updating: the runner samples the next fragment
            # while the learner computes — the decoupling IMPALA is about.
            self._submit(runner)
            self.version_lags.append(self.policy_version - version)
            if len(self.version_lags) > 64:
                del self.version_lags[:-64]
            metrics = self.learner.update(batch)
            self.policy_version += 1
        rets = [x for r in self.runners
                for x in ray_tpu.get(r.episode_returns.remote(), timeout=60)]
        self._recent_returns.extend(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        steps = (self.config.rollout_len * self.config.num_envs_per_runner
                 * self.config.updates_per_iter)
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "episodes_this_iter": len(rets),
            "num_env_steps_sampled": steps * self._iteration,
            "mean_version_lag": float(np.mean(self.version_lags[-64:])),
            "time_this_iter_s": time.time() - t0,
            **metrics,
        }

    def stop(self):
        import ray_tpu
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        if hasattr(self.learner, "shutdown"):
            self.learner.shutdown()

    def get_weights(self):
        return self.learner.get_weights()


APPO = IMPALA  # the class is shared; APPOConfig flips the surrogate
