"""Convolutional actor-critic for pixel observations (Atari-class).

Reference: ``rllib/models/torch/visionnet.py`` (VisionNetwork — the Nature
CNN filter stack) — rebuilt as a functional jax module: big NHWC convs in
bfloat16-friendly shapes so the whole rollout/update path stays compiled
(lax.conv on the MXU; no dynamic shapes).
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from .models import ActorCriticMLP, Params

# The Nature-CNN stack (Mnih et al. 2015): (out_channels, kernel, stride)
NATURE_FILTERS = ((32, 8, 4), (64, 4, 2), (64, 3, 1))


class ActorCriticConv(ActorCriticMLP):
    """Shared conv torso + separate pi/value dense heads.

    ``obs_shape`` is HWC (e.g. (84, 84, 4) stacked Atari frames); uint8
    inputs are scaled to [0, 1] inside apply, so env runners ship raw
    frames (4x smaller than float32 over the object store)."""

    def __init__(self, obs_shape: Sequence[int], action_dim: int,
                 filters=NATURE_FILTERS, hidden: int = 512,
                 continuous: bool = False):
        self.obs_shape = tuple(obs_shape)
        self.filters = tuple(filters)
        self.hidden_size = hidden
        # dense-head bookkeeping reuses the MLP distributions; obs_dim is
        # unused for convs but kept for spec round-tripping
        super().__init__(obs_dim=int(jnp.prod(jnp.array(self.obs_shape))),
                         action_dim=action_dim, hidden=(hidden,),
                         continuous=continuous)

    # ----------------------------------------------------------- params

    def _conv_out_hw(self) -> Tuple[int, int]:
        h, w = self.obs_shape[0], self.obs_shape[1]
        for _c, k, s in self.filters:
            h = (h - k) // s + 1
            w = (w - k) // s + 1
        return h, w

    def init(self, key: jax.Array) -> Params:
        params: Params = {}
        keys = jax.random.split(key, len(self.filters) + 6)
        ki = iter(keys)
        in_c = self.obs_shape[-1]
        for i, (out_c, k, _s) in enumerate(self.filters):
            fan_in = k * k * in_c
            params[f"conv_w{i}"] = jax.random.normal(
                next(ki), (k, k, in_c, out_c)) * (2.0 / fan_in) ** 0.5
            params[f"conv_b{i}"] = jnp.zeros((out_c,))
            in_c = out_c
        h, w = self._conv_out_hw()
        flat = h * w * in_c
        params["torso_w"] = jax.random.normal(
            next(ki), (flat, self.hidden_size)) * (2.0 / flat) ** 0.5
        params["torso_b"] = jnp.zeros((self.hidden_size,))
        out_dim = self.action_dim * (2 if self.continuous else 1)
        params["pi_out_w"] = jax.random.normal(
            next(ki), (self.hidden_size, out_dim)) * 0.01
        params["pi_out_b"] = jnp.zeros((out_dim,))
        params["vf_out_w"] = jax.random.normal(
            next(ki), (self.hidden_size, 1)) / self.hidden_size ** 0.5
        params["vf_out_b"] = jnp.zeros((1,))
        return params

    # ------------------------------------------------------------ apply

    def _torso(self, params: Params, obs) -> jnp.ndarray:
        x = obs.astype(jnp.float32)
        if obs.dtype == jnp.uint8:
            x = x / 255.0
        if x.ndim == len(self.obs_shape):  # unbatched
            x = x[None]
        for i, (_c, _k, s) in enumerate(self.filters):
            x = jax.lax.conv_general_dilated(
                x, params[f"conv_w{i}"], window_strides=(s, s),
                padding="VALID", dimension_numbers=("NHWC", "HWIO", "NHWC"))
            x = jax.nn.relu(x + params[f"conv_b{i}"])
        x = x.reshape(x.shape[0], -1)
        return jax.nn.relu(x @ params["torso_w"] + params["torso_b"])

    def apply(self, params: Params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """obs [B, H, W, C] (uint8 or float) -> (pi_out, value [B])."""
        z = self._torso(params, obs)
        pi = z @ params["pi_out_w"] + params["pi_out_b"]
        v = (z @ params["vf_out_w"] + params["vf_out_b"])[..., 0]
        return pi, v
