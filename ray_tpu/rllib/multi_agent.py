"""Multi-agent environments + multi-policy training.

Reference: ``rllib/env/multi_agent_env.py`` (the dict-keyed step/reset API
with the ``"__all__"`` termination sentinel) and the policy-mapping design of
``rllib/policy/policy_map.py``.

Scope: the dict env contract, a per-POLICY rollout collector (agents are
mapped to policies by ``policy_mapping_fn``; each policy's transitions batch
together), and a PPO-style trainer owning one Learner per policy.  Agents
sharing a policy contribute to one batch — the common self-play setup."""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np


class MultiAgentEnv:
    """Dict-keyed multi-agent environment contract.

    ``reset()`` -> (obs_dict, info_dict); ``step(action_dict)`` ->
    (obs_dict, reward_dict, terminated_dict, truncated_dict, info_dict).
    ``terminated["__all__"]`` ends the episode for everyone.  Only agents
    present in the returned obs dict act next step."""

    possible_agents: List[str] = []

    def reset(self, *, seed: Optional[int] = None
              ) -> Tuple[Dict[str, Any], Dict[str, Any]]:
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    @property
    def observation_size(self) -> int:
        raise NotImplementedError

    @property
    def num_actions(self) -> int:
        raise NotImplementedError


class RockPaperScissors(MultiAgentEnv):
    """Two-agent repeated RPS (the reference's canonical multi-agent
    example): observation is the one-hot of the opponent's previous move,
    reward +1/-1/0.  A learning policy should beat the biased scripted
    opponent baseline in the test."""

    possible_agents = ["player_0", "player_1"]

    def __init__(self, episode_len: int = 10):
        self.episode_len = episode_len
        self._t = 0

    @property
    def observation_size(self) -> int:
        return 4  # one-hot prev opponent move + "start" slot

    @property
    def num_actions(self) -> int:
        return 3

    def reset(self, *, seed: Optional[int] = None):
        self._t = 0
        start = np.array([0, 0, 0, 1], np.float32)
        return ({a: start.copy() for a in self.possible_agents}, {})

    def step(self, action_dict):
        a0 = int(action_dict["player_0"])
        a1 = int(action_dict["player_1"])
        self._t += 1
        # 0=rock, 1=paper, 2=scissors; (a - b) % 3 == 1 -> a wins
        if a0 == a1:
            r0 = r1 = 0.0
        elif (a0 - a1) % 3 == 1:
            r0, r1 = 1.0, -1.0
        else:
            r0, r1 = -1.0, 1.0
        obs = {
            "player_0": np.eye(4, dtype=np.float32)[a1],
            "player_1": np.eye(4, dtype=np.float32)[a0],
        }
        done = self._t >= self.episode_len
        term = {"player_0": done, "player_1": done, "__all__": done}
        trunc = {"player_0": False, "player_1": False, "__all__": False}
        return obs, {"player_0": r0, "player_1": r1}, term, trunc, {}


class MultiAgentEnvRunner:
    """Rollout collector: steps ONE multi-agent env, batching each agent's
    transitions under its mapped policy (reference:
    ``rllib/env/multi_agent_env_runner.py``)."""

    def __init__(self, env_ctor, model_specs: Dict[str, Dict[str, Any]],
                 policy_mapping: Dict[str, str], seed: int = 0):
        from .models import build_model
        import jax

        self.env: MultiAgentEnv = env_ctor()
        self.mapping = dict(policy_mapping)
        self.models = {pid: build_model(spec)
                       for pid, spec in model_specs.items()}
        self._applies = {pid: jax.jit(m.apply)
                         for pid, m in self.models.items()}
        self._seed = seed
        self._calls = 0
        self.obs, _ = self.env.reset(seed=seed)
        self._ep_return: Dict[str, float] = {}
        self._done_returns: Dict[str, List[float]] = {
            pid: [] for pid in self.models}

    def sample(self, weights: Dict[str, Dict[str, Any]],
               rollout_len: int = 64) -> Dict[str, Dict[str, np.ndarray]]:
        """Collect ``rollout_len`` env steps; returns per-policy batches in
        the same [T, B, ...] layout the single-agent Learner consumes (B =
        number of agents mapped to that policy and alive that step)."""
        import jax
        import jax.numpy as jnp

        params = {pid: jax.tree_util.tree_map(jnp.asarray, w)
                  for pid, w in weights.items()}
        self._calls += 1
        key = jax.random.PRNGKey((self._seed << 20) ^ self._calls)
        # per-policy time-major buffers (lists; agents per policy is stable
        # for the packaged envs)
        buf: Dict[str, Dict[str, list]] = {
            pid: {k: [] for k in ("obs", "actions", "logp", "values",
                                  "rewards", "dones")}
            for pid in self.models}

        for _ in range(rollout_len):
            acts: Dict[str, Any] = {}
            step_rec: Dict[str, Dict[str, list]] = {
                pid: {k: [] for k in ("obs", "actions", "logp", "values")}
                for pid in self.models}
            for aid, ob in self.obs.items():
                pid = self.mapping[aid]
                pi_out, value = self._applies[pid](
                    params[pid], jnp.asarray(ob, jnp.float32)[None])
                key, sub = jax.random.split(key)
                action = self.models[pid].sample_action(pi_out, sub)
                logp = self.models[pid].log_prob(pi_out, action)
                acts[aid] = int(np.asarray(action)[0])
                step_rec[pid]["obs"].append(np.asarray(ob, np.float32))
                step_rec[pid]["actions"].append(float(np.asarray(action)[0]))
                step_rec[pid]["logp"].append(float(np.asarray(logp)[0]))
                step_rec[pid]["values"].append(float(np.asarray(value)[0]))
            nobs, rews, terms, truncs, _ = self.env.step(acts)
            done_all = terms.get("__all__", False) or truncs.get("__all__",
                                                                 False)
            for aid in acts:
                pid = self.mapping[aid]
                self._ep_return[aid] = self._ep_return.get(aid, 0.0) \
                    + rews.get(aid, 0.0)
            for pid in self.models:
                aids = [a for a in acts if self.mapping[a] == pid]
                if not aids:
                    continue
                buf[pid]["obs"].append(np.stack(step_rec[pid]["obs"]))
                buf[pid]["actions"].append(
                    np.array(step_rec[pid]["actions"], np.float32))
                buf[pid]["logp"].append(
                    np.array(step_rec[pid]["logp"], np.float32))
                buf[pid]["values"].append(
                    np.array(step_rec[pid]["values"], np.float32))
                buf[pid]["rewards"].append(np.array(
                    [rews.get(a, 0.0) for a in aids], np.float32))
                buf[pid]["dones"].append(np.array(
                    [float(done_all or terms.get(a, False)) for a in aids],
                    np.float32))
            if done_all:
                for aid, ret in self._ep_return.items():
                    self._done_returns[self.mapping[aid]].append(ret)
                self._ep_return.clear()
                nobs, _ = self.env.reset()
            self.obs = nobs

        out: Dict[str, Dict[str, np.ndarray]] = {}
        for pid, b in buf.items():
            batch = {k: np.stack(v) for k, v in b.items()}   # [T, B, ...]
            # bootstrap values for GAE
            last = []
            for aid, ob in self.obs.items():
                if self.mapping[aid] == pid:
                    _, v = self._applies[pid](
                        params[pid], jnp.asarray(ob, jnp.float32)[None])
                    last.append(float(np.asarray(v)[0]))
            batch["last_values"] = np.array(last, np.float32)
            out[pid] = batch
        return out

    def episode_returns(self, clear: bool = True) -> Dict[str, List[float]]:
        out = {pid: list(v) for pid, v in self._done_returns.items()}
        if clear:
            for v in self._done_returns.values():
                v.clear()
        return out

    def ping(self) -> bool:
        return True


class MultiAgentPPO:
    """One Learner per policy over shared rollout actors (reference:
    multi-policy training in ``Algorithm`` with a PolicyMap)."""

    def __init__(self, env_ctor: Callable[[], MultiAgentEnv],
                 policy_mapping_fn: Callable[[str], str],
                 num_runners: int = 2, rollout_len: int = 64,
                 train_config: Optional[Dict[str, Any]] = None,
                 hidden: Tuple[int, ...] = (32, 32), seed: int = 0):
        import ray_tpu
        from .learner import Learner
        from .models import build_model

        probe = env_ctor()
        self.policy_ids = sorted({policy_mapping_fn(a)
                                  for a in probe.possible_agents})
        mapping = {a: policy_mapping_fn(a) for a in probe.possible_agents}
        spec = dict(obs_dim=probe.observation_size,
                    action_dim=probe.num_actions,
                    hidden=tuple(hidden), continuous=False)
        self.model_specs = {pid: dict(spec) for pid in self.policy_ids}
        cfg = dict({"lr": 5e-4, "num_epochs": 2, "num_minibatches": 2,
                    "entropy_coeff": 0.01}, **(train_config or {}))
        self.learners = {
            pid: Learner(build_model(self.model_specs[pid]), cfg,
                         seed=seed + i)
            for i, pid in enumerate(self.policy_ids)}
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                env_ctor, self.model_specs, mapping, seed=seed + 7 * i)
            for i in range(num_runners)]
        self.rollout_len = rollout_len
        self._iteration = 0
        self._recent: Dict[str, List[float]] = {p: [] for p in self.policy_ids}

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        t0 = time.time()
        weights = {pid: ln.get_weights()
                   for pid, ln in self.learners.items()}
        wref = ray_tpu.put(weights)
        samples = ray_tpu.get(
            [r.sample.remote(wref, self.rollout_len) for r in self.runners],
            timeout=600)
        metrics: Dict[str, Any] = {}
        for pid, learner in self.learners.items():
            per = [s[pid] for s in samples if pid in s]
            if not per:
                continue
            rollout = {
                k: np.concatenate([b[k] for b in per],
                                  axis=0 if k == "last_values" else 1)
                for k in per[0]}
            m = learner.update(rollout)
            metrics.update({f"{pid}/{k}": v for k, v in m.items()})
        for r in self.runners:
            rets = ray_tpu.get(r.episode_returns.remote(), timeout=60)
            for pid, vals in rets.items():
                self._recent[pid].extend(vals)
                self._recent[pid] = self._recent[pid][-100:]
        self._iteration += 1
        for pid in self.policy_ids:
            if self._recent[pid]:
                metrics[f"{pid}/episode_return_mean"] = float(
                    np.mean(self._recent[pid]))
        metrics["training_iteration"] = self._iteration
        metrics["time_this_iter_s"] = time.time() - t0
        return metrics

    def get_weights(self):
        return {pid: ln.get_weights() for pid, ln in self.learners.items()}

    def stop(self):
        import ray_tpu
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
