"""EnvRunner: the rollout actor.

Reference: ``rllib/env/env_runner.py:9`` (EnvRunner ABC) and
``rllib/evaluation/rollout_worker.py:159`` — an actor that owns gymnasium
envs, receives policy weights, and returns fixed-length sample batches.
Stepping is Python/CPU; policy inference is jax on the worker (CPU devices —
the big compiled update runs in the learner, not here)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class EnvRunner:
    """Collects rollout fragments from N vectorized gymnasium envs."""

    def __init__(self, env_name: str, model_spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0,
                 env_config: Optional[dict] = None):
        import gymnasium as gym

        from .models import build_model

        self.envs = [gym.make(env_name, **(env_config or {}))
                     for _ in range(num_envs)]
        self.model = build_model(model_spec)
        # compiled once: a fresh jit(self.model.apply) per sample() would
        # retrace the policy on every rollout (bound methods never hit the
        # jit cache)
        import jax
        self._apply = jax.jit(self.model.apply)
        self.num_envs = num_envs
        self._seed = seed
        self._rng_calls = 0
        self.obs = np.stack([e.reset(seed=seed + i)[0]
                             for i, e in enumerate(self.envs)])
        self._ep_returns = np.zeros(num_envs)
        self._done_returns: List[float] = []

    def sample(self, params_blob: Dict[str, Any],
               rollout_len: int = 128) -> Dict[str, np.ndarray]:
        """Run `rollout_len` steps per env under the given weights; returns
        the batch plus bootstrap values (learner computes GAE in-jit)."""
        import jax
        import jax.numpy as jnp

        params = jax.tree_util.tree_map(jnp.asarray, params_blob)
        apply = self._apply
        self._rng_calls += 1
        key = jax.random.PRNGKey(
            (self._seed << 20) ^ self._rng_calls)

        T, N = rollout_len, self.num_envs
        obs_buf = np.zeros((T, N) + self.obs.shape[1:], np.float32)
        act_shape = ((N,) if not self.model.continuous
                     else (N, self.model.action_dim))
        acts_buf = np.zeros((T,) + act_shape, np.float32)
        logp_buf = np.zeros((T, N), np.float32)
        val_buf = np.zeros((T, N), np.float32)
        rew_buf = np.zeros((T, N), np.float32)
        done_buf = np.zeros((T, N), np.float32)

        for t in range(T):
            pi_out, value = apply(params, jnp.asarray(self.obs, jnp.float32))
            key, sub = jax.random.split(key)
            action = self.model.sample_action(pi_out, sub)
            logp = self.model.log_prob(pi_out, action)
            action_np = np.asarray(action)
            obs_buf[t] = self.obs
            acts_buf[t] = action_np
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            for i, env in enumerate(self.envs):
                a = action_np[i]
                if not self.model.continuous:
                    a = int(a)
                nobs, rew, term, trunc, _ = env.step(a)
                rew_buf[t, i] = rew
                self._ep_returns[i] += rew
                if term or trunc:
                    done_buf[t, i] = 1.0
                    self._done_returns.append(self._ep_returns[i])
                    self._ep_returns[i] = 0.0
                    nobs, _ = env.reset()
                self.obs[i] = nobs
        _, last_val = apply(params, jnp.asarray(self.obs, jnp.float32))
        return {
            "obs": obs_buf, "actions": acts_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "last_values": np.asarray(last_val, np.float32),
        }

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._done_returns)
        if clear:
            self._done_returns.clear()
        return out

    def ping(self) -> bool:
        return True
