"""Rainbow-style distributional DQN: C51 categorical head + dueling.

Reference: ``rllib/algorithms/dqn/dqn.py`` — the reference folds the
Rainbow components into DQNConfig as knobs (``num_atoms`` > 1 enables
the C51 distributional head, ``dueling`` the value/advantage split,
``n_step`` the multi-step target; noisy-nets is the piece deliberately
not carried — the per-actor epsilon ladder of apex.py covers the same
exploration role in this stack).

TPU-first shape: the C51 projection — the categorical analogue of the
TD backup — is fully vectorized inside the jitted update: the projected
target distribution is two one-hot matmuls (floor/ceil neighbors)
instead of the reference's scatter loop, which is exactly the form the
MXU batches well. n-step/terminal handling rides the same per-sample
``discounts`` field the runner already emits (gamma^k, zero at
termination), so ``Tz = r + discounts * z`` covers every case.
"""

from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .dqn import DQN, DQNConfig

__all__ = ["Rainbow", "RainbowConfig", "DistQNetwork"]


class DistQNetwork:
    """MLP torso -> (dueling) categorical head over a fixed support.

    ``apply`` returns expected Q [B, A] (so epsilon-greedy rollout code
    is head-agnostic); ``log_probs`` exposes the full distribution
    [B, A, atoms] for the learner's cross-entropy."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(64, 64),
                 num_atoms: int = 51, v_min: float = -10.0,
                 v_max: float = 10.0, dueling: bool = True):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)
        self.num_atoms = int(num_atoms)
        self.v_min = float(v_min)
        self.v_max = float(v_max)
        self.dueling = bool(dueling)

    @property
    def support(self):
        import jax.numpy as jnp
        return jnp.linspace(self.v_min, self.v_max, self.num_atoms)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        sizes = (self.obs_dim,) + self.hidden
        params: Dict[str, Any] = {}
        n_heads = 2 if self.dueling else 1
        keys = jax.random.split(key, len(sizes) + n_heads)
        for i in range(len(sizes) - 1):
            scale = (2.0 / sizes[i]) ** 0.5
            params[f"w{i}"] = jax.random.normal(
                keys[i], (sizes[i], sizes[i + 1])) * scale
            params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
        h = sizes[-1]
        params["adv_w"] = jax.random.normal(
            keys[-1], (h, self.action_dim * self.num_atoms)) * 0.01
        params["adv_b"] = jnp.zeros((self.action_dim * self.num_atoms,))
        if self.dueling:
            params["val_w"] = jax.random.normal(
                keys[-2], (h, self.num_atoms)) * 0.01
            params["val_b"] = jnp.zeros((self.num_atoms,))
        return params

    def _logits(self, params, obs):
        import jax.numpy as jnp

        x = obs
        for i in range(len(self.hidden)):
            x = jnp.maximum(x @ params[f"w{i}"] + params[f"b{i}"], 0.0)
        adv = (x @ params["adv_w"] + params["adv_b"]).reshape(
            x.shape[0], self.action_dim, self.num_atoms)
        if self.dueling:
            val = (x @ params["val_w"] + params["val_b"])[:, None, :]
            # dueling in distribution space: center the advantage stream
            return val + adv - adv.mean(axis=1, keepdims=True)
        return adv

    def log_probs(self, params, obs):
        import jax
        return jax.nn.log_softmax(self._logits(params, obs), axis=-1)

    def probs(self, params, obs):
        import jax
        return jax.nn.softmax(self._logits(params, obs), axis=-1)

    def apply(self, params, obs):
        """Expected Q values [B, A] under the categorical distribution."""
        return (self.probs(params, obs) * self.support).sum(axis=-1)


class RainbowConfig(DQNConfig):
    """DQNConfig pinned to the distributional regime (reference DQN
    defaults for Rainbow runs: 51 atoms, dueling, n-step 3, PER)."""

    def __init__(self):
        super().__init__()
        self.model.update(num_atoms=51, v_min=-10.0, v_max=10.0,
                          dueling=True)
        self.train.update(n_step=3)
        self.replay.update(prioritized=True)

    def build(self) -> "Rainbow":
        if not self.env_name:
            raise ValueError("call .environment(env_name) first")
        return Rainbow(self)


class Rainbow(DQN):
    """DQN driver with the C51 cross-entropy update swapped in."""

    def _build_update(self):
        import jax
        import jax.numpy as jnp
        import optax

        cfg = self.config.train
        tau = cfg["target_update_tau"]
        double_q = cfg["double_q"]
        model = self.model
        atoms = model.num_atoms
        z = model.support                              # [atoms]
        dz = (model.v_max - model.v_min) / (atoms - 1)

        def loss_fn(params, target_params, batch):
            logp = model.log_probs(params, batch["obs"])     # [B, A, M]
            a = batch["actions"].astype(jnp.int32)
            logp_a = jnp.take_along_axis(
                logp, a[:, None, None].repeat(atoms, -1), 1)[:, 0]  # [B, M]

            # next-action selection on expected Q
            if double_q:
                next_q = model.apply(params, batch["next_obs"])
            else:
                next_q = model.apply(target_params, batch["next_obs"])
            next_a = next_q.argmax(axis=-1)
            p_next = jnp.take_along_axis(
                model.probs(target_params, batch["next_obs"]),
                next_a[:, None, None].repeat(atoms, -1), 1)[:, 0]  # [B, M]

            # categorical projection of Tz = r + gamma^k * z onto the
            # support — two one-hot matmuls, no scatter
            tz = jnp.clip(batch["rewards"][:, None]
                          + batch["discounts"][:, None] * z,
                          model.v_min, model.v_max)       # [B, M]
            b = (tz - model.v_min) / dz
            low = jnp.clip(jnp.floor(b), 0, atoms - 1)
            up = jnp.clip(low + 1, 0, atoms - 1)
            w_up = b - low                                 # 0 when b integral
            w_low = 1.0 - w_up
            onehot_l = jax.nn.one_hot(low.astype(jnp.int32), atoms)
            onehot_u = jax.nn.one_hot(up.astype(jnp.int32), atoms)
            m = jnp.einsum("bm,bmn->bn", p_next * w_low, onehot_l) \
                + jnp.einsum("bm,bmn->bn", p_next * w_up, onehot_u)
            m = jax.lax.stop_gradient(m)

            ce = -(m * logp_a).sum(axis=-1)                # [B]
            w = batch.get("weights", jnp.ones_like(ce))
            return (w * ce).mean(), ce

        def update(params, target_params, opt_state, batch):
            (loss, ce), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            target_params = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, target_params, params)
            # the per-sample cross-entropy doubles as the PER priority
            return params, target_params, opt_state, loss, ce

        return jax.jit(update)
