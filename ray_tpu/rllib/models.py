"""RL policy/value networks as plain-pytree jax modules.

Reference analogue: ``rllib/core/rl_module/rl_module.py`` (RLModule) — here a
functional (params, obs) -> outputs design so the same apply() runs on an
EnvRunner's CPU jax and inside the learner's compiled update.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Params = Dict[str, jnp.ndarray]


def build_model(spec: Dict):
    """Model factory (reference: rllib's catalog): a spec with
    ``obs_shape`` builds the conv net (pixel obs); ``obs_dim`` builds the
    MLP.  Specs are plain dicts so they ship to EnvRunner actors."""
    if "obs_shape" in spec:
        from .conv import ActorCriticConv
        return ActorCriticConv(**spec)
    return ActorCriticMLP(**spec)


class ActorCriticMLP:
    """Shared-nothing actor-critic MLP: policy logits (discrete) or
    mean/log_std (continuous) + value head."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(64, 64),
                 continuous: bool = False):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)
        self.continuous = continuous

    def init(self, key: jax.Array) -> Params:
        sizes = (self.obs_dim,) + self.hidden
        params: Params = {}
        keys = jax.random.split(key, 2 * len(self.hidden) + 4)
        ki = iter(keys)
        for tower in ("pi", "vf"):
            for i in range(len(self.hidden)):
                fan_in = sizes[i]
                params[f"{tower}_w{i}"] = jax.random.normal(
                    next(ki), (sizes[i], sizes[i + 1])) * (2.0 / fan_in) ** 0.5
                params[f"{tower}_b{i}"] = jnp.zeros((sizes[i + 1],))
        out_dim = self.action_dim * (2 if self.continuous else 1)
        params["pi_out_w"] = jax.random.normal(
            next(ki), (self.hidden[-1], out_dim)) * 0.01
        params["pi_out_b"] = jnp.zeros((out_dim,))
        params["vf_out_w"] = jax.random.normal(
            next(ki), (self.hidden[-1], 1)) * 1.0 / self.hidden[-1] ** 0.5
        params["vf_out_b"] = jnp.zeros((1,))
        return params

    def _tower(self, params: Params, obs, tower: str):
        x = obs
        for i in range(len(self.hidden)):
            x = jnp.tanh(x @ params[f"{tower}_w{i}"] + params[f"{tower}_b{i}"])
        return x

    def apply(self, params: Params, obs) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """obs [B, obs_dim] -> (pi_out [B, A or 2A], value [B])."""
        pi = (self._tower(params, obs, "pi") @ params["pi_out_w"]
              + params["pi_out_b"])
        v = (self._tower(params, obs, "vf") @ params["vf_out_w"]
             + params["vf_out_b"])[..., 0]
        return pi, v

    # ------------------------------------------------------ distributions

    def dist(self, pi_out):
        if self.continuous:
            mean, log_std = jnp.split(pi_out, 2, axis=-1)
            log_std = jnp.clip(log_std, -5.0, 2.0)
            return ("gaussian", mean, log_std)
        return ("categorical", pi_out, None)

    def sample_action(self, pi_out, key):
        kind, a, b = self.dist(pi_out)
        if kind == "gaussian":
            return a + jnp.exp(b) * jax.random.normal(key, a.shape)
        return jax.random.categorical(key, a, axis=-1)

    def log_prob(self, pi_out, action):
        kind, a, b = self.dist(pi_out)
        if kind == "gaussian":
            var = jnp.exp(2 * b)
            lp = -0.5 * (((action - a) ** 2) / var + 2 * b
                         + jnp.log(2 * jnp.pi))
            return lp.sum(-1)
        logp = jax.nn.log_softmax(a, axis=-1)
        return jnp.take_along_axis(
            logp, action[..., None].astype(jnp.int32), axis=-1)[..., 0]

    def entropy(self, pi_out):
        kind, a, b = self.dist(pi_out)
        if kind == "gaussian":
            return (b + 0.5 * jnp.log(2 * jnp.pi * jnp.e)).sum(-1)
        logp = jax.nn.log_softmax(a, axis=-1)
        return -(jnp.exp(logp) * logp).sum(-1)
