"""External-env protocol: train on simulators that live OUTSIDE the cluster.

Reference surface: ``rllib/env/policy_client.py`` (remote-inference
commands START_EPISODE / GET_ACTION / LOG_ACTION / LOG_RETURNS /
END_EPISODE) and ``rllib/env/policy_server_input.py`` (a threaded HTTP
server that doubles as the algorithm's sample-input reader).

TPU-first redesign: the reference parks a full RolloutWorker behind the
server and supports client-side ("local") inference by shipping policy
weights; here the server holds only the pure-jax apply fn + current
params — inference is one jitted call on the driver's devices, and the
sample stream is assembled directly in the learner's ``[T, 1, ...]``
rollout layout (episode boundaries ride the ``dones`` channel, so the
jitted GAE scan handles concatenated episodes unchanged).  Client-side
inference falls out for free anyway: ``get_weights`` + the same model
spec rebuild the policy anywhere.

Transport is pickled dicts over HTTP POST, like the reference — this
assumes a trusted network (same assumption as ``policy_server_input.py``;
do not expose the port publicly).

Usage (server / driver side)::

    config = PPOConfig().environment("CartPole-v1").external(port=9900)
    algo = PPO(config)          # serves policy at 127.0.0.1:9900
    algo.train()                # consumes externally-collected samples

External simulator::

    client = PolicyClient("127.0.0.1:9900")
    eid = client.start_episode()
    action = client.get_action(eid, obs)
    client.log_returns(eid, reward)
    client.end_episode(eid, obs)
"""

from __future__ import annotations

import http.server
import pickle
import socketserver
import threading
import time
import uuid
from typing import Any, Dict, List, Optional

import numpy as np

__all__ = ["PolicyServerInput", "PolicyClient"]


class _Episode:
    def __init__(self, training_enabled: bool = True):
        self.training_enabled = training_enabled
        #: committed within-episode steps: (obs, action, logp, value, reward)
        self.steps: List[tuple] = []
        #: the last acted step, waiting for its reward: (obs, a, logp, v)
        self.pending: Optional[tuple] = None
        self.pending_reward = 0.0
        self.total_reward = 0.0
        self.started = time.monotonic()  # refreshed on activity (TTL sweep)


class PolicyServerInput:
    """Serve the current policy over HTTP and collect the resulting
    experience as training input (reference:
    ``policy_server_input.py:28`` — HTTPServer + InputReader in one).

    ``next(min_steps)`` blocks until that many committed steps exist and
    returns one rollout dict in the learner's ``[T, 1, ...]`` layout.
    """

    def __init__(self, model, params, address: str = "127.0.0.1",
                 port: int = 9900, gamma: float = 0.99,
                 fragment_len: int = 64, episode_ttl_s: float = 3600.0):
        import jax

        self.model = model
        self.gamma = float(gamma)
        self.fragment_len = int(fragment_len)
        self.episode_ttl_s = float(episode_ttl_s)
        self._params = params
        self._params_version = 0
        self._apply = jax.jit(model.apply)
        self._lock = threading.Lock()
        self._episodes: Dict[str, _Episode] = {}
        # committed stream: (obs, action, logp, value, reward, done) —
        # whole CONTIGUOUS per-episode fragments only, each ending done=1
        # (truncated fragments fold gamma*V(next_obs) into the last reward,
        # the standard time-limit bootstrap trick), so the jitted GAE scan
        # never bootstraps across interleaved episodes.
        self._steps: List[tuple] = []
        self._returns: List[float] = []
        self._steps_ready = threading.Condition(self._lock)
        # numpy Generators are not thread-safe; handler threads sample
        # concurrently, so sampling holds its own small lock
        self._rng = np.random.default_rng(0)
        self._rng_lock = threading.Lock()

        handler = self._make_handler()

        class _Server(socketserver.ThreadingMixIn, http.server.HTTPServer):
            daemon_threads = True

        self._server = _Server((address, port), handler)
        self.address = f"{address}:{self._server.server_address[1]}"
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="policy-server")
        self._thread.start()

    # ------------------------------------------------------------ commands

    def _cmd_start_episode(self, req):
        eid = req.get("episode_id") or uuid.uuid4().hex[:12]
        with self._lock:
            # opportunistic TTL sweep: a crashed external client never
            # end_episode's, so stale episodes would leak forever
            cutoff = time.monotonic() - self.episode_ttl_s
            for k in [k for k, e in self._episodes.items()
                      if e.started < cutoff]:
                del self._episodes[k]
            self._episodes[eid] = _Episode(req.get("training_enabled", True))
        return {"episode_id": eid}

    def _policy_step(self, obs: np.ndarray):
        """One inference: (action, logp, value) for a single observation.
        Jitted apply over a [1, ...] batch — the same compiled program the
        env runners use, so server inference rides the MXU when the driver
        holds TPU devices."""
        import jax.numpy as jnp

        pi_out, value = self._apply(self._params, jnp.asarray(
            obs[None], jnp.float32))
        if self.model.continuous:
            mean, log_std = pi_out
            mean, log_std = np.asarray(mean)[0], np.asarray(log_std)[0]
            std = np.exp(log_std)
            with self._rng_lock:
                noise = self._rng.standard_normal(mean.shape)
            action = mean + std * noise
            logp = float(np.sum(
                -0.5 * ((action - mean) / std) ** 2 - log_std
                - 0.5 * np.log(2 * np.pi)))
        else:
            logits = np.asarray(pi_out)[0]
            p = np.exp(logits - logits.max())
            p /= p.sum()
            with self._rng_lock:
                action = int(self._rng.choice(len(p), p=p))
            logp = float(np.log(p[action] + 1e-12))
        return action, logp, float(np.asarray(value)[0])

    def _record_step(self, eid: str, obs: np.ndarray, action, logp: float,
                     value: float):
        """Commit the episode's previous pending step (its reward is now
        complete) and park the new one.  Flushes a contiguous fragment to
        the training stream when the episode's buffer is long enough."""
        ep = self._episodes.get(eid)
        if ep is None:
            raise KeyError(f"unknown episode {eid!r}")
        ep.started = time.monotonic()
        if not ep.training_enabled:
            return
        if ep.pending is not None:
            ep.steps.append((*ep.pending, ep.pending_reward))
            ep.pending_reward = 0.0
        ep.pending = (obs, np.asarray(action, np.float32), logp, value)
        if len(ep.steps) >= self.fragment_len:
            # truncated fragment: bootstrap folds into the last reward as
            # gamma * V(next obs) — the pending step's value estimate
            o, a, lp, v, r = ep.steps[-1]
            ep.steps[-1] = (o, a, lp, v, r + self.gamma * value)
            self._flush_fragment(ep)

    def _cmd_get_action(self, req):
        eid = req["episode_id"]
        obs = np.asarray(req["observation"], np.float32)
        action, logp, value = self._policy_step(obs)
        with self._steps_ready:
            self._record_step(eid, obs, action, logp, value)
        return {"action": action}

    def _cmd_log_action(self, req):
        """Client computed the action itself (client-side inference via
        get_weights): record the transition with the server's value/logp
        estimates (reference: ``PolicyClient.log_action``)."""
        eid = req["episode_id"]
        obs = np.asarray(req["observation"], np.float32)
        _, logp, value = self._policy_step(obs)
        with self._steps_ready:
            self._record_step(eid, obs, req["action"], logp, value)
        return {}

    def _cmd_log_returns(self, req):
        with self._lock:
            ep = self._episodes.get(req["episode_id"])
            if ep is None:
                raise KeyError(f"unknown episode {req['episode_id']!r}")
            r = float(req["reward"])
            ep.pending_reward += r
            ep.total_reward += r
            ep.started = time.monotonic()
        return {}

    def _cmd_end_episode(self, req):
        truncated = bool(req.get("truncated", False))
        final_obs = req.get("observation")
        bootstrap = 0.0
        if truncated and final_obs is not None:
            # time-limit truncation is NOT a true terminal: fold
            # gamma * V(final_obs) into the last reward, like the
            # fragment-cut path (gymnasium terminated-vs-truncated split)
            _, _, v = self._policy_step(np.asarray(final_obs, np.float32))
            bootstrap = self.gamma * v
        with self._steps_ready:
            ep = self._episodes.pop(req["episode_id"], None)
            if ep is None:
                raise KeyError(f"unknown episode {req['episode_id']!r}")
            if ep.training_enabled and ep.pending is not None:
                ep.steps.append((*ep.pending,
                                 ep.pending_reward + bootstrap))
                ep.pending = None
                self._flush_fragment(ep, terminal=True)
            self._returns.append(ep.total_reward)
        return {}

    def _cmd_get_weights(self, req):
        import jax
        return {"weights": jax.tree_util.tree_map(np.asarray, self._params),
                "version": self._params_version}

    def _flush_fragment(self, ep: _Episode, terminal: bool = False):
        """Append the episode's committed steps to the training stream as
        one contiguous run ending done=1 (caller holds the lock)."""
        if not ep.steps:
            return
        n = len(ep.steps)
        for i, (o, a, lp, v, r) in enumerate(ep.steps):
            self._steps.append((o, a, lp, v, r, 1.0 if i == n - 1 else 0.0))
        ep.steps.clear()
        self._steps_ready.notify_all()

    # -------------------------------------------------------- input reader

    def next(self, min_steps: int, timeout: Optional[float] = None
             ) -> Optional[Dict[str, np.ndarray]]:
        """Block until ``min_steps`` committed steps exist; return them as
        one ``[T, 1, ...]`` rollout (reference: ``InputReader.next``)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._steps_ready:
            while len(self._steps) < min_steps:
                remaining = None if deadline is None else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._steps_ready.wait(remaining if remaining is not None
                                       else 1.0)
            take = self._steps[:min_steps]
            del self._steps[:min_steps]
            if take[-1][5] == 0.0 and self._steps:
                # fixed-T slicing cut a fragment: the continuation is
                # self._steps[0] (fragments append atomically, so the
                # stream stays contiguous).  Fold its value estimate into
                # the cut step as the truncation bootstrap and close the
                # sequence — the remainder trains as a fresh sequence.
                o, a, lp, v, r, _ = take[-1]
                v_next = self._steps[0][3]
                take[-1] = (o, a, lp, v, r + self.gamma * v_next, 1.0)
        obs, actions, logp, values, rewards, dones = map(list, zip(*take))
        batch = {
            "obs": np.stack(obs)[:, None],
            "actions": np.stack(actions)[:, None]
            if self.model.continuous else np.asarray(actions, np.float32)[:, None],
            "logp": np.asarray(logp, np.float32)[:, None],
            "values": np.asarray(values, np.float32)[:, None],
            "rewards": np.asarray(rewards, np.float32)[:, None],
            "dones": np.asarray(dones, np.float32)[:, None],
            # every fragment is self-contained (ends done=1 with any
            # truncation bootstrap folded into its last reward), so the
            # stream-level bootstrap is always zero
            "last_values": np.zeros((1,), np.float32),
        }
        return batch

    def episode_returns(self, clear: bool = True) -> List[float]:
        with self._lock:
            out = list(self._returns)
            if clear:
                self._returns.clear()
        return out

    def set_weights(self, params):
        import jax.numpy as jnp
        import jax

        self._params = jax.tree_util.tree_map(jnp.asarray, params)
        self._params_version += 1

    def stop(self):
        self._server.shutdown()
        self._server.server_close()

    # ------------------------------------------------------------ plumbing

    def _make_handler(self):
        class Handler(http.server.BaseHTTPRequestHandler):
            def log_message(self, *args):  # quiet
                pass

            def do_POST(inner):
                try:
                    n = int(inner.headers.get("Content-Length", 0))
                    req = pickle.loads(inner.rfile.read(n))
                    cmd = req["command"].lower()
                    fn = getattr(self, f"_cmd_{cmd}", None)
                    if fn is None:
                        raise ValueError(f"unknown command {req['command']!r}")
                    payload = pickle.dumps(fn(req))
                    inner.send_response(200)
                except Exception as e:  # ship the error to the client
                    payload = pickle.dumps({"error": repr(e)})
                    inner.send_response(500)
                inner.send_header("Content-Length", str(len(payload)))
                inner.end_headers()
                inner.wfile.write(payload)

        return Handler


class PolicyClient:
    """Drive a remote policy server from an external simulator
    (reference: ``policy_client.py:58``; remote-inference mode — for
    client-side inference pull ``get_weights`` and run the model
    locally)."""

    def __init__(self, address: str, timeout: float = 60.0):
        if "://" not in address:
            address = f"http://{address}"
        self.address = address
        self.timeout = timeout

    def _send(self, command: str, **kwargs) -> Dict[str, Any]:
        import urllib.request

        body = pickle.dumps({"command": command, **kwargs})
        req = urllib.request.Request(self.address, data=body, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return pickle.loads(resp.read())
        except urllib.error.HTTPError as e:
            payload = pickle.loads(e.read())
            raise RuntimeError(
                f"policy server error: {payload.get('error')}") from None

    def start_episode(self, episode_id: Optional[str] = None,
                      training_enabled: bool = True) -> str:
        return self._send("start_episode", episode_id=episode_id,
                          training_enabled=training_enabled)["episode_id"]

    def get_action(self, episode_id: str, observation):
        return self._send("get_action", episode_id=episode_id,
                          observation=np.asarray(observation))["action"]

    def log_action(self, episode_id: str, observation, action):
        self._send("log_action", episode_id=episode_id,
                   observation=np.asarray(observation), action=action)

    def log_returns(self, episode_id: str, reward: float):
        self._send("log_returns", episode_id=episode_id, reward=float(reward))

    def end_episode(self, episode_id: str, observation=None,
                    truncated: bool = False):
        """``truncated=True`` with the final observation marks a time-limit
        end: the server folds ``gamma * V(observation)`` into the last
        reward instead of treating it as a true terminal."""
        self._send("end_episode", episode_id=episode_id,
                   observation=(None if observation is None
                                else np.asarray(observation)),
                   truncated=truncated)

    def get_weights(self):
        out = self._send("get_weights")
        return out["weights"], out["version"]
