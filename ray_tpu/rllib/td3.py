"""TD3: twin-delayed deterministic policy gradient for continuous control.

Reference: ``rllib/algorithms/td3/`` (TD3Config/TD3, itself DDPG +
the three TD3 fixes).  The components: twin critics with clipped double-Q
targets, TARGET POLICY SMOOTHING (clipped Gaussian noise on the target
action), and DELAYED policy/target updates (actor steps every
``policy_delay`` critic steps).  TPU-first shape, same as sac.py: each
update is a jitted program (two compiled variants — with and without the
actor step — selected by the delay counter); rollouts ride remote runner
actors with replay on the driver.  Shares ``QNetworkSA`` and the replay
buffer with SAC.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .sac import QNetworkSA


class DeterministicPolicy:
    """MLP -> tanh action in [-1, 1]^A (DDPG/TD3 actor)."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(256, 256)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        sizes = (self.obs_dim,) + self.hidden + (self.action_dim,)
        params = {}
        keys = jax.random.split(key, len(sizes))
        for i in range(len(sizes) - 1):
            scale = (2.0 / sizes[i]) ** 0.5 if i < len(sizes) - 2 else 0.01
            params[f"w{i}"] = jax.random.normal(
                keys[i], (sizes[i], sizes[i + 1])) * scale
            params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
        return params

    def apply(self, params, obs):
        import jax.numpy as jnp

        x = obs
        n = len(self.hidden)
        for i in range(n):
            x = jnp.maximum(x @ params[f"w{i}"] + params[f"b{i}"], 0.0)
        return jnp.tanh(x @ params[f"w{n}"] + params[f"b{n}"])


class TD3Runner:
    """Rollout actor: deterministic policy + exploration noise.  Same
    surface/semantics as SACRunner: ``steps`` is the TOTAL transition
    budget (T = steps // num_envs), all envs batch through ONE jitted
    forward per step."""

    def __init__(self, env_name: str, spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0,
                 env_config: Optional[dict] = None,
                 explore_noise: float = 0.1):
        import gymnasium as gym
        import jax

        self._envs = [gym.make(env_name, **(env_config or {}))
                      for _ in range(num_envs)]
        self._policy = DeterministicPolicy(
            spec["obs_dim"], spec["action_dim"], spec["hidden"])
        self._apply = jax.jit(self._policy.apply)
        self.num_envs = num_envs
        self._obs = np.stack([e.reset(seed=seed + i)[0] for i, e in
                              enumerate(self._envs)], dtype=np.float32)
        self._rng = np.random.default_rng(seed)
        self._noise = explore_noise
        low = self._envs[0].action_space.low
        high = self._envs[0].action_space.high
        self._mid, self._half = (high + low) / 2.0, (high - low) / 2.0
        self._returns: List[float] = []
        self._ep_ret = np.zeros(num_envs)

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return self._mid + self._half * a

    def sample(self, params_blob, steps: int, random_actions: bool = False
               ) -> Dict[str, np.ndarray]:
        import jax.numpy as jnp

        params = params_blob
        N = self.num_envs
        T = max(1, steps // N)
        A = self._policy.action_dim
        obs_l, act_l, rew_l, done_l, next_l = [], [], [], [], []
        for _ in range(T):
            if random_actions:
                acts = self._rng.uniform(-1.0, 1.0, (N, A)).astype(
                    np.float32)
            else:
                acts = np.asarray(self._apply(params,
                                              jnp.asarray(self._obs)))
                acts = np.clip(
                    acts + self._rng.normal(0.0, self._noise, acts.shape),
                    -1.0, 1.0).astype(np.float32)
            for i, env in enumerate(self._envs):
                nxt, r, term, trunc, _ = env.step(self._scale(acts[i]))
                self._ep_ret[i] += float(r)
                obs_l.append(self._obs[i].copy())
                act_l.append(acts[i])
                rew_l.append(float(r))
                done_l.append(float(term))
                next_l.append(np.asarray(nxt, np.float32).reshape(-1))
                if term or trunc:
                    self._returns.append(float(self._ep_ret[i]))
                    self._ep_ret[i] = 0.0
                    nxt = env.reset()[0]
                self._obs[i] = np.asarray(nxt, np.float32).reshape(-1)
        return {"obs": np.stack(obs_l), "actions": np.stack(act_l),
                "rewards": np.asarray(rew_l, np.float32),
                "dones": np.asarray(done_l, np.float32),
                "next_obs": np.stack(next_l)}

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._returns)
        if clear:
            self._returns.clear()
        return out


class TD3Config:
    """Builder, same surface shape as SACConfig."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: dict = {}
        self.num_env_runners = 1
        self.num_envs_per_runner = 1
        self.rollout_steps = 256
        self.model: Dict[str, Any] = {"hidden": (256, 256)}
        self.train: Dict[str, Any] = {
            "actor_lr": 3e-4, "critic_lr": 3e-4, "gamma": 0.99,
            "tau": 0.005, "policy_noise": 0.2, "noise_clip": 0.5,
            "policy_delay": 2, "explore_noise": 0.1,
            "batch_size": 256, "train_iters": 32,
            "twin_q": True,  # False = single-critic DDPG semantics
        }
        self.replay: Dict[str, Any] = {
            "capacity": 100_000, "learn_starts": 1000,
            "random_warmup": True,
        }
        self.seed = 0

    def environment(self, env: str, *, env_config: Optional[dict] = None):
        self.env_name = env
        self.env_config = dict(env_config or {})
        return self

    def env_runners(self, num_env_runners: int = 1,
                    num_envs_per_env_runner: int = 1,
                    rollout_steps: int = 256):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_steps = rollout_steps
        return self

    def training(self, **kwargs):
        if "model" in kwargs:
            self.model.update(kwargs.pop("model"))
        if "replay" in kwargs:
            self.replay.update(kwargs.pop("replay"))
        self.train.update(kwargs)
        return self

    def debugging(self, seed: int = 0):
        self.seed = seed
        return self

    #: algorithm class this config builds — subclasses (DDPGConfig) override
    _algo_cls: Optional[type] = None

    def build(self) -> "TD3":
        if not self.env_name:
            raise ValueError("call .environment(env_name) first")
        return (self._algo_cls or TD3)(self)


class TD3:
    """Driver: noisy rollouts -> replay -> delayed twin-critic updates."""

    def __init__(self, config: TD3Config):
        import gymnasium as gym
        import jax
        import optax

        import ray_tpu

        from .replay_buffer import ReplayBuffer

        self.config = config
        probe = gym.make(config.env_name, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        action_dim = int(np.prod(probe.action_space.shape))
        probe.close()
        hidden = tuple(config.model["hidden"])
        self.spec = dict(obs_dim=obs_dim, action_dim=action_dim,
                         hidden=hidden)
        self.policy = DeterministicPolicy(**self.spec)
        self.q1 = QNetworkSA(obs_dim, action_dim, hidden)
        self.q2 = QNetworkSA(obs_dim, action_dim, hidden)
        k = jax.random.split(jax.random.PRNGKey(config.seed), 3)
        self.state = {
            "pi": self.policy.init(k[0]),
            "q1": self.q1.init(k[1]),
            "q2": self.q2.init(k[2]),
        }
        for name in ("pi", "q1", "q2"):
            self.state[f"{name}_t"] = jax.tree_util.tree_map(
                lambda x: x, self.state[name])
        t = config.train
        self.opt = {"pi": optax.adam(t["actor_lr"]),
                    "q": optax.adam(t["critic_lr"])}
        self.opt_state = {
            "pi": self.opt["pi"].init(self.state["pi"]),
            "q": self.opt["q"].init((self.state["q1"], self.state["q2"])),
        }
        self._update = self._build_update()
        self.buffer = ReplayBuffer(config.replay["capacity"],
                                   seed=config.seed)
        runner_cls = ray_tpu.remote(TD3Runner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, self.spec,
                num_envs=config.num_envs_per_runner,
                seed=config.seed + 1000 * i,
                env_config=config.env_config,
                explore_noise=t["explore_noise"])
            for i in range(config.num_env_runners)]
        self._iteration = 0
        self._env_steps = 0
        self._updates = 0
        self._recent_returns: List[float] = []

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config.train
        gamma, tau = cfg["gamma"], cfg["tau"]
        pnoise, nclip = cfg["policy_noise"], cfg["noise_clip"]
        twin = bool(cfg.get("twin_q", True))
        policy, q1, q2 = self.policy, self.q1, self.q2
        opt = self.opt

        def update(state, opt_state, batch, key, do_actor: bool):
            # --- Q target with target policy smoothing; twin_q=False is
            # plain DDPG (single critic, no clipped double-Q)
            noise = jnp.clip(
                pnoise * jax.random.normal(key, batch["actions"].shape),
                -nclip, nclip)
            next_a = jnp.clip(
                policy.apply(state["pi_t"], batch["next_obs"]) + noise,
                -1.0, 1.0)
            q1_next = q1.apply(state["q1_t"], batch["next_obs"], next_a)
            q_next = (jnp.minimum(
                q1_next, q2.apply(state["q2_t"], batch["next_obs"], next_a))
                if twin else q1_next)
            target = jax.lax.stop_gradient(
                batch["rewards"] + gamma * (1 - batch["dones"]) * q_next)

            def critic_loss(qs):
                p1, p2 = qs
                e1 = q1.apply(p1, batch["obs"], batch["actions"]) - target
                if not twin:
                    return (e1 ** 2).mean()
                e2 = q2.apply(p2, batch["obs"], batch["actions"]) - target
                return (e1 ** 2).mean() + (e2 ** 2).mean()

            closs, cgrads = jax.value_and_grad(critic_loss)(
                (state["q1"], state["q2"]))
            cup, q_opt = opt["q"].update(cgrads, opt_state["q"],
                                         (state["q1"], state["q2"]))
            new_q1, new_q2 = jax.tree_util.tree_map(
                lambda p, u: p + u, (state["q1"], state["q2"]), cup)
            new_state = dict(state, q1=new_q1, q2=new_q2)
            new_opt = dict(opt_state, q=q_opt)
            aloss = jnp.float32(0.0)

            if do_actor:  # python bool -> two compiled variants
                def actor_loss(pi_params):
                    a = policy.apply(pi_params, batch["obs"])
                    return -q1.apply(new_q1, batch["obs"], a).mean()

                aloss, agrads = jax.value_and_grad(actor_loss)(state["pi"])
                aup, pi_opt = opt["pi"].update(agrads, opt_state["pi"],
                                               state["pi"])
                new_pi = jax.tree_util.tree_map(lambda p, u: p + u,
                                                state["pi"], aup)
                soft = lambda t_, p: (1 - tau) * t_ + tau * p
                new_state.update(
                    pi=new_pi,
                    pi_t=jax.tree_util.tree_map(soft, state["pi_t"], new_pi),
                    q1_t=jax.tree_util.tree_map(soft, state["q1_t"], new_q1),
                    q2_t=jax.tree_util.tree_map(soft, state["q2_t"], new_q2))
                new_opt["pi"] = pi_opt
            return new_state, new_opt, closs, aloss

        return jax.jit(update, static_argnames=("do_actor",))

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        import ray_tpu

        t0 = time.time()
        cfg = self.config
        warm = (cfg.replay.get("random_warmup", True)
                and self._env_steps < cfg.replay["learn_starts"])
        weights_ref = ray_tpu.put(jax.tree_util.tree_map(
            np.asarray, self.state["pi"]))
        per_runner = max(1, cfg.rollout_steps // cfg.num_env_runners)
        batches = ray_tpu.get(
            [r.sample.remote(weights_ref, per_runner, warm)
             for r in self.runners], timeout=600)
        for b in batches:
            self.buffer.add(b)
            self._env_steps += len(b["rewards"])

        closs = aloss = float("nan")
        delay = cfg.train["policy_delay"]
        if len(self.buffer) >= cfg.replay["learn_starts"]:
            for j in range(cfg.train["train_iters"]):
                s = self.buffer.sample(cfg.train["batch_size"])
                batch = {k: jnp.asarray(v) for k, v in s.items()
                         if not k.startswith("_")}
                key = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.seed),
                    self._iteration * 131 + j)
                self._updates += 1
                state, opt_state, closs, aloss = self._update(
                    self.state, self.opt_state, batch, key,
                    do_actor=(self._updates % delay == 0))
                self.state, self.opt_state = state, opt_state
            closs, aloss = float(closs), float(aloss)

        rets = [x for chunk in ray_tpu.get(
            [r.episode_returns.remote() for r in self.runners], timeout=60)
            for x in chunk]
        self._recent_returns.extend(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "episodes_this_iter": len(rets),
            "num_env_steps_sampled": self._env_steps,
            "critic_loss": closs, "actor_loss": aloss,
            "replay_size": len(self.buffer),
            "time_this_iter_s": time.time() - t0,
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def get_weights(self):
        import jax
        return jax.tree_util.tree_map(np.asarray, self.state["pi"])
