"""PPO: config builder + algorithm driver.

Reference: ``rllib/algorithms/ppo/ppo.py`` (PPOConfig/PPO) over
``algorithms/algorithm.py:191`` (Algorithm.train loop).  The driver keeps the
reference's shape — config builder, EnvRunner fan-out, learner update,
weight broadcast — with the learner math compiled (learner.py).
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np


class PPOConfig:
    """Builder (reference: AlgorithmConfig fluent API)."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 2
        self.num_envs_per_runner = 1
        self.rollout_len = 128
        self.num_learners = 0
        self.num_devices_per_learner = 1
        self.train: Dict[str, Any] = dict(
            lr=3e-4, gamma=0.99, clip_param=0.2, vf_loss_coeff=0.5,
            entropy_coeff=0.0, num_epochs=4, num_minibatches=4,
            grad_clip=0.5)
        self.model: Dict[str, Any] = dict(hidden=(64, 64))
        self.seed = 0
        self.worker_env: Optional[Dict[str, str]] = None
        self.observation_space = None
        self.action_space = None
        self.external_port: Optional[int] = None
        self.external_address = "127.0.0.1"
        self.external_fragment_len = 64

    def environment(self, env: Optional[str] = None, *,
                    env_config: Optional[dict] = None,
                    observation_space=None, action_space=None):
        """``env=None`` with explicit spaces is the external-env mode —
        there is no in-cluster simulator to probe (reference:
        AlgorithmConfig.environment(env=None, observation_space=...,
        action_space=...) for policy-server setups)."""
        self.env_name = env
        self.env_config = env_config or {}
        self.observation_space = observation_space
        self.action_space = action_space
        return self

    def external(self, port: int = 9900, address: str = "127.0.0.1",
                 fragment_len: int = 64):
        """Serve the policy to external simulators instead of running
        in-cluster env runners (reference: policy_server_input.py wired
        via ``config.offline_data(input_=...)``)."""
        self.external_port = port
        self.external_address = address
        self.external_fragment_len = fragment_len
        return self

    def env_runners(self, num_env_runners: int = 2,
                    num_envs_per_env_runner: int = 1,
                    rollout_fragment_length: int = 128):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_len = rollout_fragment_length
        return self

    def learners(self, num_learners: int = 0,
                 num_devices_per_learner: int = 1):
        """Reference semantics (AlgorithmConfig.learners): 0 = update in the
        driver process on its local devices; N >= 1 = place N learner ACTORS
        forming one jax.distributed mesh (learner_group.py)."""
        self.num_learners = num_learners
        self.num_devices_per_learner = num_devices_per_learner
        return self

    def training(self, **kwargs):
        model = kwargs.pop("model", None)
        if model:
            self.model.update(model)
        for k, v in kwargs.items():
            if k == "lambda_":
                k = "lambda"
            self.train[k] = v
        return self

    def debugging(self, seed: int = 0, worker_env: Optional[dict] = None):
        self.seed = seed
        self.worker_env = worker_env
        return self

    #: algorithm class this config builds — subclasses (A2CConfig) override
    _algo_cls: Optional[type] = None

    def build(self) -> "PPO":
        if not self.env_name and self.external_port is None:
            raise ValueError("call .environment(env_name) first "
                             "(or .external(port) with explicit spaces)")
        return (self._algo_cls or PPO)(self)


class PPO:
    """The algorithm driver: rollout fan-out -> compiled update -> broadcast.

    ``train()`` returns a result dict (reference: Algorithm.train's result
    with episode_return_mean), so it drops straight into a Tune trainable.
    """

    def __init__(self, config: PPOConfig):
        import gymnasium as gym

        import ray_tpu

        from .env_runner import EnvRunner as _ER
        from .learner import LearnerGroup
        from .models import build_model

        self.config = config
        if config.env_name is not None:
            probe = gym.make(config.env_name, **config.env_config)
            obs_space, act_space = probe.observation_space, probe.action_space
            probe.close()
        else:  # external-env mode: spaces come from the config
            obs_space, act_space = (config.observation_space,
                                    config.action_space)
            if obs_space is None or act_space is None:
                raise ValueError(
                    "external mode needs .environment(observation_space=..., "
                    "action_space=...) — there is no env to probe")
        obs_shape = obs_space.shape
        continuous = not hasattr(act_space, "n")
        action_dim = (act_space.shape[0] if continuous
                      else int(act_space.n))
        if config.model.get("conv") or len(obs_shape) == 3:
            # pixel obs: Nature-CNN torso (Atari-class envs); filters /
            # torso width overridable for small test grids
            self.model_spec = dict(obs_shape=tuple(obs_shape),
                                   action_dim=action_dim,
                                   continuous=continuous)
            if config.model.get("filters"):
                self.model_spec["filters"] = tuple(
                    tuple(f) for f in config.model["filters"])
            if config.model.get("conv_hidden"):
                self.model_spec["hidden"] = int(config.model["conv_hidden"])
        else:
            self.model_spec = dict(obs_dim=int(np.prod(obs_shape)),
                                   action_dim=action_dim,
                                   hidden=tuple(config.model["hidden"]),
                                   continuous=continuous)
        if config.num_learners >= 1:
            from .learner_group import DistributedLearnerGroup

            self.learner_group = DistributedLearnerGroup(
                self.model_spec, config.train,
                num_learners=config.num_learners, seed=config.seed,
                devices_per_learner=config.num_devices_per_learner)
        else:
            # driver-local: num_devices_per_learner > 1 maps to an
            # in-process dp mesh over that many local devices
            model = build_model(self.model_spec)
            self.learner_group = LearnerGroup(
                model, config.train,
                num_learners=max(1, config.num_devices_per_learner),
                seed=config.seed)
        self.policy_server = None
        if config.external_port is not None:
            # external-env mode: no in-cluster runners — samples arrive
            # over the policy server (external.py)
            from .external import PolicyServerInput
            from .models import build_model
            self.policy_server = PolicyServerInput(
                build_model(self.model_spec),
                self.learner_group.get_weights(),
                address=config.external_address, port=config.external_port,
                gamma=config.train.get("gamma", 0.99),
                fragment_len=config.external_fragment_len)
            self.runners = []
        else:
            runner_cls = ray_tpu.remote(_ER)
            self.runners = [
                runner_cls.options(num_cpus=1).remote(
                    config.env_name, self.model_spec,
                    num_envs=config.num_envs_per_runner,
                    seed=config.seed + 1000 * i,
                    env_config=config.env_config)
                for i in range(config.num_env_runners)]
        self._iteration = 0
        self._recent_returns: List[float] = []

    def train(self) -> Dict[str, Any]:
        """One iteration: sample on all runners, one compiled update."""
        import ray_tpu

        t0 = time.time()
        if self.policy_server is not None:
            rollout = self.policy_server.next(self.config.rollout_len)
            metrics = self.learner_group.update(rollout)
            self.policy_server.set_weights(self.learner_group.get_weights())
            rets = self.policy_server.episode_returns()
            steps = self.config.rollout_len
        else:
            weights = self.learner_group.get_weights()
            weights_ref = ray_tpu.put(weights)
            batches = ray_tpu.get(
                [r.sample.remote(weights_ref, self.config.rollout_len)
                 for r in self.runners], timeout=600)
            # concat along the env axis: [T, sum(B_i), ...]
            rollout = {
                k: np.concatenate([b[k] for b in batches],
                                  axis=0 if k == "last_values" else 1)
                for k in batches[0]}
            metrics = self.learner_group.update(rollout)
            rets = [x for r in self.runners
                    for x in ray_tpu.get(r.episode_returns.remote(),
                                         timeout=60)]
            steps = (self.config.rollout_len * self.config.num_env_runners
                     * self.config.num_envs_per_runner)
        self._recent_returns.extend(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        out = {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "episodes_this_iter": len(rets),
            "num_env_steps_sampled": steps * self._iteration,
            "time_this_iter_s": time.time() - t0,
            **metrics,
        }
        return out

    def stop(self):
        import ray_tpu

        if self.policy_server is not None:
            self.policy_server.stop()
        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass
        if hasattr(self.learner_group, "shutdown"):
            self.learner_group.shutdown()

    def get_weights(self):
        return self.learner_group.get_weights()

    @staticmethod
    def as_tune_trainable(config_builder):
        """Wrap a PPOConfig-producing callable into a Tune trainable fn."""
        def trainable(tune_config: Dict[str, Any]):
            from ray_tpu import tune as rt_tune

            cfg = config_builder(tune_config)
            algo = cfg.build()
            try:
                while True:
                    rt_tune.report(algo.train())
            finally:
                algo.stop()
        return trainable
