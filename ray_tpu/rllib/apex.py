"""Ape-X DQN: distributed prioritized experience replay.

Reference: ``rllib/algorithms/apex_dqn/apex_dqn.py`` (APEX — Horgan et
al.: many samplers with a per-actor exploration ladder, replay sharded
across dedicated actors, a learner that consumes shard samples
asynchronously and pushes refreshed priorities back).

Reuse map: the jitted double-DQN update and the n-step env runner come
straight from dqn.py; the decoupled resubmit-on-arrival pattern is the
one IMPALA proved (impala.py) — here applied to replay inserts instead
of on-policy batches. Replay shards are ordinary actors wrapping
PrioritizedReplayBuffer, so the replay tier scales (and fails) like any
other actor pool: a shard lost to a node failure costs only its slice
of the buffer.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

from .dqn import DQN, DQNConfig

__all__ = ["APEX", "APEXConfig", "ReplayShard"]


class ReplayShard:
    """One slice of the distributed replay tier (reference:
    ApexDQN's replay actors over utils/replay_buffers)."""

    def __init__(self, capacity: int, alpha: float, beta: float,
                 seed: int = 0):
        from .replay_buffer import PrioritizedReplayBuffer
        self.buf = PrioritizedReplayBuffer(capacity, alpha=alpha,
                                           beta=beta, seed=seed)

    def add(self, batch: Dict[str, np.ndarray],
            priorities: Optional[np.ndarray] = None) -> int:
        idx = self.buf.add(batch)
        if priorities is not None:
            # replace the default max-priority with the caller's |TD|
            self.buf.update_priorities(idx, priorities)
        return len(self.buf)

    def sample(self, batch_size: int):
        """Returns the sampled dict (fields + _indices/_weights) or None
        when the shard is still shallower than one batch."""
        if len(self.buf) < batch_size:
            return None
        return self.buf.sample(batch_size)

    def update_priorities(self, indices: np.ndarray,
                          priorities: np.ndarray) -> bool:
        self.buf.update_priorities(indices, priorities)
        return True

    def size(self) -> int:
        return len(self.buf)


class APEXConfig(DQNConfig):
    """DQNConfig plus the Ape-X distribution knobs."""

    def __init__(self):
        super().__init__()
        self.num_env_runners = 4
        self.num_replay_shards = 2
        # per-actor exploration ladder: eps_i = base ** (1 + i/(N-1)*alpha)
        # (the paper's schedule — a spread of exploration temperaments
        # replacing the single annealed epsilon)
        self.epsilon_base = 0.4
        self.epsilon_alpha = 7.0
        self.replay.update(prioritized=True, learn_starts=500)

    def env_runners(self, num_env_runners: int = 4, **kw):
        return super().env_runners(num_env_runners, **kw)

    def sharding(self, num_replay_shards: int = 2,
                 epsilon_base: float = 0.4, epsilon_alpha: float = 7.0):
        self.num_replay_shards = num_replay_shards
        self.epsilon_base = epsilon_base
        self.epsilon_alpha = epsilon_alpha
        return self

    def build(self) -> "APEX":
        if not self.env_name:
            raise ValueError("call .environment(env_name) first")
        return APEX(self)


class APEX(DQN):
    """Driver: sampler fleet -> sharded prioritized replay -> learner.

    One ``train()`` iteration: harvest whichever sampler batches have
    arrived (resubmitting each sampler immediately — samplers never wait
    on the learner), insert with fresh TD priorities into a
    round-robin shard, then run ``train_iters`` learner updates pulling
    from random shards and pushing refreshed priorities back.
    """

    def _make_buffer(self):
        return None  # replay lives in the shard actors

    def __init__(self, config: APEXConfig):
        import jax

        import ray_tpu

        super().__init__(config)
        shard_cls = ray_tpu.remote(ReplayShard)
        r = config.replay
        per_shard = max(1, r["capacity"] // config.num_replay_shards)
        self.shards = [
            shard_cls.options(num_cpus=0.5).remote(
                per_shard, r["alpha"], r["beta"], seed=config.seed + i)
            for i in range(config.num_replay_shards)]
        n = max(2, config.num_env_runners)
        self._actor_eps = [
            float(config.epsilon_base
                  ** (1.0 + i / (n - 1) * config.epsilon_alpha))
            for i in range(config.num_env_runners)]
        self._inflight: Dict[Any, int] = {}   # sample ref -> runner index
        self._next_shard = 0
        self._rng = np.random.default_rng(config.seed)

        # jitted initial-priority pass: |TD error| under current params
        # (the paper computes these actor-side; with the learner one hop
        # away we spend one forward here instead of shipping weights to
        # every sampler every rollout)
        import jax.numpy as jnp
        model = self.model
        double_q = bool(config.train["double_q"])

        def td_abs(params, target_params, batch):
            # must mirror the learner's target (dqn.py loss_fn) including
            # the double_q branch — a priority computed against a
            # different target than training optimizes skews PER
            q = model.apply(params, batch["obs"])
            qa = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), -1)[:, 0]
            nq_t = model.apply(target_params, batch["next_obs"])
            if double_q:
                sel = model.apply(params, batch["next_obs"]).argmax(axis=-1)
                boot = jnp.take_along_axis(nq_t, sel[:, None], -1)[:, 0]
            else:
                boot = nq_t.max(axis=-1)
            target = batch["rewards"] + batch["discounts"] * boot
            return jnp.abs(qa - target)

        self._td_abs = jax.jit(td_abs)

    def _harvest_and_insert(self, timeout: float) -> int:
        """Collect arrived sampler batches, resubmit samplers, insert
        into shards with fresh priorities. Returns env steps inserted."""
        import ray_tpu

        cfg = self.config
        per_runner = max(1, cfg.rollout_steps // cfg.num_env_runners)
        weights_ref = ray_tpu.put(
            {k: np.asarray(v) for k, v in self.params.items()})
        if not self._inflight:
            for i, r in enumerate(self.runners):
                ref = r.sample.remote(weights_ref, per_runner,
                                      self._actor_eps[i],
                                      cfg.train["n_step"],
                                      cfg.train["gamma"])
                self._inflight[ref] = i
        ready, _ = ray_tpu.wait(list(self._inflight),
                                num_returns=len(self._inflight),
                                timeout=timeout)
        if not ready and self._inflight:
            # decoupled tier may lag the learner; block for one batch so
            # an iteration always makes replay progress
            ready, _ = ray_tpu.wait(list(self._inflight), num_returns=1,
                                    timeout=600)
        steps = 0
        add_refs = []
        for ref in ready:
            i = self._inflight.pop(ref)
            batch = ray_tpu.get(ref, timeout=60)
            prios = np.asarray(self._td_abs(self.params,
                                            self.target_params, batch))
            shard = self.shards[self._next_shard]
            self._next_shard = ((self._next_shard + 1)
                                % len(self.shards))
            add_refs.append(shard.add.remote(batch, prios + 1e-6))
            steps += len(batch["rewards"])
            # resubmit immediately — the sampler never idles
            nref = self.runners[i].sample.remote(
                weights_ref, per_runner, self._actor_eps[i],
                cfg.train["n_step"], cfg.train["gamma"])
            self._inflight[nref] = i
        if add_refs:
            ray_tpu.get(add_refs, timeout=60)
        return steps

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        t0 = time.time()
        cfg = self.config
        self._env_steps += self._harvest_and_insert(timeout=0.05)

        sizes = ray_tpu.get([s.size.remote() for s in self.shards],
                            timeout=60)
        losses: List[float] = []
        if sum(sizes) >= cfg.replay["learn_starts"]:
            for _ in range(cfg.train["train_iters"]):
                order = self._rng.permutation(len(self.shards))
                picked = None
                for j in order:  # first shard deep enough this pull
                    picked = ray_tpu.get(
                        self.shards[j].sample.remote(
                            cfg.train["batch_size"]), timeout=60)
                    if picked is not None:
                        break
                if picked is None:
                    break
                indices = picked.pop("_indices")
                batch = dict(picked, weights=picked.pop("_weights"))
                (self.params, self.target_params, self.opt_state, loss,
                 td) = self._update(self.params, self.target_params,
                                    self.opt_state, batch)
                losses.append(float(loss))
                self.shards[int(j)].update_priorities.remote(
                    indices, np.abs(np.asarray(td)) + 1e-6)

        rets = [x for r in self.runners
                for x in ray_tpu.get(r.episode_returns.remote(),
                                     timeout=60)]
        self._recent_returns.extend(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else
                                    float("nan")),
            "episodes_this_iter": len(rets),
            "timesteps_total": self._env_steps,
            "replay_shard_sizes": sizes,
            "actor_epsilons": self._actor_eps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "num_updates": len(losses),
            "time_this_iter_s": time.time() - t0,
        }

    def stop(self):
        import ray_tpu

        super().stop()
        for a in self.shards:
            try:
                ray_tpu.kill(a)
            except Exception:
                pass
