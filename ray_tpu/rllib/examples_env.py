"""Tiny example environments (reference: rllib/examples/envs).

``Catch-v0`` is a 12x12x1 pixel env — a minimal Atari stand-in for CI:
a ball falls one row per step from a random column; the agent moves a
3-pixel paddle on the bottom row (actions: left/stay/right); +1 for
catching, -1 for missing, episode length = grid height.  Importing this
module registers it, so remote EnvRunners can
``gym.make("ray_tpu.rllib.examples_env:Catch-v0")``.
"""

from __future__ import annotations

import numpy as np

try:
    import gymnasium as gym
    from gymnasium import spaces
except ImportError:  # pragma: no cover - gymnasium is in the image
    gym = None


if gym is not None:
    class CatchEnv(gym.Env):
        SIZE = 12

        def __init__(self, render_mode=None):
            n = self.SIZE
            self.observation_space = spaces.Box(0.0, 1.0, (n, n, 1),
                                                np.float32)
            self.action_space = spaces.Discrete(3)
            self._rng = np.random.default_rng(0)

        def _obs(self):
            n = self.SIZE
            frame = np.zeros((n, n, 1), np.float32)
            frame[self.ball_y, self.ball_x, 0] = 1.0
            lo = max(0, self.paddle - 1)
            hi = min(n, self.paddle + 2)
            frame[n - 1, lo:hi, 0] = 1.0
            return frame

        def reset(self, *, seed=None, options=None):
            if seed is not None:
                self._rng = np.random.default_rng(seed)
            self.ball_x = int(self._rng.integers(0, self.SIZE))
            self.ball_y = 0
            self.paddle = self.SIZE // 2
            return self._obs(), {}

        def step(self, action):
            self.paddle = int(np.clip(self.paddle + int(action) - 1,
                                      0, self.SIZE - 1))
            self.ball_y += 1
            done = self.ball_y >= self.SIZE - 1
            reward = 0.0
            if done:
                reward = 1.0 if abs(self.ball_x - self.paddle) <= 1 else -1.0
            return self._obs(), reward, done, False, {}

    gym.register(id="Catch-v0", entry_point=CatchEnv)

    class BiasBanditEnv(gym.Env):
        """8-step two-armed bandit with a constant observation: reward
        equals the chosen action. The smallest env whose optimum a policy
        must LEARN (bias toward arm 1) — CI smoke target for the
        derivative-free algorithms (es.py), where a few iterations must
        visibly move the policy."""

        HORIZON = 8

        def __init__(self, render_mode=None):
            self.observation_space = spaces.Box(-1.0, 1.0, (2,), np.float32)
            self.action_space = spaces.Discrete(2)
            self._t = 0

        def _obs(self):
            return np.array([1.0, -1.0], np.float32)

        def reset(self, *, seed=None, options=None):
            self._t = 0
            return self._obs(), {}

        def step(self, action):
            self._t += 1
            return (self._obs(), float(action), self._t >= self.HORIZON,
                    False, {})

    gym.register(id="Bandit-v0", entry_point=BiasBanditEnv)
