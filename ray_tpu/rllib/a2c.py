"""A2C: synchronous advantage actor-critic.

Reference: ``rllib/algorithms/a2c/a2c.py`` (A2CConfig, the synchronous
A3C variant).  A2C is the PPO driver degenerated to one on-policy pass:
with ``num_epochs=1`` and one minibatch the importance ratio is
identically 1 at the update point, so PPO's clipped surrogate's gradient
reduces EXACTLY to the vanilla policy gradient ``adv * grad(logp)`` —
one jitted program serves both algorithms (learner.py), the config is
the axis between them (same inversion as DDPG/TD3 in td3.py).
"""

from __future__ import annotations

from .ppo import PPO, PPOConfig

__all__ = ["A2C", "A2CConfig"]


class A2C(PPO):
    """Driver: synchronous rollout fan-out -> one policy-gradient pass."""


class A2CConfig(PPOConfig):
    """PPOConfig pinned to the single-pass on-policy regime (reference
    defaults: entropy bonus on, one SGD pass per batch)."""

    _algo_cls = A2C

    def __init__(self):
        super().__init__()
        self.train.update(
            num_epochs=1,        # one on-policy pass: ratio == 1
            num_minibatches=1,   # whole-batch gradient
            clip_param=1e9,      # clipping never binds at ratio 1
            entropy_coeff=0.01,  # A2C's exploration bonus (reference default)
        )
