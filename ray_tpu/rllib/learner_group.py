"""Cross-process LearnerGroup: learner ACTORS spanning processes/hosts.

Reference: ``rllib/core/learner/learner_group.py:61`` — N learner workers,
each on its own accelerator(s), DDP-synced with NCCL allreduce; the
Algorithm ships batches to the group and pulls weights back.

TPU-first redesign: the N learner actors form ONE ``jax.distributed``
namespace (the seam proven by ``tests/test_train.py``'s two-process mesh
test) and build a single global ``Mesh`` over every process's devices.  The
update stays the same jitted program as the local path — each actor feeds
its process-local batch slice, ``jax.make_array_from_process_local_data``
assembles the global batch, and XLA inserts the cross-process gradient psum
(ICI on a real pod, gloo on the CPU CI mesh).  There is no hand-written
allreduce anywhere.

On a real multi-host TPU pod: one LearnerWorker per host (placement-group
STRICT_SPREAD), each seeing its local chips; here in CI: N processes on one
box, each with the 8-device virtual CPU mesh.
"""

from __future__ import annotations

import socket
from typing import Any, Dict, List, Optional, Type

import numpy as np


def _node_ip() -> str:
    """Best-effort routable IP for the jax.distributed coordinator (falls
    back to loopback on a single box, which is the CI case)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect(("8.8.8.8", 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except Exception:
        return "127.0.0.1"


class LearnerWorker:
    """One learner process of the group (runs as a ray_tpu actor).

    ``__init__`` stores config only; ``setup`` joins the jax.distributed
    namespace and builds the learner — split so the group can first ask
    rank 0 for a coordinator address, then set every rank up concurrently
    (``jax.distributed.initialize`` blocks until all ranks connect).
    """

    def __init__(self, model_spec: Dict[str, Any], train_cfg: Dict[str, Any],
                 learner_cls: Optional[Type] = None, seed: int = 0,
                 devices_per_learner: int = 1):
        self._spec = dict(model_spec)
        self._cfg = dict(train_cfg)
        self._learner_cls = learner_cls
        self._seed = seed
        self._per = int(devices_per_learner)
        self.learner = None
        self.rank = 0

    def pick_coordinator(self) -> str:
        s = socket.socket()
        s.bind(("", 0))
        port = s.getsockname()[1]
        s.close()
        return f"{_node_ip()}:{port}"

    def setup(self, coordinator: str, rank: int, world: int) -> Dict[str, int]:
        import jax

        if world > 1:
            import os

            from ray_tpu.util import jax_compat
            if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
                # CPU-only learner groups (CI) need gloo collectives
                # selected before the backend exists.
                jax_compat.enable_cpu_multiprocess_collectives()
            jax.distributed.initialize(coordinator_address=coordinator,
                                       num_processes=world, process_id=rank)
        from .learner import Learner
        from .models import build_model

        model = build_model(self._spec)
        # dp mesh over the first devices_per_learner devices of EVERY
        # process, in process order (reference num_gpus_per_learner); the
        # process-major order keeps each rank's batch block contiguous.
        by_proc: Dict[int, list] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, []).append(d)
        devs = np.array([d for p in sorted(by_proc)
                         for d in by_proc[p][:self._per]])
        mesh = jax.sharding.Mesh(devs, ("dp",))
        cls = self._learner_cls or Learner
        self.learner = cls(model, self._cfg, mesh=mesh, seed=self._seed)
        self.rank = rank
        return {"rank": rank, "num_devices": len(devs),
                "num_processes": jax.process_count()}

    def update(self, shard: Dict[str, np.ndarray]) -> Optional[Dict[str, float]]:
        """Run the collective update on this rank's batch slice.  Every rank
        MUST be called with its slice of the same global batch (the group
        guarantees this); only rank 0 returns metrics."""
        metrics = self.learner.update(shard)
        return metrics if self.rank == 0 else None

    def get_weights(self) -> Dict[str, np.ndarray]:
        return self.learner.get_weights()


class DistributedLearnerGroup:
    """N learner actors, one jax.distributed mesh, same Learner interface.

    Drop-in for ``LearnerGroup``: ``update(rollout)`` splits the global
    batch's env axis across ranks and blocks on all of them (the psum is a
    barrier anyway); ``get_weights`` reads rank 0's replicated params.
    """

    def __init__(self, model_spec: Dict[str, Any], train_cfg: Dict[str, Any],
                 num_learners: int, seed: int = 0,
                 learner_cls: Optional[Type] = None,
                 devices_per_learner: int = 1):
        import ray_tpu

        self.world = int(num_learners)
        self.dp_shards = self.world * int(devices_per_learner)
        actor_cls = ray_tpu.remote(LearnerWorker)
        self.workers = [
            actor_cls.options(num_cpus=1).remote(
                model_spec, train_cfg, learner_cls, seed,
                devices_per_learner)
            for _ in range(self.world)]
        try:
            coordinator = ray_tpu.get(
                self.workers[0].pick_coordinator.remote(), timeout=120)
            self.info = ray_tpu.get(
                [w.setup.remote(coordinator, i, self.world)
                 for i, w in enumerate(self.workers)], timeout=600)[0]
        except BaseException:
            # a rank failing setup leaves the others blocked inside
            # jax.distributed.initialize — reap them all before raising
            self.shutdown()
            raise

    def _split(self, rollout: Dict[str, np.ndarray]) -> List[Dict[str, np.ndarray]]:
        shards: List[Dict[str, np.ndarray]] = [dict() for _ in range(self.world)]
        for k, v in rollout.items():
            v = np.asarray(v)
            axis = 0 if v.ndim <= 1 else 1
            b = v.shape[axis]
            if b % self.dp_shards:
                raise ValueError(
                    f"batch axis {b} of '{k}' not divisible by the dp mesh "
                    f"({self.world} learners x devices_per_learner = "
                    f"{self.dp_shards} shards); size the per-update env "
                    f"axis (PPO: env_runners x num_envs; IMPALA: num_envs "
                    f"of ONE fragment) to a multiple of it")
            for i, piece in enumerate(np.split(v, self.world, axis=axis)):
                shards[i][k] = piece
        return shards

    def update(self, rollout: Dict[str, np.ndarray]) -> Dict[str, float]:
        import ray_tpu

        shards = self._split(rollout)
        out = ray_tpu.get(
            [w.update.remote(s) for w, s in zip(self.workers, shards)],
            timeout=600)
        return out[0]

    def get_weights(self) -> Dict[str, np.ndarray]:
        import ray_tpu

        return ray_tpu.get(self.workers[0].get_weights.remote(), timeout=300)

    def shutdown(self):
        import ray_tpu

        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:
                pass
