"""ray_tpu.rllib — reinforcement learning on the actor substrate.

Reference surface (SURVEY §2.8): ``rllib/algorithms/algorithm.py:191``
(Algorithm), ``core/learner/learner_group.py:61`` (LearnerGroup),
``env/env_runner.py:9`` / ``evaluation/rollout_worker.py:159`` (EnvRunner).

TPU-first re-architecture: rollouts run in EnvRunner *actors* (CPU-bound
gymnasium stepping, policy inference in jax on the worker); the learner
update is ONE jitted program — GAE, minibatch epochs and the PPO loss all
inside jit, data-parallel over a ``jax.sharding.Mesh`` with XLA allreduce
(the reference's NCCL learner-group allreduce becomes a compiled psum).
``learners(num_learners=N)`` scales that same program across N learner
ACTOR processes on one ``jax.distributed`` mesh (learner_group.py).

Algorithms: PPO and A2C (MLP + conv), DQN, SAC, DDPG, TD3, IMPALA/APPO (V-trace,
decoupled async sampling), ES/ARS (derivative-free, seed-replicated noise),
BC/MARWIL/CQL offline; multi-agent dict envs; external-env protocol
(PolicyServerInput/PolicyClient over HTTP).
"""

from .a2c import A2C, A2CConfig
from .apex import APEX, APEXConfig, ReplayShard
from .conv import ActorCriticConv
from .ddpg import DDPG, DDPGConfig
from .dqn import DQN, DQNConfig, QNetwork
from .env_runner import EnvRunner
from .es import ARS, ARSConfig, ES, ESConfig
from .external import PolicyClient, PolicyServerInput
from .impala import APPO, APPOConfig, IMPALA, IMPALAConfig
from .learner import Learner, LearnerGroup
from .learner_group import DistributedLearnerGroup, LearnerWorker
from .models import ActorCriticMLP, build_model
from .multi_agent import (MultiAgentEnv, MultiAgentEnvRunner, MultiAgentPPO,
                          RockPaperScissors)
from .offline import (BCConfig, CQL, CQLConfig, MARWIL, MARWILConfig,
                      OfflineDataset, TransitionDataset,
                      collect_episodes, write_episodes)
from .ppo import PPO, PPOConfig
from .rainbow import DistQNetwork, Rainbow, RainbowConfig
from .replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from .sac import SAC, SACConfig
from .td3 import TD3, TD3Config

__all__ = ["PPO", "PPOConfig", "A2C", "A2CConfig", "DQN", "DQNConfig",
           "SAC", "SACConfig", "DDPG", "DDPGConfig", "TD3", "TD3Config",
           "IMPALA", "IMPALAConfig", "APPO", "APPOConfig",
           "APEX", "APEXConfig", "ReplayShard",
           "Rainbow", "RainbowConfig", "DistQNetwork",
           "ES", "ESConfig", "ARS", "ARSConfig",
           "PolicyClient", "PolicyServerInput",
           "BCConfig", "CQL", "CQLConfig", "MARWIL", "MARWILConfig",
           "OfflineDataset", "TransitionDataset",
           "collect_episodes", "write_episodes",
           "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
           "RockPaperScissors",
           "QNetwork", "EnvRunner", "Learner", "LearnerGroup",
           "DistributedLearnerGroup", "LearnerWorker",
           "ActorCriticMLP", "ActorCriticConv", "build_model",
           "ReplayBuffer", "PrioritizedReplayBuffer"]

# Usage telemetry: which libraries a cluster actually uses (reference:
# usage_lib.record_library_usage at import time).  Never raises.
from ray_tpu.util.usage_stats import record_library_usage as _rlu
_rlu("rllib")
del _rlu
