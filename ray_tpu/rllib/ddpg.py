"""DDPG: deterministic policy gradient for continuous control.

Reference: ``rllib/algorithms/ddpg/`` (DDPGConfig/DDPG).  TD3 is DDPG
plus three fixes (twin critics, target smoothing, delayed actor), so
here DDPG is the TD3 driver with those fixes switched OFF — one jitted
update program either way (td3.py), which is exactly how the reference
relates them (its TD3 subclasses DDPG; we invert the direction because
the general update lives in td3.py).

Usage::

    algo = (DDPGConfig()
            .environment("Pendulum-v1")
            .training(train_iters=16)
            .build())
    algo.train()
"""

from __future__ import annotations

from .td3 import TD3, TD3Config

__all__ = ["DDPG", "DDPGConfig"]


class DDPG(TD3):
    """Driver: noisy rollouts -> replay -> single-critic updates."""


class DDPGConfig(TD3Config):
    """TD3Config with the TD3-specific fixes disabled by default
    (callers can re-enable any of them individually — that is the
    DDPG->TD3 ablation axis)."""

    _algo_cls = DDPG

    def __init__(self):
        super().__init__()
        self.train.update(
            twin_q=False,       # single critic, no clipped double-Q
            policy_noise=0.0,   # no target policy smoothing
            policy_delay=1,     # actor + targets update every step
        )
