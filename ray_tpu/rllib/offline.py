"""Offline RL: episode datasets + BC / MARWIL training.

Reference: ``rllib/offline/`` (JSON episode readers, the input_/output_
config) and ``rllib/algorithms/marwil`` (MARWIL — Monotonic Advantage
Re-Weighted Imitation Learning; BC is its beta=0 special case).

TPU-first shape: offline data is just arrays — one jitted update does
advantage estimation (Monte-Carlo returns minus the value head), the
exponentially advantage-weighted NLL policy loss, and the value
regression, data-parallel over a mesh like every other learner here.
Episodes read/write as JSON-lines files (one episode per line), the same
wire shape the reference's JsonReader consumes, so corpora can be shared.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

import numpy as np


# ---------------------------------------------------------------------------
# episode IO (reference: offline/json_reader.py / json_writer.py)
# ---------------------------------------------------------------------------

def write_episodes(path: str, episodes: List[Dict[str, Any]]) -> int:
    """Append episodes ({obs, actions, rewards} lists) as JSON lines."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "a") as f:
        for ep in episodes:
            f.write(json.dumps({
                "obs": np.asarray(ep["obs"]).tolist(),
                "actions": np.asarray(ep["actions"]).tolist(),
                "rewards": np.asarray(ep["rewards"]).tolist(),
            }) + "\n")
    return len(episodes)


def collect_episodes(env_name: str, policy_fn: Callable[[np.ndarray], int],
                     num_episodes: int, path: Optional[str] = None,
                     env_config: Optional[dict] = None,
                     seed: int = 0) -> List[Dict[str, Any]]:
    """Roll a (scripted or learned) policy and optionally persist the
    episodes — the offline corpus generator for tests/demos."""
    import gymnasium as gym
    env = gym.make(env_name, **(env_config or {}))
    episodes = []
    for i in range(num_episodes):
        obs, _ = env.reset(seed=seed + i)
        ep = {"obs": [], "actions": [], "rewards": []}
        done = False
        while not done:
            a = int(policy_fn(np.asarray(obs)))
            ep["obs"].append(np.asarray(obs).tolist())
            ep["actions"].append(a)
            obs, r, term, trunc, _ = env.step(a)
            ep["rewards"].append(float(r))
            done = term or trunc
        episodes.append(ep)
    if path:
        write_episodes(path, episodes)
    return episodes


class OfflineDataset:
    """Flattened (obs, action, mc_return) transitions from episode files."""

    def __init__(self, obs: np.ndarray, actions: np.ndarray,
                 returns: np.ndarray):
        self.obs = obs
        self.actions = actions
        self.returns = returns

    def __len__(self):
        return len(self.obs)

    @classmethod
    def from_jsonl(cls, path: str, gamma: float = 0.99) -> "OfflineDataset":
        obs, acts, rets = [], [], []
        with open(path) as f:
            for line in f:
                ep = json.loads(line)
                r = np.asarray(ep["rewards"], np.float32)
                # discounted Monte-Carlo return-to-go per step
                g = np.zeros_like(r)
                acc = 0.0
                for t in range(len(r) - 1, -1, -1):
                    acc = r[t] + gamma * acc
                    g[t] = acc
                obs.append(np.asarray(ep["obs"], np.float32))
                acts.append(np.asarray(ep["actions"]))
                rets.append(g)
        return cls(np.concatenate(obs), np.concatenate(acts),
                   np.concatenate(rets))


# ---------------------------------------------------------------------------
# MARWIL / BC
# ---------------------------------------------------------------------------

class MARWILConfig:
    """Builder mirroring the on-policy config style (reference:
    algorithms/marwil/marwil.py MARWILConfig).  beta=0.0 is exact behavior
    cloning (the advantage weight collapses to 1)."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: dict = {}
        self.input_path: Optional[str] = None
        self.cfg: Dict[str, Any] = {
            "lr": 1e-3, "beta": 1.0, "vf_coeff": 1.0, "grad_clip": 10.0,
            "train_batch_size": 512, "gamma": 0.99, "hidden": (64, 64),
            "advantage_clip": 10.0, "updates_per_iter": 50, "seed": 0,
        }

    def environment(self, env: str, *, env_config: Optional[dict] = None):
        self.env_name = env
        self.env_config = env_config or {}
        return self

    def offline_data(self, input_path: str):
        self.input_path = input_path
        return self

    def training(self, **kwargs):
        self.cfg.update(kwargs)
        return self

    def build(self) -> "MARWIL":
        assert self.env_name and self.input_path, \
            "need .environment(...) and .offline_data(...)"
        return MARWIL(self)


class BCConfig(MARWILConfig):
    """Behavior cloning = MARWIL with beta=0 (reference: algorithms/bc)."""

    def __init__(self):
        super().__init__()
        self.cfg["beta"] = 0.0


class MARWIL:
    """Offline learner: one jitted update over sampled minibatches."""

    def __init__(self, config: MARWILConfig):
        import jax
        import jax.numpy as jnp
        import optax

        import gymnasium as gym

        from .models import ActorCriticMLP

        self.config = config
        cfg = config.cfg
        env = gym.make(config.env_name, **config.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(env.action_space.n)
        self._eval_env = env
        self.model = ActorCriticMLP(obs_dim, act_dim,
                                    hidden=tuple(cfg["hidden"]))
        self.params = self.model.init(jax.random.PRNGKey(cfg["seed"]))
        self.opt = optax.chain(optax.clip_by_global_norm(cfg["grad_clip"]),
                               optax.adam(cfg["lr"]))
        self.opt_state = self.opt.init(self.params)
        self.data = OfflineDataset.from_jsonl(config.input_path,
                                              gamma=cfg["gamma"])
        self._rng = np.random.default_rng(cfg["seed"])
        self.iteration = 0

        beta = float(cfg["beta"])
        vf_coeff = float(cfg["vf_coeff"])
        aclip = float(cfg["advantage_clip"])
        model = self.model

        def loss_fn(params, obs, actions, returns):
            pi_out, value = model.apply(params, obs)
            logp = model.log_prob(pi_out, actions)
            if beta > 0:
                adv = returns - jax.lax.stop_gradient(value)
                # RMS-normalize before exponentiating (reference MARWIL's
                # running moment): keeps beta's meaning independent of the
                # env's reward scale instead of saturating the clip bound.
                adv = adv / (jnp.sqrt(jnp.mean(adv ** 2)) + 1e-8)
                w = jax.lax.stop_gradient(
                    jnp.exp(jnp.clip(beta * adv, -aclip, aclip)))
                vf_loss = ((value - returns) ** 2).mean()
                vf = vf_coeff
            else:
                # pure BC: no advantage weight, and no value head to fit —
                # its huge early regression gradients would only eat the
                # shared global-norm clip budget.
                w = 1.0
                vf_loss = jnp.zeros(())
                vf = 0.0
            pi_loss = -(w * logp).mean()
            return pi_loss + vf * vf_loss, (pi_loss, vf_loss)

        @jax.jit
        def update(params, opt_state, obs, actions, returns):
            (loss, (pl, vl)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, obs, actions, returns)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, pl, vl

        self._update = update
        self._jnp = jnp

    def train(self) -> Dict[str, Any]:
        cfg = self.config.cfg
        bs = min(cfg["train_batch_size"], len(self.data))
        losses, pls, vls = [], [], []
        for _ in range(cfg["updates_per_iter"]):
            idx = self._rng.integers(0, len(self.data), bs)
            self.params, self.opt_state, loss, pl, vl = self._update(
                self.params, self.opt_state,
                self._jnp.asarray(self.data.obs[idx]),
                self._jnp.asarray(self.data.actions[idx]),
                self._jnp.asarray(self.data.returns[idx]))
            losses.append(float(loss))
            pls.append(float(pl))
            vls.append(float(vl))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "loss": float(np.mean(losses)),
                "policy_loss": float(np.mean(pls)),
                "vf_loss": float(np.mean(vls)),
                "num_transitions": len(self.data)}

    def compute_action(self, obs: np.ndarray) -> int:
        import jax.numpy as jnp
        pi_out, _ = self.model.apply(self.params,
                                     jnp.asarray(obs)[None, :])
        return int(np.argmax(np.asarray(pi_out)[0]))

    def evaluate(self, num_episodes: int = 5, seed: int = 10_000) -> float:
        """Greedy-policy mean episode return in the real env."""
        eps = collect_episodes(self.config.env_name, self.compute_action,
                               num_episodes,
                               env_config=self.config.env_config, seed=seed)
        return float(np.mean([sum(ep["rewards"]) for ep in eps]))


# ---------------------------------------------------------------------------
# CQL — conservative Q-learning from the same JSONL corpora
# ---------------------------------------------------------------------------

class TransitionDataset:
    """(obs, action, reward, next_obs, done) tuples for Q-learning.

    Same JSONL wire shape as OfflineDataset; the episode's last transition
    bootstraps to a terminal next state (done=1)."""

    def __init__(self, obs, actions, rewards, next_obs, dones):
        self.obs = obs
        self.actions = actions
        self.rewards = rewards
        self.next_obs = next_obs
        self.dones = dones

    def __len__(self):
        return len(self.obs)

    @classmethod
    def from_jsonl(cls, path: str) -> "TransitionDataset":
        obs, acts, rews, nxt, dones = [], [], [], [], []
        with open(path) as f:
            for line in f:
                ep = json.loads(line)
                o = np.asarray(ep["obs"], np.float32)
                if len(o) == 0:
                    continue
                obs.append(o)
                acts.append(np.asarray(ep["actions"]))
                rews.append(np.asarray(ep["rewards"], np.float32))
                # next_obs: shift; last step re-uses its own obs but is
                # masked by done=1 so the bootstrap term vanishes
                nxt.append(np.concatenate([o[1:], o[-1:]], axis=0))
                d = np.zeros(len(o), np.float32)
                d[-1] = 1.0
                dones.append(d)
        return cls(np.concatenate(obs), np.concatenate(acts),
                   np.concatenate(rews), np.concatenate(nxt),
                   np.concatenate(dones))


class CQLConfig(MARWILConfig):
    """Conservative Q-Learning (reference: ``rllib/algorithms/cql/cql.py``
    — there SAC-based for continuous control; here the discrete-action
    CQL(H) regime, which is the right regime for the JSONL corpora the
    offline stack ships: the conservative penalty
    ``logsumexp_a Q(s,a) - Q(s, a_data)`` needs no action sampling when
    the action set is enumerable — it's one reduction on the Q head."""

    def __init__(self):
        super().__init__()
        self.cfg.update(
            lr=3e-4, cql_alpha=1.0, target_update_every=100,
            train_batch_size=256, updates_per_iter=100, gamma=0.99,
        )

    def build(self) -> "CQL":
        assert self.env_name and self.input_path, \
            "need .environment(...) and .offline_data(...)"
        return CQL(self)


class CQL:
    """Offline Q-learner: jitted double-Q TD update + conservative penalty."""

    def __init__(self, config: CQLConfig):
        import jax
        import jax.numpy as jnp
        import optax

        import gymnasium as gym

        from .dqn import QNetwork

        self.config = config
        cfg = config.cfg
        env = gym.make(config.env_name, **config.env_config)
        obs_dim = int(np.prod(env.observation_space.shape))
        act_dim = int(env.action_space.n)
        env.close()
        self.model = QNetwork(obs_dim, act_dim, hidden=tuple(cfg["hidden"]))
        self.params = self.model.init(jax.random.PRNGKey(cfg["seed"]))
        self.target_params = jax.tree_util.tree_map(lambda x: x,
                                                    self.params)
        self.opt = optax.chain(optax.clip_by_global_norm(cfg["grad_clip"]),
                               optax.adam(cfg["lr"]))
        self.opt_state = self.opt.init(self.params)
        self.data = TransitionDataset.from_jsonl(config.input_path)
        self._rng = np.random.default_rng(cfg["seed"])
        self.iteration = 0
        self._updates = 0

        gamma = float(cfg["gamma"])
        alpha = float(cfg["cql_alpha"])
        model = self.model

        def loss_fn(params, target_params, obs, actions, rewards,
                    next_obs, dones):
            q = model.apply(params, obs)                        # [B, A]
            q_data = jnp.take_along_axis(q, actions[:, None], 1)[:, 0]
            # double-Q target: select with the online net, evaluate with
            # the target net (overestimation control matters doubly
            # offline — there is no fresh data to correct optimism)
            sel = jnp.argmax(jax.lax.stop_gradient(
                model.apply(params, next_obs)), axis=1)
            next_q = model.apply(target_params, next_obs)
            boot = jnp.take_along_axis(next_q, sel[:, None], 1)[:, 0]
            target = rewards + gamma * (1.0 - dones) * boot
            td = ((q_data - jax.lax.stop_gradient(target)) ** 2).mean()
            # the conservative term: push down the policy's value estimate
            # everywhere, push up only on dataset actions
            gap = (jax.scipy.special.logsumexp(q, axis=1) - q_data).mean()
            return td + alpha * gap, (td, gap)

        @jax.jit
        def update(params, target_params, opt_state, obs, actions,
                   rewards, next_obs, dones):
            (loss, (td, gap)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, obs,
                                       actions, rewards, next_obs, dones)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss, td, gap

        self._update = update
        self._jnp = jnp

    def train(self) -> Dict[str, Any]:
        import jax

        cfg = self.config.cfg
        bs = min(cfg["train_batch_size"], len(self.data))
        losses, tds, gaps = [], [], []
        for _ in range(cfg["updates_per_iter"]):
            idx = self._rng.integers(0, len(self.data), bs)
            (self.params, self.opt_state, loss, td, gap) = self._update(
                self.params, self.target_params, self.opt_state,
                self._jnp.asarray(self.data.obs[idx]),
                self._jnp.asarray(self.data.actions[idx]),
                self._jnp.asarray(self.data.rewards[idx]),
                self._jnp.asarray(self.data.next_obs[idx]),
                self._jnp.asarray(self.data.dones[idx]))
            self._updates += 1
            if self._updates % int(cfg["target_update_every"]) == 0:
                self.target_params = jax.tree_util.tree_map(
                    lambda x: x, self.params)
            losses.append(float(loss))
            tds.append(float(td))
            gaps.append(float(gap))
        self.iteration += 1
        return {"training_iteration": self.iteration,
                "loss": float(np.mean(losses)),
                "td_loss": float(np.mean(tds)),
                "cql_gap": float(np.mean(gaps)),
                "num_transitions": len(self.data)}

    def compute_action(self, obs: np.ndarray) -> int:
        q = self.model.apply(self.params,
                             self._jnp.asarray(obs, self._jnp.float32)[None])
        return int(np.argmax(np.asarray(q)[0]))

    def evaluate(self, num_episodes: int = 5, seed: int = 10_000) -> float:
        eps = collect_episodes(self.config.env_name, self.compute_action,
                               num_episodes,
                               env_config=self.config.env_config, seed=seed)
        return float(np.mean([sum(ep["rewards"]) for ep in eps]))


__all__ = ["BCConfig", "MARWIL", "MARWILConfig", "OfflineDataset",
           "collect_episodes", "write_episodes",
           "CQL", "CQLConfig", "TransitionDataset"]
