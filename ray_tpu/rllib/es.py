"""ES + ARS: derivative-free policy search over an actor fan-out.

Reference: ``rllib/algorithms/es/es.py`` (Salimans et al. evolution
strategies: antithetic gaussian perturbations, centered-rank fitness
shaping, SharedNoiseTable workers) and ``rllib/algorithms/ars/ars.py``
(Augmented Random Search: top-k direction selection, reward-std step
scaling, MeanStdFilter observation normalization).

Design here: instead of shipping a 250MB shared noise table to every
worker (the reference's SharedNoiseTable), workers regenerate each
perturbation from a 64-bit seed — the wire cost per direction is ONE
int + two floats back, and the driver reconstructs the same noise for
the update. The update itself is a single jitted rank-weighted matvec
``theta += lr/(n*sigma) * w @ eps`` on device; evaluation is
embarrassingly parallel over ``num_env_runners`` actors, which is the
whole point of running ES on a cluster.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["ES", "ESConfig", "ARS", "ARSConfig"]


def _noise(seed: int, dim: int) -> np.ndarray:
    """The perturbation for a seed — identical on worker and driver."""
    return np.random.default_rng(seed).standard_normal(dim).astype(np.float32)


def centered_rank(x: np.ndarray) -> np.ndarray:
    """Map fitnesses to centered ranks in [-0.5, 0.5] (fitness shaping:
    makes the update invariant to reward scale and outliers)."""
    flat = x.ravel()
    ranks = np.empty(len(flat), dtype=np.float32)
    ranks[flat.argsort()] = np.arange(len(flat), dtype=np.float32)
    ranks = ranks / (len(flat) - 1) - 0.5
    return ranks.reshape(x.shape)


class _RunningStat:
    """Chan-merge running mean/std for observation filtering (reference:
    ray/rllib/utils/filter.py MeanStdFilter semantics)."""

    def __init__(self, dim: int):
        self.count = 0.0
        self.mean = np.zeros(dim, np.float64)
        self.m2 = np.zeros(dim, np.float64)

    def merge(self, count: float, mean: np.ndarray, m2: np.ndarray):
        if count == 0:
            return
        delta = mean - self.mean
        tot = self.count + count
        self.mean += delta * (count / tot)
        self.m2 += m2 + delta * delta * (self.count * count / tot)
        self.count = tot

    def stats(self) -> Tuple[np.ndarray, np.ndarray]:
        if self.count < 2:
            return self.mean.astype(np.float32), np.ones_like(
                self.mean, dtype=np.float32)
        std = np.sqrt(np.maximum(self.m2 / (self.count - 1), 1e-8))
        return self.mean.astype(np.float32), std.astype(np.float32)


class ESPolicy:
    """Deterministic MLP policy. ES perturbs the flat parameter vector, so
    the policy carries its own flatten/unflatten mapping (ravel_pytree)."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(64, 64),
                 continuous: bool = False, seed: int = 0):
        import jax
        import jax.numpy as jnp
        from jax.flatten_util import ravel_pytree

        self.continuous = continuous
        key = jax.random.PRNGKey(seed)
        sizes = (obs_dim,) + tuple(hidden) + (action_dim,)
        params = []
        for i in range(len(sizes) - 1):
            key, sub = jax.random.split(key)
            w = jax.random.normal(sub, (sizes[i], sizes[i + 1])) \
                / np.sqrt(sizes[i])
            params.append({"w": w, "b": jnp.zeros(sizes[i + 1])})
        flat, self._unravel = ravel_pytree(params)
        self.dim = int(flat.shape[0])
        self.theta0 = np.asarray(flat, np.float32)

        def forward(flat_theta, obs):
            layers = self._unravel(flat_theta)
            h = obs
            for i, lyr in enumerate(layers):
                h = h @ lyr["w"] + lyr["b"]
                if i < len(layers) - 1:
                    h = jnp.tanh(h)
            return h

        self._forward = jax.jit(forward)

    def act(self, theta: np.ndarray, obs: np.ndarray):
        out = np.asarray(self._forward(theta, obs.astype(np.float32)))
        if self.continuous:
            return np.tanh(out)
        return int(out.argmax())


class ESWorker:
    """Evaluation actor: regenerates each direction's noise from its seed,
    rolls the antithetic pair, returns (ret+, ret-, len+, len-) per seed
    plus batched observation statistics for the driver's filter merge."""

    def __init__(self, env_name: str, spec: Dict[str, Any], seed: int = 0,
                 env_config: Optional[dict] = None,
                 episode_horizon: int = 1000):
        import gymnasium as gym

        from . import examples_env  # noqa: F401 — registers Catch-v0
        self.env = gym.make(env_name, **(env_config or {}))
        self.policy = ESPolicy(**spec, seed=seed)
        self.horizon = episode_horizon
        self._ep_seed = seed

    def _rollout(self, theta: np.ndarray, mean: np.ndarray,
                 std: np.ndarray, collect) -> Tuple[float, int]:
        obs, _ = self.env.reset(seed=self._ep_seed)
        self._ep_seed += 1
        total, steps = 0.0, 0
        for _ in range(self.horizon):
            flat = np.asarray(obs, np.float32).ravel()
            if collect is not None:
                collect.append(flat)
            a = self.policy.act(theta, (flat - mean) / std)
            obs, r, term, trunc, _ = self.env.step(a)
            total += float(r)
            steps += 1
            if term or trunc:
                break
        return total, steps

    def evaluate(self, theta_blob, seeds: List[int], sigma: float,
                 mean: np.ndarray, std: np.ndarray) -> Dict[str, Any]:
        theta = np.asarray(theta_blob, np.float32)
        rets, lens, obs_acc = [], [], []
        for s in seeds:
            eps = _noise(int(s), self.policy.dim)
            rp, lp = self._rollout(theta + sigma * eps, mean, std, obs_acc)
            rn, ln = self._rollout(theta - sigma * eps, mean, std, obs_acc)
            rets.append((rp, rn))
            lens.append((lp, ln))
        if obs_acc:
            batch = np.stack(obs_acc).astype(np.float64)
            stats = (float(len(batch)), batch.mean(0),
                     ((batch - batch.mean(0)) ** 2).sum(0))
        else:
            stats = (0.0, 0.0, 0.0)
        return {"returns": np.asarray(rets, np.float32),
                "lengths": np.asarray(lens, np.int64),
                "obs_stats": stats}

    def rollout_current(self, theta_blob, mean, std) -> float:
        """Unperturbed evaluation episode (reference: eval_prob rollouts)."""
        ret, _ = self._rollout(np.asarray(theta_blob, np.float32),
                               mean, std, None)
        return ret

    def ping(self) -> bool:
        return True


class ESConfig:
    """Builder (reference: ESConfig fluent API)."""

    _algo_cls: Optional[type] = None

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 2
        self.cfg: Dict[str, Any] = dict(
            num_perturbations=32,   # antithetic pairs per iteration
            sigma=0.02,             # noise stddev (reference: noise_stdev)
            lr=0.01,                # step size
            l2_coeff=0.005,         # weight decay toward 0
            hidden=(64, 64),
            episode_horizon=1000,
            observation_filter="MeanStdFilter",
            eval_episodes=4,        # unperturbed rollouts per iteration
        )
        self.seed = 0

    def environment(self, env: str, *, env_config: Optional[dict] = None):
        self.env_name = env
        self.env_config = env_config or {}
        return self

    def env_runners(self, num_env_runners: int = 2, **_):
        self.num_env_runners = num_env_runners
        return self

    def training(self, **kwargs):
        self.cfg.update(kwargs)
        return self

    def debugging(self, seed: int = 0):
        self.seed = seed
        return self

    def build(self) -> "ES":
        if not self.env_name:
            raise ValueError("call .environment(env_name) first")
        return (self._algo_cls or ES)(self)


class ES:
    """Driver: seed fan-out -> antithetic evaluation -> jitted rank update.

    ``train()`` returns the usual result dict (episode_return_mean, ...)
    so ES drops into Tune like every other algorithm here.
    """

    def __init__(self, config: ESConfig):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp

        import ray_tpu

        from . import examples_env  # noqa: F401

        self.config = config
        cfg = config.cfg
        probe = gym.make(config.env_name, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        continuous = not hasattr(probe.action_space, "n")
        action_dim = (probe.action_space.shape[0] if continuous
                      else int(probe.action_space.n))
        probe.close()
        self.spec = dict(obs_dim=obs_dim, action_dim=action_dim,
                         hidden=tuple(cfg["hidden"]), continuous=continuous)
        policy = ESPolicy(**self.spec, seed=config.seed)
        self.dim = policy.dim
        self.theta = policy.theta0.copy()
        self._policy = policy
        self.filter = _RunningStat(obs_dim)
        self._use_filter = cfg["observation_filter"] == "MeanStdFilter"
        self._seed_seq = np.random.SeedSequence(config.seed)
        worker_cls = ray_tpu.remote(ESWorker)
        self.workers = [
            worker_cls.options(num_cpus=1).remote(
                config.env_name, self.spec, seed=config.seed + 1000 * i,
                env_config=config.env_config,
                episode_horizon=cfg["episode_horizon"])
            for i in range(config.num_env_runners)]
        self._iteration = 0
        self._timesteps = 0

        lr, l2 = float(cfg["lr"]), float(cfg["l2_coeff"])

        def apply_update(theta, eps, w, denom):
            # rank-weighted matvec + weight decay, one fused XLA program
            g = (w @ eps) / denom
            return theta + lr * g - lr * l2 * theta

        self._apply_update = jax.jit(apply_update)
        self._jnp = jnp

    # -- one iteration -----------------------------------------------------
    def _direction_weights(self, rets: np.ndarray) -> Tuple[np.ndarray,
                                                            np.ndarray,
                                                            float]:
        """ES weighting: centered-rank-shape all 2n returns, weight each
        direction by rank(ret+) - rank(ret-). Returns (weights, used-return
        mask over directions, denominator)."""
        shaped = centered_rank(rets)
        w = shaped[:, 0] - shaped[:, 1]
        n = float(len(rets))
        return w, np.ones(len(rets), bool), n * float(self.config.cfg["sigma"])

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        cfg = self.config.cfg
        t0 = time.time()
        n = int(cfg["num_perturbations"])
        seeds = [int(s.generate_state(1)[0]) for s in
                 self._seed_seq.spawn(n)]
        mean, std = (self.filter.stats() if self._use_filter else
                     (np.zeros(self.spec["obs_dim"], np.float32),
                      np.ones(self.spec["obs_dim"], np.float32)))
        theta_ref = ray_tpu.put(self.theta)
        chunks = np.array_split(np.asarray(seeds), len(self.workers))
        futs = [w.evaluate.remote(theta_ref, [int(x) for x in chunk],
                                  float(cfg["sigma"]), mean, std)
                for w, chunk in zip(self.workers, chunks) if len(chunk)]
        outs = ray_tpu.get(futs, timeout=600)
        rets = np.concatenate([o["returns"] for o in outs])     # [n, 2]
        lens = np.concatenate([o["lengths"] for o in outs])
        if self._use_filter:
            for o in outs:
                c, m, m2 = o["obs_stats"]
                if c:
                    self.filter.merge(c, m, m2)

        w, used, denom = self._direction_weights(rets)
        idx = np.flatnonzero(used)
        eps = np.stack([_noise(seeds[i], self.dim) for i in idx])
        self.theta = np.asarray(self._apply_update(
            self.theta, eps, w[idx].astype(np.float32), float(denom)),
            np.float32)

        # unperturbed evaluation rollouts for the reported return
        eval_rets = ray_tpu.get(
            [self.workers[i % len(self.workers)].rollout_current.remote(
                self.theta, mean, std)
             for i in range(int(cfg["eval_episodes"]))], timeout=600)
        self._iteration += 1
        self._timesteps += int(lens.sum())
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": float(np.mean(eval_rets)),
            "perturbed_return_mean": float(rets.mean()),
            "timesteps_total": self._timesteps,
            "num_perturbations": n,
            "theta_norm": float(np.linalg.norm(self.theta)),
            "time_this_iter_s": time.time() - t0,
        }

    # -- checkpoint surface (Tune trainable protocol) ----------------------
    def get_weights(self) -> Dict[str, Any]:
        return {"theta": self.theta.copy(),
                "filter": (self.filter.count, self.filter.mean.copy(),
                           self.filter.m2.copy())}

    def set_weights(self, blob: Dict[str, Any]):
        self.theta = np.asarray(blob["theta"], np.float32).copy()
        c, m, m2 = blob["filter"]
        self.filter.count = c
        self.filter.mean = np.asarray(m, np.float64).copy()
        self.filter.m2 = np.asarray(m2, np.float64).copy()

    def compute_single_action(self, obs: np.ndarray):
        mean, std = (self.filter.stats() if self._use_filter else
                     (0.0, 1.0))
        flat = np.asarray(obs, np.float32).ravel()
        return self._policy.act(self.theta, (flat - mean) / std)

    def stop(self):
        import ray_tpu
        for w in self.workers:
            ray_tpu.kill(w)


class ARSConfig(ESConfig):
    """ARS (reference: ARSConfig): fewer, bigger steps — top-k direction
    selection and reward-std scaling instead of rank shaping."""

    def __init__(self):
        super().__init__()
        self.cfg.update(
            num_perturbations=16,
            top_k=8,            # reference: num_top_directions
            sigma=0.03,
            lr=0.02,
            l2_coeff=0.0,       # ARS does not regularize
        )


class ARS(ES):
    """ARS-v2: keep the top-k directions by best-of-pair return, step by
    the raw return difference scaled by the std of the used returns."""

    def _direction_weights(self, rets: np.ndarray):
        cfg = self.config.cfg
        k = min(int(cfg.get("top_k", len(rets))), len(rets))
        order = np.argsort(rets.max(axis=1))[::-1][:k]
        used = np.zeros(len(rets), bool)
        used[order] = True
        sigma_r = float(rets[order].std()) + 1e-8
        w = np.zeros(len(rets), np.float32)
        w[order] = rets[order, 0] - rets[order, 1]
        return w, used, k * sigma_r


ESConfig._algo_cls = ES
ARSConfig._algo_cls = ARS
