"""DQN: off-policy Q-learning with replay + target network.

Reference: ``rllib/algorithms/dqn/`` (DQNConfig/DQN over
``algorithms/algorithm.py:191``).  Double-DQN targets and n-step=1
transitions; optional prioritized replay (``replay_buffer.py``).  TPU-first
shape: the whole update — target computation, Huber loss, Adam, soft target
sync — is one jitted program; the ring buffer stays on host and each
sample() is a single device transfer.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np


class QNetwork:
    """MLP Q(s,·) head, same functional pytree style as ActorCriticMLP."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(64, 64)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        sizes = (self.obs_dim,) + self.hidden + (self.action_dim,)
        params = {}
        keys = jax.random.split(key, len(sizes) - 1)
        for i in range(len(sizes) - 1):
            scale = (2.0 / sizes[i]) ** 0.5 if i < len(sizes) - 2 else 0.01
            params[f"w{i}"] = jax.random.normal(
                keys[i], (sizes[i], sizes[i + 1])) * scale
            params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
        return params

    def apply(self, params, obs):
        import jax.numpy as jnp

        x = obs
        n = len(self.hidden)
        for i in range(n):
            x = jnp.maximum(x @ params[f"w{i}"] + params[f"b{i}"], 0.0)
        return x @ params[f"w{n}"] + params[f"b{n}"]


def build_q_model(spec: Dict[str, Any]):
    """Factory over the Q-head family: any distributional/dueling knob
    (``num_atoms``/``v_min``/``v_max``/``dueling``) selects the C51 head
    (rainbow.py, which defaults the others), else the plain QNetwork.
    Both expose ``apply(params, obs) -> [B, A]`` expected-Q, so rollout
    action selection is head-agnostic."""
    if any(k in spec for k in ("num_atoms", "v_min", "v_max", "dueling")):
        from .rainbow import DistQNetwork
        return DistQNetwork(**spec)
    return QNetwork(**spec)


class DQNRunner:
    """Epsilon-greedy rollout actor producing replay transitions."""

    def __init__(self, env_name: str, model_spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0,
                 env_config: Optional[dict] = None):
        import gymnasium as gym
        import jax

        self.envs = [gym.make(env_name, **(env_config or {}))
                     for _ in range(num_envs)]
        self.model = build_q_model(model_spec)
        self._apply = jax.jit(self.model.apply)
        self.num_envs = num_envs
        self._rng = np.random.default_rng(seed)
        self.obs = np.stack([e.reset(seed=seed + i)[0]
                             for i, e in enumerate(self.envs)],
                            dtype=np.float32)
        self._ep_returns = np.zeros(num_envs)
        self._done_returns: List[float] = []

    def sample(self, params_blob, steps: int, epsilon: float,
               n_step: int = 1, gamma: float = 0.99) -> Dict[str, np.ndarray]:
        """Roll out ``steps`` env steps, return n-step transitions.

        Matches the reference's ``n_step`` support (rllib DQNConfig): each
        transition carries the n-step discounted reward sum and a per-sample
        ``discounts`` factor (gamma^k, zeroed at termination) so the learner's
        bootstrap term is simply ``R + discounts * maxQ(next_obs)`` — no
        special-casing of terminal vs truncated vs window-clipped samples.
        """
        import jax
        import jax.numpy as jnp

        params = jax.tree_util.tree_map(jnp.asarray, params_blob)
        N = self.num_envs
        T = max(1, steps // N)
        shp = self.obs.shape[1:]
        obs_t = np.zeros((T, N) + shp, np.float32)
        act_t = np.zeros((T, N), np.int32)
        rew_t = np.zeros((T, N), np.float32)
        nobs_t = np.zeros((T, N) + shp, np.float32)
        term_t = np.zeros((T, N), bool)
        stop_t = np.zeros((T, N), bool)  # term OR trunc: n-step window ends
        for t in range(T):
            q = np.asarray(self._apply(params, jnp.asarray(self.obs)))
            greedy = q.argmax(axis=-1)
            explore = self._rng.random(N) < epsilon
            random_a = self._rng.integers(0, q.shape[-1], N)
            actions = np.where(explore, random_a, greedy)
            for i, env in enumerate(self.envs):
                nobs, rew, term, trunc, _ = env.step(int(actions[i]))
                obs_t[t, i] = self.obs[i]
                act_t[t, i] = actions[i]
                rew_t[t, i] = rew
                # The stored successor must be the ACTUAL next observation
                # from env.step — at truncation the TD target still
                # bootstraps from it, so record it before any reset
                # replaces it with a fresh episode's initial obs.
                nobs_t[t, i] = np.asarray(nobs, np.float32)
                term_t[t, i] = term
                stop_t[t, i] = term or trunc
                self._ep_returns[i] += rew
                if term or trunc:
                    self._done_returns.append(self._ep_returns[i])
                    self._ep_returns[i] = 0.0
                    nobs, _ = env.reset()
                self.obs[i] = np.asarray(nobs, np.float32)
        # n-step aggregation per env column (windows never cross episode
        # boundaries; windows clipped by the rollout end bootstrap early
        # with discount gamma^k, k < n).
        out = {
            "obs": obs_t.reshape((T * N,) + shp),
            "actions": act_t.reshape(-1),
            "rewards": np.zeros((T * N,), np.float32),
            "next_obs": np.zeros((T * N,) + shp, np.float32),
            "discounts": np.zeros((T * N,), np.float32),
        }
        k = 0
        for t in range(T):
            for i in range(N):
                acc, g = 0.0, 1.0
                j = t
                while True:
                    acc += g * rew_t[j, i]
                    g *= gamma
                    if stop_t[j, i] or j - t + 1 >= n_step or j + 1 >= T:
                        break
                    j += 1
                out["rewards"][k] = acc
                out["next_obs"][k] = nobs_t[j, i]
                out["discounts"][k] = 0.0 if term_t[j, i] else g
                k += 1
        return out

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._done_returns)
        if clear:
            self._done_returns.clear()
        return out

    def ping(self) -> bool:
        return True


class DQNConfig:
    """Builder (reference: DQNConfig fluent API)."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 1
        self.num_envs_per_runner = 1
        self.rollout_steps = 256          # env steps sampled per iteration
        self.train: Dict[str, Any] = dict(
            lr=1e-3, gamma=0.99, batch_size=128, train_iters=8,
            target_update_tau=0.01, double_q=True, huber_delta=1.0,
            n_step=1)
        self.model: Dict[str, Any] = dict(hidden=(64, 64))
        self.replay: Dict[str, Any] = dict(
            capacity=50_000, prioritized=False, alpha=0.6, beta=0.4,
            learn_starts=1_000)
        self.exploration: Dict[str, Any] = dict(
            epsilon_start=1.0, epsilon_end=0.05, epsilon_decay_steps=10_000)
        self.seed = 0

    def environment(self, env: str, *, env_config: Optional[dict] = None):
        self.env_name = env
        self.env_config = env_config or {}
        return self

    def env_runners(self, num_env_runners: int = 1,
                    num_envs_per_env_runner: int = 1,
                    rollout_steps: int = 256):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_steps = rollout_steps
        return self

    def training(self, **kwargs):
        model = kwargs.pop("model", None)
        if model:
            self.model.update(model)
        replay = kwargs.pop("replay", None)
        if replay:
            self.replay.update(replay)
        self.train.update(kwargs)
        return self

    def exploring(self, **kwargs):
        self.exploration.update(kwargs)
        return self

    def debugging(self, seed: int = 0):
        self.seed = seed
        return self

    def build(self) -> "DQN":
        if not self.env_name:
            raise ValueError("call .environment(env_name) first")
        return DQN(self)


class DQN:
    """Driver: epsilon-greedy sampling -> replay -> compiled double-DQN update."""

    def __init__(self, config: DQNConfig):
        import gymnasium as gym
        import jax

        import ray_tpu

        self.config = config
        probe = gym.make(config.env_name, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        action_dim = int(probe.action_space.n)
        probe.close()
        self.model_spec = dict(obs_dim=obs_dim, action_dim=action_dim,
                               hidden=tuple(config.model["hidden"]))
        for k in ("num_atoms", "v_min", "v_max", "dueling"):
            if k in config.model:  # distributional/dueling heads (rainbow)
                self.model_spec[k] = config.model[k]
        self.model = build_q_model(self.model_spec)
        self.params = self.model.init(jax.random.PRNGKey(config.seed))
        self.target_params = jax.tree_util.tree_map(lambda x: x, self.params)

        import optax
        self.opt = optax.adam(config.train["lr"])
        self.opt_state = self.opt.init(self.params)
        self._update = self._build_update()

        self.buffer = self._make_buffer()

        runner_cls = ray_tpu.remote(DQNRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, self.model_spec,
                num_envs=config.num_envs_per_runner,
                seed=config.seed + 1000 * i,
                env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self._iteration = 0
        self._env_steps = 0
        self._recent_returns: List[float] = []

    def _make_buffer(self):
        """Driver-side replay; APEX overrides to None (its replay tier
        lives in shard actors — allocating here would be wasted)."""
        from .replay_buffer import PrioritizedReplayBuffer, ReplayBuffer

        r = self.config.replay
        if r.get("prioritized"):
            return PrioritizedReplayBuffer(r["capacity"], alpha=r["alpha"],
                                           beta=r["beta"],
                                           seed=self.config.seed)
        return ReplayBuffer(r["capacity"], seed=self.config.seed)

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config.train
        tau = cfg["target_update_tau"]
        double_q = cfg["double_q"]
        delta = cfg["huber_delta"]
        model = self.model

        def loss_fn(params, target_params, batch):
            q = model.apply(params, batch["obs"])
            qa = jnp.take_along_axis(
                q, batch["actions"][:, None].astype(jnp.int32), axis=-1)[:, 0]
            q_next_t = model.apply(target_params, batch["next_obs"])
            if double_q:
                q_next_o = model.apply(params, batch["next_obs"])
                next_a = q_next_o.argmax(axis=-1)
                q_next = jnp.take_along_axis(
                    q_next_t, next_a[:, None], axis=-1)[:, 0]
            else:
                q_next = q_next_t.max(axis=-1)
            # discounts = gamma^k with 0 at termination (computed by the
            # runner's n-step aggregation), so one expression covers 1-step,
            # n-step, terminal, and truncation-bootstrapped samples.
            target = batch["rewards"] + batch["discounts"] * q_next
            td = qa - jax.lax.stop_gradient(target)
            huber = jnp.where(jnp.abs(td) <= delta, 0.5 * td ** 2,
                              delta * (jnp.abs(td) - 0.5 * delta))
            w = batch.get("weights", jnp.ones_like(td))
            return (w * huber).mean(), td

        def update(params, target_params, opt_state, batch):
            (loss, td), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = self.opt.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params,
                                            updates)
            target_params = jax.tree_util.tree_map(
                lambda t, p: (1 - tau) * t + tau * p, target_params, params)
            return params, target_params, opt_state, loss, td

        return jax.jit(update)

    def _epsilon(self) -> float:
        e = self.config.exploration
        frac = min(1.0, self._env_steps / max(1, e["epsilon_decay_steps"]))
        return e["epsilon_start"] + frac * (e["epsilon_end"]
                                            - e["epsilon_start"])

    def train(self) -> Dict[str, Any]:
        import jax.numpy as jnp

        import ray_tpu

        t0 = time.time()
        cfg = self.config
        eps = self._epsilon()
        weights_ref = ray_tpu.put(
            {k: np.asarray(v) for k, v in self.params.items()})
        per_runner = max(1, cfg.rollout_steps // cfg.num_env_runners)
        batches = ray_tpu.get(
            [r.sample.remote(weights_ref, per_runner, eps,
                             cfg.train["n_step"], cfg.train["gamma"])
             for r in self.runners], timeout=600)
        for b in batches:
            self.buffer.add(b)
            self._env_steps += len(b["rewards"])

        losses = []
        if len(self.buffer) >= cfg.replay["learn_starts"]:
            for _ in range(cfg.train["train_iters"]):
                sample = self.buffer.sample(cfg.train["batch_size"])
                batch = {
                    "obs": jnp.asarray(sample["obs"]),
                    "actions": jnp.asarray(sample["actions"]),
                    "rewards": jnp.asarray(sample["rewards"]),
                    "next_obs": jnp.asarray(sample["next_obs"]),
                    "discounts": jnp.asarray(sample["discounts"]),
                }
                if "_weights" in sample:
                    batch["weights"] = jnp.asarray(sample["_weights"])
                (self.params, self.target_params, self.opt_state, loss,
                 td) = self._update(self.params, self.target_params,
                                    self.opt_state, batch)
                self.buffer.update_priorities(sample["_indices"],
                                              np.asarray(td))
                losses.append(float(loss))

        rets = [x for r in self.runners
                for x in ray_tpu.get(r.episode_returns.remote(), timeout=60)]
        self._recent_returns.extend(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "episodes_this_iter": len(rets),
            "num_env_steps_sampled": self._env_steps,
            "epsilon": eps,
            "loss": float(np.mean(losses)) if losses else float("nan"),
            "replay_size": len(self.buffer),
            "time_this_iter_s": time.time() - t0,
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def get_weights(self):
        return {k: np.asarray(v) for k, v in self.params.items()}
