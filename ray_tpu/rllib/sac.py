"""SAC: off-policy maximum-entropy actor-critic for continuous control.

Reference: ``rllib/algorithms/sac/`` (SACConfig/SAC over
``algorithms/algorithm.py:191``).  Twin Q networks with target smoothing,
a tanh-squashed Gaussian policy, and automatic entropy-temperature tuning
(the three standard SAC components).  TPU-first shape: the whole update —
twin-critic targets, actor reparameterized gradient, alpha step, soft
target sync — is ONE jitted program; rollouts ride the same remote-runner
pattern as DQN with replay on the driver.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

import numpy as np

LOG_STD_MIN, LOG_STD_MAX = -10.0, 2.0


class SquashedGaussianPolicy:
    """MLP -> (mean, log_std) -> tanh-squashed action in [-1, 1]^A."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(256, 256)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        sizes = (self.obs_dim,) + self.hidden + (2 * self.action_dim,)
        params = {}
        keys = jax.random.split(key, len(sizes))
        for i in range(len(sizes) - 1):
            scale = (2.0 / sizes[i]) ** 0.5 if i < len(sizes) - 2 else 0.01
            params[f"w{i}"] = jax.random.normal(
                keys[i], (sizes[i], sizes[i + 1])) * scale
            params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
        return params

    def forward(self, params, obs):
        import jax.numpy as jnp

        x = obs
        n = len(self.hidden)
        for i in range(n):
            x = jnp.maximum(x @ params[f"w{i}"] + params[f"b{i}"], 0.0)
        out = x @ params[f"w{n}"] + params[f"b{n}"]
        mean, log_std = jnp.split(out, 2, axis=-1)
        return mean, jnp.clip(log_std, LOG_STD_MIN, LOG_STD_MAX)

    def sample(self, params, obs, key):
        """Reparameterized squashed sample -> (action, log_prob)."""
        import jax
        import jax.numpy as jnp

        mean, log_std = self.forward(params, obs)
        std = jnp.exp(log_std)
        eps = jax.random.normal(key, mean.shape)
        pre = mean + std * eps
        act = jnp.tanh(pre)
        # log prob with tanh change-of-variables (numerically stable form)
        lp = (-0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))).sum(-1)
        lp -= (2 * (jnp.log(2.0) - pre - jax.nn.softplus(-2 * pre))).sum(-1)
        return act, lp


class QNetworkSA:
    """Q(s, a) MLP (concatenated input)."""

    def __init__(self, obs_dim: int, action_dim: int, hidden=(256, 256)):
        self.obs_dim = obs_dim
        self.action_dim = action_dim
        self.hidden = tuple(hidden)

    def init(self, key):
        import jax
        import jax.numpy as jnp

        sizes = (self.obs_dim + self.action_dim,) + self.hidden + (1,)
        params = {}
        keys = jax.random.split(key, len(sizes))
        for i in range(len(sizes) - 1):
            scale = (2.0 / sizes[i]) ** 0.5
            params[f"w{i}"] = jax.random.normal(
                keys[i], (sizes[i], sizes[i + 1])) * scale
            params[f"b{i}"] = jnp.zeros((sizes[i + 1],))
        return params

    def apply(self, params, obs, act):
        import jax.numpy as jnp

        x = jnp.concatenate([obs, act], axis=-1)
        n = len(self.hidden)
        for i in range(n):
            x = jnp.maximum(x @ params[f"w{i}"] + params[f"b{i}"], 0.0)
        return (x @ params[f"w{n}"] + params[f"b{n}"])[..., 0]


class SACRunner:
    """Rollout actor: squashed-Gaussian exploration, env-scaled actions."""

    def __init__(self, env_name: str, spec: Dict[str, Any],
                 num_envs: int = 1, seed: int = 0,
                 env_config: Optional[dict] = None):
        import gymnasium as gym
        import jax

        self.envs = [gym.make(env_name, **(env_config or {}))
                     for _ in range(num_envs)]
        self.policy = SquashedGaussianPolicy(**spec)
        self._sample = jax.jit(self.policy.sample)
        self.num_envs = num_envs
        space = self.envs[0].action_space
        self.act_low = np.asarray(space.low, np.float32)
        self.act_high = np.asarray(space.high, np.float32)
        self._seed = seed
        self._calls = 0
        self.obs = np.stack([e.reset(seed=seed + i)[0]
                             for i, e in enumerate(self.envs)],
                            dtype=np.float32)
        self._ep_returns = np.zeros(num_envs)
        self._done_returns: List[float] = []

    def _scale(self, a: np.ndarray) -> np.ndarray:
        return self.act_low + (a + 1.0) * 0.5 * (self.act_high - self.act_low)

    def sample(self, params_blob, steps: int, random_actions: bool = False
               ) -> Dict[str, np.ndarray]:
        import jax
        import jax.numpy as jnp

        params = jax.tree_util.tree_map(jnp.asarray, params_blob)
        N = self.num_envs
        T = max(1, steps // N)
        A = self.policy.action_dim
        rng = np.random.default_rng(self._seed * 7919 + self._calls)
        self._calls += 1
        buf = {
            "obs": np.zeros((T * N,) + self.obs.shape[1:], np.float32),
            "actions": np.zeros((T * N, A), np.float32),
            "rewards": np.zeros((T * N,), np.float32),
            "next_obs": np.zeros((T * N,) + self.obs.shape[1:], np.float32),
            "dones": np.zeros((T * N,), np.float32),
        }
        k = 0
        for t in range(T):
            if random_actions:  # warmup: uniform in the squashed range
                acts = rng.uniform(-1, 1, (N, A)).astype(np.float32)
            else:
                key = jax.random.PRNGKey((self._seed << 18) ^ self._calls
                                         ^ (t << 1))
                acts, _ = self._sample(params, jnp.asarray(self.obs), key)
                acts = np.asarray(acts)
            for i, env in enumerate(self.envs):
                nobs, rew, term, trunc, _ = env.step(self._scale(acts[i]))
                buf["obs"][k] = self.obs[i]
                buf["actions"][k] = acts[i]
                buf["rewards"][k] = rew
                buf["next_obs"][k] = np.asarray(nobs, np.float32)
                buf["dones"][k] = float(term)
                self._ep_returns[i] += rew
                if term or trunc:
                    self._done_returns.append(self._ep_returns[i])
                    self._ep_returns[i] = 0.0
                    nobs, _ = env.reset()
                self.obs[i] = np.asarray(nobs, np.float32)
                k += 1
        return buf

    def episode_returns(self, clear: bool = True) -> List[float]:
        out = list(self._done_returns)
        if clear:
            self._done_returns.clear()
        return out

    def ping(self) -> bool:
        return True


class SACConfig:
    """Builder (reference: SACConfig fluent API)."""

    def __init__(self):
        self.env_name: Optional[str] = None
        self.env_config: Dict[str, Any] = {}
        self.num_env_runners = 1
        self.num_envs_per_runner = 1
        self.rollout_steps = 256
        self.train: Dict[str, Any] = dict(
            actor_lr=3e-4, critic_lr=3e-4, alpha_lr=3e-4, gamma=0.99,
            tau=0.005, batch_size=256, train_iters=8,
            target_entropy=None, init_alpha=0.1)
        self.model: Dict[str, Any] = dict(hidden=(256, 256))
        self.replay: Dict[str, Any] = dict(capacity=100_000,
                                           learn_starts=1_000,
                                           random_warmup=True)
        self.seed = 0

    def environment(self, env: str, *, env_config: Optional[dict] = None):
        self.env_name = env
        self.env_config = env_config or {}
        return self

    def env_runners(self, num_env_runners: int = 1,
                    num_envs_per_env_runner: int = 1,
                    rollout_steps: int = 256):
        self.num_env_runners = num_env_runners
        self.num_envs_per_runner = num_envs_per_env_runner
        self.rollout_steps = rollout_steps
        return self

    def training(self, **kwargs):
        model = kwargs.pop("model", None)
        if model:
            self.model.update(model)
        replay = kwargs.pop("replay", None)
        if replay:
            self.replay.update(replay)
        self.train.update(kwargs)
        return self

    def debugging(self, seed: int = 0):
        self.seed = seed
        return self

    def build(self) -> "SAC":
        if not self.env_name:
            raise ValueError("call .environment(env_name) first")
        return SAC(self)


class SAC:
    """Driver: stochastic rollouts -> replay -> one compiled SAC update."""

    def __init__(self, config: SACConfig):
        import gymnasium as gym
        import jax

        import ray_tpu

        from .replay_buffer import ReplayBuffer

        self.config = config
        probe = gym.make(config.env_name, **config.env_config)
        obs_dim = int(np.prod(probe.observation_space.shape))
        action_dim = int(np.prod(probe.action_space.shape))
        probe.close()
        hidden = tuple(config.model["hidden"])
        self.spec = dict(obs_dim=obs_dim, action_dim=action_dim,
                         hidden=hidden)
        self.policy = SquashedGaussianPolicy(**self.spec)
        self.q1 = QNetworkSA(obs_dim, action_dim, hidden)
        self.q2 = QNetworkSA(obs_dim, action_dim, hidden)
        k = jax.random.split(jax.random.PRNGKey(config.seed), 3)
        import jax.numpy as jnp

        import optax
        self.state = {
            "pi": self.policy.init(k[0]),
            "q1": self.q1.init(k[1]),
            "q2": self.q2.init(k[2]),
            "log_alpha": jnp.asarray(
                np.log(config.train["init_alpha"]), jnp.float32),
        }
        self.state["q1_t"] = jax.tree_util.tree_map(lambda x: x,
                                                    self.state["q1"])
        self.state["q2_t"] = jax.tree_util.tree_map(lambda x: x,
                                                    self.state["q2"])
        t = config.train
        self.opt = {
            "pi": optax.adam(t["actor_lr"]),
            "q": optax.adam(t["critic_lr"]),
            "alpha": optax.adam(t["alpha_lr"]),
        }
        self.opt_state = {
            "pi": self.opt["pi"].init(self.state["pi"]),
            "q": self.opt["q"].init((self.state["q1"], self.state["q2"])),
            "alpha": self.opt["alpha"].init(self.state["log_alpha"]),
        }
        self.target_entropy = (t["target_entropy"]
                               if t["target_entropy"] is not None
                               else -float(action_dim))
        self._update = self._build_update()
        self.buffer = ReplayBuffer(config.replay["capacity"],
                                   seed=config.seed)
        runner_cls = ray_tpu.remote(SACRunner)
        self.runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, self.spec,
                num_envs=config.num_envs_per_runner,
                seed=config.seed + 1000 * i,
                env_config=config.env_config)
            for i in range(config.num_env_runners)]
        self._iteration = 0
        self._env_steps = 0
        self._recent_returns: List[float] = []

    def _build_update(self):
        import jax
        import jax.numpy as jnp

        cfg = self.config.train
        gamma, tau = cfg["gamma"], cfg["tau"]
        policy, q1, q2 = self.policy, self.q1, self.q2
        target_entropy = self.target_entropy
        opt = self.opt

        def update(state, opt_state, batch, key):
            k1, k2 = jax.random.split(key)
            alpha = jnp.exp(state["log_alpha"])

            # --- critics
            next_a, next_lp = policy.sample(state["pi"], batch["next_obs"],
                                            k1)
            q_next = jnp.minimum(
                q1.apply(state["q1_t"], batch["next_obs"], next_a),
                q2.apply(state["q2_t"], batch["next_obs"], next_a))
            target = batch["rewards"] + gamma * (1 - batch["dones"]) * (
                q_next - alpha * next_lp)
            target = jax.lax.stop_gradient(target)

            def critic_loss(qs):
                p1, p2 = qs
                e1 = q1.apply(p1, batch["obs"], batch["actions"]) - target
                e2 = q2.apply(p2, batch["obs"], batch["actions"]) - target
                return (e1 ** 2).mean() + (e2 ** 2).mean()

            closs, cgrads = jax.value_and_grad(critic_loss)(
                (state["q1"], state["q2"]))
            cup, q_opt = opt["q"].update(cgrads, opt_state["q"],
                                         (state["q1"], state["q2"]))
            new_q1, new_q2 = jax.tree_util.tree_map(
                lambda p, u: p + u, (state["q1"], state["q2"]), cup)

            # --- actor (reparameterized)
            def actor_loss(pi_params):
                a, lp = policy.sample(pi_params, batch["obs"], k2)
                q = jnp.minimum(q1.apply(new_q1, batch["obs"], a),
                                q2.apply(new_q2, batch["obs"], a))
                return (alpha * lp - q).mean(), lp

            (aloss, lp), agrads = jax.value_and_grad(
                actor_loss, has_aux=True)(state["pi"])
            aup, pi_opt = opt["pi"].update(agrads, opt_state["pi"],
                                           state["pi"])
            new_pi = jax.tree_util.tree_map(lambda p, u: p + u,
                                            state["pi"], aup)

            # --- temperature
            def alpha_loss(log_alpha):
                return -(jnp.exp(log_alpha)
                         * jax.lax.stop_gradient(lp + target_entropy)).mean()

            _, algrad = jax.value_and_grad(alpha_loss)(state["log_alpha"])
            alup, al_opt = opt["alpha"].update(algrad, opt_state["alpha"],
                                               state["log_alpha"])
            new_log_alpha = state["log_alpha"] + alup

            new_state = {
                "pi": new_pi, "q1": new_q1, "q2": new_q2,
                "log_alpha": new_log_alpha,
                "q1_t": jax.tree_util.tree_map(
                    lambda t_, p: (1 - tau) * t_ + tau * p,
                    state["q1_t"], new_q1),
                "q2_t": jax.tree_util.tree_map(
                    lambda t_, p: (1 - tau) * t_ + tau * p,
                    state["q2_t"], new_q2),
            }
            new_opt = {"pi": pi_opt, "q": q_opt, "alpha": al_opt}
            return new_state, new_opt, closs, aloss, alpha

        import jax
        return jax.jit(update)

    def train(self) -> Dict[str, Any]:
        import jax
        import jax.numpy as jnp

        import ray_tpu

        t0 = time.time()
        cfg = self.config
        warm = (cfg.replay.get("random_warmup", True)
                and self._env_steps < cfg.replay["learn_starts"])
        weights_ref = ray_tpu.put(jax.tree_util.tree_map(
            np.asarray, self.state["pi"]))
        per_runner = max(1, cfg.rollout_steps // cfg.num_env_runners)
        batches = ray_tpu.get(
            [r.sample.remote(weights_ref, per_runner, warm)
             for r in self.runners], timeout=600)
        for b in batches:
            self.buffer.add(b)
            self._env_steps += len(b["rewards"])

        closs = aloss = alpha_v = float("nan")
        if len(self.buffer) >= cfg.replay["learn_starts"]:
            for j in range(cfg.train["train_iters"]):
                s = self.buffer.sample(cfg.train["batch_size"])
                batch = {k: jnp.asarray(v) for k, v in s.items()
                         if not k.startswith("_")}
                key = jax.random.fold_in(
                    jax.random.PRNGKey(cfg.seed), self._iteration * 131 + j)
                (self.state, self.opt_state, closs, aloss,
                 alpha_v) = self._update(self.state, self.opt_state, batch,
                                         key)
            closs, aloss, alpha_v = (float(closs), float(aloss),
                                     float(alpha_v))

        rets = [x for r in self.runners
                for x in ray_tpu.get(r.episode_returns.remote(), timeout=60)]
        self._recent_returns.extend(rets)
        self._recent_returns = self._recent_returns[-100:]
        self._iteration += 1
        return {
            "training_iteration": self._iteration,
            "episode_return_mean": (float(np.mean(self._recent_returns))
                                    if self._recent_returns else float("nan")),
            "episodes_this_iter": len(rets),
            "num_env_steps_sampled": self._env_steps,
            "critic_loss": closs, "actor_loss": aloss, "alpha": alpha_v,
            "replay_size": len(self.buffer),
            "time_this_iter_s": time.time() - t0,
        }

    def stop(self):
        import ray_tpu

        for r in self.runners:
            try:
                ray_tpu.kill(r)
            except Exception:
                pass

    def get_weights(self):
        import jax
        return jax.tree_util.tree_map(np.asarray, self.state["pi"])
