"""Replay buffers for off-policy algorithms.

Reference: ``rllib/utils/replay_buffers/`` (``ReplayBuffer``,
``PrioritizedEpisodeReplayBuffer``).  Stored as flat numpy ring buffers so a
whole sample() lands in one host->device transfer for the compiled update;
prioritized sampling uses a segment tree over priorities like the reference
(and the PER paper), with O(log n) updates.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class ReplayBuffer:
    """Uniform ring-buffer replay of transition dicts."""

    def __init__(self, capacity: int, seed: int = 0):
        self.capacity = int(capacity)
        self._store: Optional[Dict[str, np.ndarray]] = None
        self._next = 0
        self._size = 0
        self._rng = np.random.default_rng(seed)

    def __len__(self) -> int:
        return self._size

    def add(self, batch: Dict[str, np.ndarray]):
        """Append a batch of transitions; each value is [B, ...]."""
        n = len(next(iter(batch.values())))
        if self._store is None:
            self._store = {
                k: np.zeros((self.capacity,) + np.asarray(v).shape[1:],
                            np.asarray(v).dtype)
                for k, v in batch.items()}
        idx = (self._next + np.arange(n)) % self.capacity
        for k, v in batch.items():
            self._store[k][idx] = v
        self._next = (self._next + n) % self.capacity
        self._size = min(self._size + n, self.capacity)
        return idx

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, batch_size)
        out = {k: v[idx] for k, v in self._store.items()}
        out["_indices"] = idx
        return out

    def update_priorities(self, indices, priorities):
        pass  # uniform buffer: no-op (keeps the caller generic)


class _SumTree:
    """Binary-indexed segment tree: prefix-sum sampling in O(log n)."""

    def __init__(self, capacity: int):
        self.n = 1
        while self.n < capacity:
            self.n *= 2
        self.tree = np.zeros(2 * self.n, np.float64)

    def set(self, idx: int, value: float):
        i = self.n + idx
        self.tree[i] = value
        i //= 2
        while i >= 1:
            self.tree[i] = self.tree[2 * i] + self.tree[2 * i + 1]
            i //= 2

    def total(self) -> float:
        return float(self.tree[1])

    def find(self, prefix: float) -> int:
        i = 1
        while i < self.n:
            left = self.tree[2 * i]
            if prefix < left:
                i = 2 * i
            else:
                prefix -= left
                i = 2 * i + 1
        return i - self.n


class PrioritizedReplayBuffer(ReplayBuffer):
    """Proportional prioritized replay (PER): P(i) ∝ p_i^alpha, with
    importance weights (1/(N·P(i)))^beta returned per sample."""

    def __init__(self, capacity: int, alpha: float = 0.6, beta: float = 0.4,
                 seed: int = 0):
        super().__init__(capacity, seed)
        self.alpha = alpha
        self.beta = beta
        self._tree = _SumTree(self.capacity)
        self._max_prio = 1.0

    def add(self, batch: Dict[str, np.ndarray]):
        idx = super().add(batch)
        for i in idx:
            self._tree.set(int(i), self._max_prio ** self.alpha)
        return idx

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        total = self._tree.total()
        # stratified: one draw per equal-mass segment
        bounds = np.linspace(0, total, batch_size + 1)
        draws = self._rng.uniform(bounds[:-1], bounds[1:])
        idx = np.array([min(self._tree.find(d), self._size - 1)
                        for d in draws])
        probs = np.array([max(self._tree.tree[self._tree.n + i], 1e-12)
                          for i in idx]) / max(total, 1e-12)
        weights = (self._size * probs) ** (-self.beta)
        weights /= weights.max()
        out = {k: v[idx] for k, v in self._store.items()}
        out["_indices"] = idx
        out["_weights"] = weights.astype(np.float32)
        return out

    def update_priorities(self, indices, priorities):
        for i, p in zip(np.asarray(indices), np.asarray(priorities)):
            p = float(abs(p)) + 1e-6
            self._max_prio = max(self._max_prio, p)
            self._tree.set(int(i), p ** self.alpha)
