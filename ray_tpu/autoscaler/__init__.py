"""ray_tpu.autoscaler — demand-driven cluster scaling.

Reference: ``python/ray/autoscaler/_private/autoscaler.py:166``
(StandardAutoscaler), ``monitor.py`` (the polling process),
``resource_demand_scheduler.py`` (bin-packing pending demands onto node
types), ``node_provider.py:13`` (the cloud plugin ABC).

TPU angle: node types carry ``tpu_slice``/``ici_coord`` labels so scaled-up
nodes land in the topology-aware placement path (core/scheduling.py); a
GCE/QR provider plugs in through the same NodeProvider ABC the local
subprocess provider implements.
"""

from .autoscaler import AutoscalerConfig, NodeType, StandardAutoscaler
from .providers import LocalNodeProvider, NodeProvider

__all__ = ["StandardAutoscaler", "AutoscalerConfig", "NodeType",
           "NodeProvider", "LocalNodeProvider"]
