"""GCE TPU-pod node provider: provision TPU VM slices via queued resources.

Reference: ``python/ray/autoscaler/_private/gcp/node_provider.py`` (+
``config.py`` bootstrapping and the TPU pod support in
``_private/gcp/node.py:GCPTPUNode``).  This provider implements the same
``NodeProvider`` surface against the Cloud TPU API (``tpu.googleapis.com``),
with two TPU-specific behaviors the reference's GCE path lacks:

* **Queued resources** (`projects.locations.queuedResources`): TPU capacity
  is usually obtained through the QR queue, not direct ``nodes.create`` —
  a create returns immediately and the slice materializes when capacity
  frees up (state WAITING_FOR_RESOURCES -> PROVISIONING -> ACTIVE).
  ``create_node`` submits a QR and returns the QR id as the provider id;
  ``non_terminated_nodes`` reports ids whose QR/node is still live, so the
  autoscaler's bin-packing counts in-flight capacity and does not
  double-request (the reference achieves the same with its
  ``pending_launches`` counter).
* **Reservations**: pass ``reserved=True`` in the node type to consume a
  capacity reservation instead of on-demand quota.

Transport is injectable: tests (and this repo's zero-egress CI) pass a fake
``transport(method, url, body) -> dict``; production uses urllib with a
metadata-server OAuth token.  No GCP SDK dependency.
"""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from .providers import NodeProvider

_TPU_API = "https://tpu.googleapis.com/v2"
_METADATA_TOKEN_URL = ("http://metadata.google.internal/computeMetadata/v1/"
                       "instance/service-accounts/default/token")

# QR states that still hold (or may yet yield) capacity — anything else is
# terminal and the id disappears from non_terminated_nodes.
_LIVE_QR_STATES = {"ACCEPTED", "WAITING_FOR_RESOURCES", "PROVISIONING",
                   "ACTIVE", "CREATING"}


def _default_transport(method: str, url: str, body: Optional[dict]) -> dict:
    """urllib transport with metadata-server auth (runs on a GCP VM)."""
    import urllib.request

    tok_req = urllib.request.Request(_METADATA_TOKEN_URL,
                                     headers={"Metadata-Flavor": "Google"})
    with urllib.request.urlopen(tok_req, timeout=10) as r:
        token = json.loads(r.read())["access_token"]
    req = urllib.request.Request(
        url, method=method,
        data=json.dumps(body).encode() if body is not None else None,
        headers={"Authorization": f"Bearer {token}",
                 "Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as r:
        payload = r.read()
    return json.loads(payload) if payload else {}


class GCETpuNodeProvider(NodeProvider):
    """Provision TPU VM slices as cluster nodes via queued resources.

    ``node_types`` entries (autoscaler config "available_node_types"):

    .. code-block:: python

        {"tpu_v5e_8": {
            "resources": {"CPU": 8, "TPU": 8},
            "accelerator_type": "v5litepod-8",
            "runtime_version": "tpu-vm-tf-2.16.1-pjrt",
            "reserved": False,          # use a reservation?
            "spot": False,              # preemptible capacity?
            "labels": {"tpu_slice": "v5e-8"},
        }}
    """

    def __init__(self, gcs_address: str, node_types: Dict[str, dict],
                 project: str = "", zone: str = "",
                 transport: Optional[Callable[..., dict]] = None,
                 cluster_name: str = "raytpu",
                 poll_interval_s: float = 5.0):
        if not project or not zone:
            raise ValueError("GCETpuNodeProvider requires project and zone")
        self.gcs_address = gcs_address
        self.node_types = node_types
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.poll_interval_s = poll_interval_s
        self._transport = transport or _default_transport
        self._parent = f"projects/{project}/locations/{zone}"
        # provider id -> {"qr_name":…, "node_name":…, "node_type":…}
        self._nodes: Dict[str, Dict[str, Any]] = {}

    # ------------------------------------------------------------- provider

    def create_node(self, node_type: str, labels: Dict[str, str]) -> str:
        spec = self.node_types[node_type]
        pid = f"qr-{uuid.uuid4().hex[:10]}"
        node_name = f"{self.cluster_name}-{node_type}-{pid[3:]}"
        all_labels = dict(spec.get("labels", {}))
        all_labels.update(labels)
        all_labels["raytpu-cluster"] = self.cluster_name
        # The provider id rides into the node's cluster labels via the boot
        # script; the autoscaler matches it back to map provider id ->
        # cluster node id (enables idle drain + zombie cleanup).
        all_labels["raytpu-provider-id"] = pid
        # The boot script joins the slice to the cluster exactly like a
        # manually-started worker node (raytpu start --address=GCS).
        startup = ("#! /bin/bash\n"
                   f"raytpu start --address={self.gcs_address} "
                   f"--labels='{json.dumps(all_labels)}'\n")
        node_body = {
            "acceleratorType": spec["accelerator_type"],
            "runtimeVersion": spec["runtime_version"],
            "networkConfig": {"enableExternalIps": False},
            "labels": {k.replace("_", "-").lower(): str(v).lower()
                       for k, v in all_labels.items()},
            "metadata": {"startup-script": startup},
        }
        if spec.get("spot"):
            node_body["schedulingConfig"] = {"preemptible": True}
        qr_body: Dict[str, Any] = {
            "tpu": {"nodeSpec": [{
                "parent": self._parent,
                "nodeId": node_name,
                "node": node_body,
            }]},
        }
        if spec.get("reserved"):
            qr_body["guaranteed"] = {"reserved": True}
        else:
            qr_body["spot" if spec.get("spot") else "bestEffort"] = {}
        self._transport(
            "POST",
            f"{_TPU_API}/{self._parent}/queuedResources"
            f"?queuedResourceId={pid}",
            qr_body)
        self._nodes[pid] = {"qr_name": f"{self._parent}/queuedResources/{pid}",
                            "node_name": f"{self._parent}/nodes/{node_name}",
                            "node_type": node_type}
        return pid

    def wait_active(self, provider_id: str, timeout_s: float = 1800.0) -> bool:
        """Block until the QR yields an ACTIVE slice (or goes terminal).
        The autoscaler does NOT call this — it treats a live QR as pending
        capacity; this is for interactive `raytpu up`-style flows."""
        info = self._nodes.get(provider_id)
        if info is None:
            return False
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            state = self._qr_state(info["qr_name"])
            if state == "ACTIVE":
                return True
            if state not in _LIVE_QR_STATES:
                return False
            time.sleep(self.poll_interval_s)
        return False

    def terminate_node(self, provider_id: str) -> None:
        info = self._nodes.pop(provider_id, None)
        if info is None:
            return
        # Deleting the QR releases queued capacity; an ACTIVE QR requires
        # deleting the node first (API constraint), so try node then QR.
        for url in (info["node_name"], info["qr_name"]):
            try:
                self._transport("DELETE", f"{_TPU_API}/{url}", None)
            except Exception:
                pass  # already gone / not yet materialized

    def non_terminated_nodes(self) -> List[str]:
        live = []
        for pid, info in list(self._nodes.items()):
            try:
                state = self._qr_state(info["qr_name"])
            except Exception:
                live.append(pid)  # API hiccup: assume alive, never leak
                continue
            if state in _LIVE_QR_STATES:
                live.append(pid)
            else:
                self._nodes.pop(pid, None)
        return live

    def raytpu_node_id(self, provider_id: str) -> Optional[str]:
        """Cluster node id for a provisioned slice, or None while the QR is
        still queued/provisioning.  The mapping arrives when the slice's
        startup script registers with the GCS and reports the provider id
        label back (``register_provider_node``); until then the autoscaler
        must not treat the node as a zombie."""
        return self._nodes.get(provider_id, {}).get("raytpu_node_id")

    def record_node_registration(self, provider_id: str, raytpu_node_id: str):
        info = self._nodes.get(provider_id)
        if info is not None:
            info["raytpu_node_id"] = raytpu_node_id

    def shutdown(self):
        for pid in list(self._nodes):
            self.terminate_node(pid)

    # ------------------------------------------------------------- helpers

    def _qr_state(self, qr_name: str) -> str:
        res = self._transport("GET", f"{_TPU_API}/{qr_name}", None)
        return (res.get("state") or {}).get("state", "UNKNOWN") \
            if isinstance(res.get("state"), dict) else res.get("state", "UNKNOWN")
