"""Node providers: how the autoscaler actually gets machines.

Reference: ``python/ray/autoscaler/node_provider.py:13`` (NodeProvider ABC)
and ``_private/fake_multi_node/node_provider.py:237`` (FakeMultiNodeProvider
— real node processes on one machine, used to test autoscaler logic without
a cloud).  ``LocalNodeProvider`` is that fake-multi-node equivalent; a GCE
TPU-pod provider implements the same three methods against the GCE/QR APIs.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
import uuid
from typing import Dict, List, Optional


class NodeProvider:
    """Minimal cloud-plugin surface (create/terminate/list)."""

    def create_node(self, node_type: str, labels: Dict[str, str]) -> str:
        """Launch one node of `node_type`; returns a provider node id."""
        raise NotImplementedError

    def terminate_node(self, provider_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> List[str]:
        raise NotImplementedError


class LocalNodeProvider(NodeProvider):
    """Real ``node_main`` subprocesses joining the GCS — autoscaling logic
    runs against genuine nodes without a cloud account."""

    def __init__(self, gcs_address: str, node_types: Dict[str, dict],
                 session_dir: Optional[str] = None):
        self.gcs_address = gcs_address
        self.node_types = node_types
        self.session_dir = session_dir or os.path.join(
            "/tmp/raytpu", f"autoscaler-{os.getpid()}")
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self._procs: Dict[str, subprocess.Popen] = {}
        self._raytpu_node_ids: Dict[str, str] = {}

    def create_node(self, node_type: str, labels: Dict[str, str]) -> str:
        spec = self.node_types[node_type]
        resources = dict(spec.get("resources", {}))
        num_cpus = resources.pop("CPU", 1)
        num_tpus = resources.pop("TPU", 0)
        all_labels = dict(spec.get("labels", {}))
        all_labels.update(labels)
        cmd = [sys.executable, "-m", "ray_tpu.core.node_main",
               "--gcs-address", self.gcs_address,
               "--num-cpus", str(num_cpus),
               "--num-tpus", str(num_tpus),
               "--resources", json.dumps(resources),
               "--labels", json.dumps(all_labels),
               "--session-dir", self.session_dir]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        logf = open(os.path.join(self.session_dir, "logs",
                                 f"scaled-{len(self._procs)}.log"), "ab")
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=logf,
                                env=env)
        # register BEFORE waiting so a failed boot is still terminated, and
        # bound the wait — an unreachable GCS must not wedge the autoscaler
        pid = f"local-{uuid.uuid4().hex[:8]}"
        self._procs[pid] = proc
        line = self._read_line_with_timeout(proc, timeout_s=60.0)
        if not line:
            self.terminate_node(pid)
            raise RuntimeError(f"node {node_type} failed to start "
                              f"(no registration line within 60s)")
        info = json.loads(line)
        self._raytpu_node_ids[pid] = info["node_id"]
        return pid

    @staticmethod
    def _read_line_with_timeout(proc, timeout_s: float) -> str:
        import threading

        box = {}

        def read():
            try:
                box["line"] = proc.stdout.readline().decode()
            except Exception:
                box["line"] = ""

        t = threading.Thread(target=read, daemon=True)
        t.start()
        t.join(timeout_s)
        return box.get("line", "")

    def terminate_node(self, provider_id: str) -> None:
        proc = self._procs.pop(provider_id, None)
        self._raytpu_node_ids.pop(provider_id, None)
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()

    def non_terminated_nodes(self) -> List[str]:
        return [pid for pid, p in self._procs.items() if p.poll() is None]

    def raytpu_node_id(self, provider_id: str) -> Optional[str]:
        return self._raytpu_node_ids.get(provider_id)

    def shutdown(self):
        for pid in list(self._procs):
            self.terminate_node(pid)
