"""Autoscaler v2: GCS-authoritative instance manager.

Reference: ``python/ray/autoscaler/v2/`` +
``src/ray/protobuf/experimental/instance_manager.proto`` — v1 keeps the
fleet picture in the head-side loop's memory, so a head restart forgets
which cloud instances it launched and why; v2 moves the instance lifecycle
state machine into the GCS, with the head-side loop reduced to (a) a
demand→target calculator and (b) a provider reconciler that converges
actual instances toward the GCS-recorded targets.

TPU-first redesign: instead of a new protobuf service + storage table, the
instance table and targets live in the GCS KV (namespace ``autoscaler``),
which the GCS already snapshots to disk and restores on restart — the
authority/durability property of the reference's GcsAutoscalerStateManager
with zero new wire surface.  Preemption (the dominant failure on TPU
fleets) is a provider-reported disappearance: the reconciler marks the
instance TERMINATED and the next tick relaunches to target.

Instance lifecycle::

    QUEUED -> REQUESTED -> ALLOCATED -> RUNNING
                   \\-> FAILED               \\-> TERMINATING -> TERMINATED
                                             \\-> TERMINATED   (preempted)
"""

from __future__ import annotations

import json
import time
import uuid
from dataclasses import asdict, dataclass, field
from typing import Dict, List, Optional

QUEUED = "QUEUED"            # target raised; not yet requested from provider
REQUESTED = "REQUESTED"      # provider.create_node in flight
ALLOCATED = "ALLOCATED"      # provider id assigned; node booting
RUNNING = "RUNNING"          # registered with the cluster (has a node_id)
TERMINATING = "TERMINATING"  # terminate requested
TERMINATED = "TERMINATED"    # gone (graceful or preempted)
FAILED = "FAILED"            # launch failed

_NS = "autoscaler"
_LIVE = (QUEUED, REQUESTED, ALLOCATED, RUNNING)


@dataclass
class Instance:
    instance_id: str
    node_type: str
    status: str = QUEUED
    provider_id: Optional[str] = None
    node_id: Optional[str] = None
    created_at: float = field(default_factory=time.time)
    updated_at: float = field(default_factory=time.time)
    detail: str = ""


class InstanceManager:
    """CRUD + lifecycle transitions over the GCS KV (the authority)."""

    def __init__(self):
        from ray_tpu.experimental import internal_kv
        self._kv = internal_kv

    # -- targets -----------------------------------------------------------

    def set_target(self, node_type: str, count: int) -> None:
        self._kv.internal_kv_put(f"target:{node_type}",
                                 str(int(count)).encode(), namespace=_NS)

    def get_targets(self) -> Dict[str, int]:
        out = {}
        for key in self._kv.internal_kv_keys("target:", namespace=_NS):
            blob = self._kv.internal_kv_get(key, namespace=_NS)
            if blob:
                out[key.split(":", 1)[1]] = int(blob)
        return out

    # -- instances ---------------------------------------------------------

    def _put(self, inst: Instance) -> None:
        inst.updated_at = time.time()
        self._kv.internal_kv_put(f"inst:{inst.instance_id}",
                                 json.dumps(asdict(inst)).encode(),
                                 namespace=_NS)

    def instances(self) -> List[Instance]:
        out = []
        for key in self._kv.internal_kv_keys("inst:", namespace=_NS):
            blob = self._kv.internal_kv_get(key, namespace=_NS)
            if blob:
                out.append(Instance(**json.loads(blob)))
        return out

    def live(self, node_type: Optional[str] = None) -> List[Instance]:
        return [i for i in self.instances() if i.status in _LIVE
                and (node_type is None or i.node_type == node_type)]

    def queue(self, node_type: str) -> Instance:
        inst = Instance(instance_id=uuid.uuid4().hex[:12],
                        node_type=node_type)
        self._put(inst)
        return inst

    def transition(self, inst: Instance, status: str, *,
                   provider_id: Optional[str] = None,
                   node_id: Optional[str] = None,
                   detail: str = "") -> Instance:
        inst.status = status
        if provider_id is not None:
            inst.provider_id = provider_id
        if node_id is not None:
            inst.node_id = node_id
        inst.detail = detail
        self._put(inst)
        try:
            from ray_tpu.util import events
            sev = "WARNING" if status in (FAILED, TERMINATED) else "INFO"
            events.record(sev, "autoscaler-v2",
                          f"instance {inst.instance_id} -> {status}",
                          node_type=inst.node_type,
                          provider_id=inst.provider_id or "",
                          detail=detail)
        except Exception:
            pass
        return inst


class Reconciler:
    """Converge provider reality toward the GCS-recorded targets.

    Stateless across restarts by construction: every decision derives from
    the KV instance table + ``provider.non_terminated_nodes()`` — a fresh
    reconciler (new head process) picks up exactly where the old one
    stopped (reference: autoscaler v2's core property)."""

    def __init__(self, provider, im: Optional[InstanceManager] = None,
                 max_launches_per_tick: int = 2,
                 requested_timeout_s: float = 300.0,
                 max_terminal_records: int = 50):
        self.provider = provider
        self.im = im or InstanceManager()
        self.max_launches = max_launches_per_tick
        self.requested_timeout_s = requested_timeout_s
        self.max_terminal = max_terminal_records

    def tick(self) -> Dict[str, int]:
        """One reconciliation pass; returns action counts (for tests)."""
        actions = {"launched": 0, "terminated": 0, "preempted": 0,
                   "queued": 0, "failed": 0, "orphans": 0}
        im = self.im
        targets = im.get_targets()
        alive_pids = set(self.provider.non_terminated_nodes())
        all_insts = im.instances()
        by_type: Dict[str, List[Instance]] = {}
        for inst in all_insts:
            by_type.setdefault(inst.node_type, []).append(inst)
        launched_pids: set = set()

        for ntype, target in targets.items():
            insts = by_type.get(ntype, [])
            now = time.time()
            for inst in insts:
                # provider-reported disappearance (preemption / crash)
                if inst.status in (ALLOCATED, RUNNING) and \
                        inst.provider_id not in alive_pids:
                    im.transition(inst, TERMINATED, detail="preempted")
                    actions["preempted"] += 1
                # a crash between transition(REQUESTED) and the
                # ALLOCATED/FAILED write strands the instance: time it out
                # so the slot recovers (any node it DID launch is reclaimed
                # by the orphan sweep below).
                elif inst.status == REQUESTED and \
                        now - inst.updated_at > self.requested_timeout_s:
                    im.transition(inst, FAILED, detail="requested-timeout")
                    actions["failed"] += 1
                # terminate failed (or crashed) mid-flight last tick: retry
                # until the provider confirms the node gone.
                elif inst.status == TERMINATING:
                    if inst.provider_id not in alive_pids:
                        im.transition(inst, TERMINATED, detail="confirmed")
                    else:
                        try:
                            self.provider.terminate_node(inst.provider_id)
                            im.transition(inst, TERMINATED,
                                          detail="scale-down")
                        except Exception:
                            pass  # stays TERMINATING; retried next tick
            live = [i for i in insts if i.status in _LIVE]
            # under target: queue + launch (bounded per tick)
            for _ in range(max(0, target - len(live))):
                live.append(im.queue(ntype))
                actions["queued"] += 1
            launched = 0
            for inst in live:
                if inst.status != QUEUED or launched >= self.max_launches:
                    continue
                im.transition(inst, REQUESTED)
                try:
                    pid = self.provider.create_node(ntype, {})
                except Exception as e:  # noqa: BLE001 — retry next tick
                    im.transition(inst, FAILED, detail=repr(e))
                    actions["failed"] += 1
                    continue
                im.transition(inst, ALLOCATED, provider_id=pid)
                launched_pids.add(pid)
                actions["launched"] += 1
                launched += 1
            # over target: drop queued first, then the NEWEST non-running
            # booting instance (keep the one closest to registering)
            excess = len(live) - target
            if excess > 0:
                for inst in sorted(live, key=lambda i: (
                        i.status == RUNNING, -i.created_at))[:excess]:
                    if inst.status in (QUEUED, REQUESTED):
                        im.transition(inst, TERMINATED, detail="un-queued")
                    elif inst.provider_id:
                        im.transition(inst, TERMINATING)
                        try:
                            self.provider.terminate_node(inst.provider_id)
                            im.transition(inst, TERMINATED,
                                          detail="scale-down")
                        except Exception:
                            pass  # stays TERMINATING; retried next tick
                    actions["terminated"] += 1
            # promote ALLOCATED -> RUNNING once the node registers
            if hasattr(self.provider, "raytpu_node_id"):
                for inst in live:
                    if inst.status == ALLOCATED and inst.provider_id:
                        nid = self.provider.raytpu_node_id(inst.provider_id)
                        if nid:
                            im.transition(inst, RUNNING, node_id=nid)

        # Orphan sweep: provider nodes referenced by NO instance record
        # (create_node returned but the head died before the ALLOCATED
        # write).  Authoritative state means unaccounted nodes are leaks.
        referenced = {i.provider_id for i in im.instances()
                      if i.provider_id and i.status != TERMINATED}
        for pid in alive_pids - referenced - launched_pids:
            try:
                self.provider.terminate_node(pid)
                actions["orphans"] += 1
            except Exception:
                pass  # retried next tick

        self._gc_terminal()
        return actions

    def _gc_terminal(self) -> None:
        """Bound dead-record growth: keep only the newest max_terminal
        TERMINATED/FAILED records (preemption-heavy fleets churn hundreds
        per day; each tick lists every key)."""
        terminal = [i for i in self.im.instances()
                    if i.status in (TERMINATED, FAILED)]
        if len(terminal) <= self.max_terminal:
            return
        from ray_tpu.experimental import internal_kv
        terminal.sort(key=lambda i: i.updated_at)
        for inst in terminal[:-self.max_terminal]:
            internal_kv.internal_kv_del(f"inst:{inst.instance_id}",
                                        namespace=_NS)


__all__ = ["Instance", "InstanceManager", "Reconciler",
           "QUEUED", "REQUESTED", "ALLOCATED", "RUNNING",
           "TERMINATING", "TERMINATED", "FAILED"]
