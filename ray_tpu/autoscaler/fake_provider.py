"""Failure-injecting provider harness for autoscaler tests.

Reference: ``python/ray/autoscaler/_private/fake_multi_node/node_provider.py:237``
(FakeMultiNodeProvider) — the reference tests its autoscaler against a
provider that can misbehave on command.  This wrapper delegates to any real
provider (usually ``LocalNodeProvider``, which boots genuine node processes)
and injects, per test knobs:

* ``fail_first_n`` — the first N ``create_node`` calls raise (provider
  outage / quota error); the autoscaler must retry on later ticks rather
  than crash or leak demand.
* ``launch_delay_s`` — every create blocks this long (slow cloud control
  plane); tests assert the autoscaler neither double-launches nor counts a
  slow launch as failed.
* ``flaky_terminate`` — first terminate per node raises; the autoscaler
  must converge anyway.
"""

from __future__ import annotations

import time
from typing import Dict, List

from .providers import NodeProvider


class FlakyNodeProvider(NodeProvider):
    def __init__(self, inner: NodeProvider, fail_first_n: int = 0,
                 launch_delay_s: float = 0.0, flaky_terminate: bool = False):
        self.inner = inner
        self.fail_first_n = fail_first_n
        self.launch_delay_s = launch_delay_s
        self.flaky_terminate = flaky_terminate
        self.create_attempts = 0
        self.create_failures = 0
        self._terminate_seen: Dict[str, bool] = {}

    def create_node(self, node_type: str, labels: Dict[str, str]) -> str:
        self.create_attempts += 1
        if self.launch_delay_s:
            time.sleep(self.launch_delay_s)
        if self.create_attempts <= self.fail_first_n:
            self.create_failures += 1
            raise RuntimeError(
                f"injected launch failure {self.create_attempts}"
                f"/{self.fail_first_n}")
        return self.inner.create_node(node_type, labels)

    def terminate_node(self, provider_id: str) -> None:
        if self.flaky_terminate and not self._terminate_seen.get(provider_id):
            self._terminate_seen[provider_id] = True
            raise RuntimeError("injected terminate failure")
        self.inner.terminate_node(provider_id)

    def non_terminated_nodes(self) -> List[str]:
        return self.inner.non_terminated_nodes()

    def raytpu_node_id(self, provider_id: str):
        fn = getattr(self.inner, "raytpu_node_id", None)
        return fn(provider_id) if fn else None

    def shutdown(self):
        fn = getattr(self.inner, "shutdown", None)
        if fn:
            fn()
