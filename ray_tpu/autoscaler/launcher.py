"""Cluster launcher: ``raytpu up / down / status`` over the TPU queued-
resource provider.

Reference: ``python/ray/autoscaler/_private/commands.py`` (``ray up`` /
``ray down`` driving a NodeProvider from a YAML cluster config).  The
launcher is deliberately thin: it owns no scaling policy — it submits the
configured node counts as queued resources via
:class:`~ray_tpu.autoscaler.gcp.GCETpuNodeProvider`, records what it
launched in a state file (so a later ``down`` from a fresh process can
tear down exactly that fleet), and reports per-node QR states.

Config YAML::

    cluster_name: myfleet
    gcs_address: 10.0.0.1:6379
    provider:
      type: gce_tpu
      project: my-project
      zone: us-central2-b
    available_node_types:
      tpu_v5e_8:
        count: 2
        accelerator_type: v5litepod-8
        runtime_version: tpu-vm-tf-2.16.1-pjrt
        resources: {CPU: 8, TPU: 8}
        spot: true

Transport is injectable exactly like the provider's (tests pass a fake
``transport(method, url, body) -> dict``; production uses the provider's
metadata-server OAuth transport) — the launcher itself performs zero
network IO.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, Dict, List, Optional

from .gcp import GCETpuNodeProvider


def load_config(path: str) -> Dict[str, Any]:
    """Read a launcher YAML (JSON is valid YAML, so either works)."""
    with open(path) as f:
        text = f.read()
    try:
        import yaml
        cfg = yaml.safe_load(text)
    except ImportError:
        cfg = json.loads(text)
    if not isinstance(cfg, dict):
        raise ValueError(f"launcher config {path} is not a mapping")
    for key in ("cluster_name", "provider", "available_node_types"):
        if key not in cfg:
            raise ValueError(f"launcher config missing {key!r}")
    return cfg


def default_state_path(cluster_name: str) -> str:
    return os.path.join("/tmp/raytpu", f"launcher-{cluster_name}.json")


class ClusterLauncher:
    """Summon / tear down / inspect one named fleet."""

    def __init__(self, config: Dict[str, Any],
                 transport: Optional[Callable[..., dict]] = None,
                 state_path: Optional[str] = None):
        self.config = config
        self.cluster_name = str(config["cluster_name"])
        provider_cfg = config.get("provider", {})
        ptype = provider_cfg.get("type", "gce_tpu")
        if ptype != "gce_tpu":
            raise ValueError(f"unknown provider type {ptype!r}")
        self.node_types: Dict[str, dict] = dict(
            config.get("available_node_types", {}))
        self.state_path = state_path or default_state_path(self.cluster_name)
        self.provider = GCETpuNodeProvider(
            gcs_address=str(config.get("gcs_address", "")),
            node_types=self.node_types,
            project=provider_cfg.get("project", ""),
            zone=provider_cfg.get("zone", ""),
            transport=transport,
            cluster_name=self.cluster_name,
            poll_interval_s=float(provider_cfg.get("poll_interval_s", 5.0)))
        self._load_state()

    # ---------------------------------------------------------------- state

    def _load_state(self):
        """Rehydrate the provider's id -> QR/node mapping from a previous
        invocation, so ``down``/``status`` in a fresh process still see the
        fleet ``up`` launched."""
        try:
            with open(self.state_path) as f:
                state = json.load(f)
        except (OSError, ValueError):
            return
        for pid, info in state.get("nodes", {}).items():
            self.provider._nodes.setdefault(pid, dict(info))

    def _save_state(self):
        os.makedirs(os.path.dirname(self.state_path), exist_ok=True)
        tmp = f"{self.state_path}.tmp"
        with open(tmp, "w") as f:
            json.dump({"cluster_name": self.cluster_name,
                       "saved_at": time.time(),
                       "nodes": self.provider._nodes}, f, indent=2)
        os.replace(tmp, self.state_path)

    # ------------------------------------------------------------- commands

    def up(self, wait: bool = False,
           wait_timeout_s: float = 1800.0) -> List[str]:
        """Bring the fleet to the configured counts (idempotent: existing
        live nodes of a type count toward its target).  Returns the
        provider ids CREATED by this call."""
        live = self.provider.non_terminated_nodes()
        by_type: Dict[str, int] = {}
        for pid in live:
            nt = self.provider._nodes.get(pid, {}).get("node_type")
            by_type[nt] = by_type.get(nt, 0) + 1
        created: List[str] = []
        for node_type, spec in self.node_types.items():
            want = int(spec.get("count", 1))
            have = by_type.get(node_type, 0)
            for _ in range(max(0, want - have)):
                created.append(self.provider.create_node(node_type, {}))
        self._save_state()
        if wait:
            deadline = time.monotonic() + wait_timeout_s
            for pid in created:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self.provider.wait_active(pid, timeout_s=left)
        return created

    def down(self) -> List[str]:
        """Tear down every node this launcher's state knows about.
        Returns the provider ids terminated."""
        pids = list(self.provider._nodes)
        for pid in pids:
            self.provider.terminate_node(pid)
        self._save_state()
        return pids

    def status(self) -> List[dict]:
        """Per-node QR/provision state of the tracked fleet."""
        rows = []
        for pid, info in sorted(self.provider._nodes.items()):
            try:
                state = self.provider._qr_state(info["qr_name"])
            except Exception as e:  # noqa: BLE001 — report, don't die
                state = f"UNKNOWN ({e})"
            rows.append({"provider_id": pid,
                         "node_type": info.get("node_type"),
                         "state": state,
                         "raytpu_node_id": info.get("raytpu_node_id")})
        return rows
