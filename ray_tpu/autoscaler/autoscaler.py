"""StandardAutoscaler: poll load -> bin-pack pending demands -> scale.

Reference: ``autoscaler/_private/autoscaler.py:166`` (StandardAutoscaler
update loop), ``resource_demand_scheduler.py`` (demand bin-packing onto node
types), ``monitor.py`` (the driving process).

Scale-up: pending lease demands that no live node can satisfy are bin-packed
onto prospective launches of the first feasible node type (first-fit
decreasing over max_workers budgets; no cost model — node_types dict order
is the preference order).
Scale-down: provider nodes idle (no queued work, full availability) past
``idle_timeout_s`` are drained + terminated, respecting ``min_workers``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional

from .providers import LocalNodeProvider, NodeProvider


@dataclasses.dataclass
class NodeType:
    resources: Dict[str, float]
    max_workers: int = 8
    min_workers: int = 0
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: Dict[str, NodeType]
    poll_interval_s: float = 1.0
    idle_timeout_s: float = 30.0
    upscaling_speed: int = 2   # max nodes launched per update


class StandardAutoscaler:
    """Runs in the driver (or a monitor process) against the GCS."""

    def __init__(self, gcs_address: str, config: AutoscalerConfig,
                 provider: Optional[NodeProvider] = None):
        self.gcs_address = gcs_address
        self.config = config
        self.provider = provider or LocalNodeProvider(
            gcs_address, {name: dataclasses.asdict(nt)
                          for name, nt in config.node_types.items()})
        self._owned: Dict[str, str] = {}       # provider id -> node type
        self._launched_at: Dict[str, float] = {}
        self._idle_since: Dict[str, float] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.num_launches = 0
        self.num_failed_launches = 0
        self.num_terminations = 0

    # ------------------------------------------------------------- control

    def start(self):
        for name, nt in self.config.node_types.items():
            for _ in range(nt.min_workers):
                self._launch(name)
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="autoscaler")
        self._thread.start()
        return self

    def stop(self, terminate_nodes: bool = True):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if terminate_nodes and hasattr(self.provider, "shutdown"):
            self.provider.shutdown()

    # ---------------------------------------------------------------- loop

    def _loop(self):
        from ray_tpu.core.rpc import RpcClient, run_async

        client = RpcClient(self.gcs_address)
        while not self._stop.is_set():
            try:
                load = run_async(client.call("get_load"), timeout=10)
                self.update(load)
            except Exception:
                pass
            self._stop.wait(self.config.poll_interval_s)
        try:
            run_async(client.close(), timeout=2)
        except Exception:
            pass

    # -------------------------------------------------------------- update

    def update(self, load: Dict):
        """One reconcile pass over a load snapshot (pure given the snapshot;
        the unit tests drive this directly like the reference's
        StandardAutoscaler.update tests).

        ``load`` is the GCS get_load payload: {"nodes": {...},
        "pending_demands": [...]} — infeasible driver-side demands arrive in
        pending_demands, node-queued ones in each node's queued_demands."""
        nodes = load.get("nodes", load)
        extra = load.get("pending_demands", []) if "nodes" in load else []
        alive = {nid: n for nid, n in nodes.items() if n.get("alive")}
        unmet = self._unmet_demands(alive, extra)
        if unmet:
            self._scale_up(unmet)
        self._scale_down(alive)

    def _unmet_demands(self, alive: Dict[str, dict],
                       extra: List[Dict[str, float]]) -> List[Dict[str, float]]:
        """Pending demand shapes no node can currently satisfy, minus what
        free capacity could absorb (simulated placement like
        resource_demand_scheduler).  Draining nodes contribute NO free
        capacity: a node under a preemption notice is about to take its
        resources with it, and letting it absorb simulated demand would
        suppress exactly the scale-up an elastic trainer (reporting its
        missing workers as pending demand) is waiting on."""
        free = {nid: dict(n["available"]) for nid, n in alive.items()
                if not n.get("draining")}
        demands = list(extra)
        for n in alive.values():
            for entry in n.get("queued_demands", []):
                # agents report aggregated [shape, count] pairs; accept bare
                # shapes too (driver pending-demand reports)
                if isinstance(entry, (list, tuple)) and len(entry) == 2:
                    shape, count = entry
                    demands.extend([shape] * min(int(count), 100))
                else:
                    demands.append(entry)
        unmet = []
        for demand in demands:
            placed = False
            for nid, avail in free.items():
                if all(avail.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items() if v > 0):
                    for k, v in demand.items():
                        avail[k] = avail.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unmet.append(demand)
        return unmet

    def _scale_up(self, unmet: List[Dict[str, float]]):
        budget = self.config.upscaling_speed
        counts = self._owned_counts()
        # first-fit decreasing onto prospective launches: a planned node
        # absorbs as many pending demands as fit before another is launched
        # (reference: resource_demand_scheduler's simulated bin-packing)
        prospective: List[Dict[str, float]] = []
        for demand in unmet:
            placed = False
            for cap in prospective:
                if all(cap.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items() if v > 0):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            if budget <= 0:
                continue
            for name, nt in self.config.node_types.items():
                if counts.get(name, 0) >= nt.max_workers:
                    continue
                if all(nt.resources.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items() if v > 0):
                    budget -= 1  # a failed attempt still consumes budget
                    if self._launch(name) is None:
                        break  # demand stays unmet; retried next update
                    counts[name] = counts.get(name, 0) + 1
                    cap = dict(nt.resources)
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    prospective.append(cap)
                    break

    def _scale_down(self, alive: Dict[str, dict]):
        # Any provider that can map its ids to cluster node ids supports
        # idle drain (LocalNodeProvider, wrapped/flaky providers; the GCE
        # TPU provider reports None until its startup script registers).
        if not hasattr(self.provider, "raytpu_node_id"):
            return
        now = time.monotonic()
        counts = self._owned_counts()
        for pid in list(self._owned):
            ntype = self._owned[pid]
            raytpu_id = self.provider.raytpu_node_id(pid)
            if raytpu_id is None:
                # Cloud providers learn the mapping when the node registers
                # with its `raytpu-provider-id` label (set at create_node).
                for nid, n in alive.items():
                    if (n.get("labels") or {}).get(
                            "raytpu-provider-id") == pid:
                        rec = getattr(self.provider,
                                      "record_node_registration", None)
                        if rec is not None:
                            rec(pid, nid)
                        raytpu_id = nid
                        break
            if raytpu_id is None:
                # Not registered yet (e.g. a queued TPU slice still
                # provisioning — can legitimately take hours): neither
                # idle-drain nor zombie cleanup applies.
                continue
            n = alive.get(raytpu_id)
            if n is None:
                # registered but not alive in the view: the node hung or the
                # GCS declared it dead — a zombie process would otherwise
                # hold a max_workers slot forever
                launched = self._launched_at.get(pid, now)
                if now - launched > 60.0:
                    self._terminate(pid)
                    counts[ntype] = max(0, counts.get(ntype, 1) - 1)
                continue
            busy = (n.get("queue_len", 0) > 0
                    or any(n["available"].get(k, 0.0) + 1e-9 < v
                           for k, v in n["total"].items()))
            if busy:
                self._idle_since.pop(pid, None)
                continue
            first_idle = self._idle_since.setdefault(pid, now)
            nt = self.config.node_types[ntype]
            if (now - first_idle >= self.config.idle_timeout_s
                    and counts.get(ntype, 0) > nt.min_workers):
                self._terminate(pid)
                counts[ntype] -= 1

    # ---------------------------------------------------------- primitives

    def _owned_counts(self) -> Dict[str, int]:
        live = set(self.provider.non_terminated_nodes())
        self._owned = {pid: t for pid, t in self._owned.items()
                       if pid in live or pid not in self._idle_since}
        counts: Dict[str, int] = {}
        for pid, t in self._owned.items():
            if pid in live:
                counts[t] = counts.get(t, 0) + 1
        return counts

    def _launch(self, node_type: str) -> Optional[str]:
        """Launch one node; a provider failure (quota, outage) is counted
        and absorbed — the demand stays unmet and the next update retries
        (reference: node_launcher.py catches and logs launch exceptions)."""
        nt = self.config.node_types[node_type]
        try:
            pid = self.provider.create_node(node_type, dict(nt.labels))
        except Exception as e:
            self.num_failed_launches += 1
            self._event("WARNING", f"launch of {node_type} failed",
                        error=repr(e))
            return None
        self._owned[pid] = node_type
        self._launched_at[pid] = time.monotonic()
        self.num_launches += 1
        self._event("INFO", f"launched {node_type}", provider_id=pid)
        return pid

    def _terminate(self, pid: str):
        node_type = self._owned.get(pid)
        self.provider.terminate_node(pid)
        self._owned.pop(pid, None)
        self._idle_since.pop(pid, None)
        self._launched_at.pop(pid, None)
        self.num_terminations += 1
        self._event("INFO", f"terminated {node_type}", provider_id=pid)

    def _event(self, severity: str, message: str, **labels):
        """Structured cluster event (util/events; reference RAY_EVENT)."""
        try:
            from ray_tpu.util import events
            events.record(severity, "autoscaler", message, **labels)
        except Exception:
            pass  # events require a live GCS; never break scaling on them
