"""Autoregressive decoding with a slot-based KV cache — the inference side of
the transformer (training side: ``transformer.apply_trunk``).

The reference has no LLM inference engine (SURVEY §2.7 note: no vLLM in the
snapshot; ``@serve.batch`` is the primitive) — this is greenfield TPU-first
code backing ``ray_tpu.serve.llm``.

TPU-first design:
* **Static shapes.**  The cache is a fixed [L, slots, max_len, KV, D] HBM
  tensor; a "slot" is one sequence's reserved cache row.  Continuous batching
  admits/retires sequences by slot index — tensor shapes never change, so jit
  compiles exactly two programs (one prefill per length bucket, one decode
  step) and reuses them forever.
* **Scan over layers** with the cache as scan-carried state: compile time is
  depth-independent, matching ``apply_trunk``.
* **Prefill** runs the normal causal forward over a right-padded [B, bucket]
  block and writes K/V for every position; padding beyond a sequence's length
  is never *read* because decode masks by per-slot length (causality makes
  the writes at pad positions harmless: real positions never attend to them).
* **Decode** is one token per active slot: q at position `len`, attention
  over the cache row masked to positions <= len.  The [slots, H, max_len]
  score tensor is tiny; XLA fuses the mask+softmax into the two matmuls.

No torch, no dynamic shapes, no per-request Python in the hot loop.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from .config import TransformerConfig
from .transformer import Params, _norm, _rope, lm_head_weight

KVCache = Dict[str, jnp.ndarray]


def init_kv_cache(cfg: TransformerConfig, num_slots: int, max_len: int,
                  dtype=jnp.bfloat16) -> KVCache:
    """Allocate the HBM cache: K/V per layer per slot, plus per-slot lengths."""
    shape = (cfg.num_layers, num_slots, max_len, cfg.num_kv_heads,
             cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "length": jnp.zeros((num_slots,), jnp.int32),
    }


def cache_bytes(cfg: TransformerConfig, num_slots: int, max_len: int,
                dtype_bytes: int = 2) -> int:
    return (2 * cfg.num_layers * num_slots * max_len * cfg.num_kv_heads
            * cfg.head_dim * dtype_bytes)


# ---------------------------------------------------------------------------
# Shared per-layer attention pieces
# ---------------------------------------------------------------------------

def _qkv(x, p, cfg: TransformerConfig, positions):
    """x: [B, S, H] -> q [B,S,NH,D], k/v [B,S,NKV,D] with RoPE applied."""
    b, s, _ = x.shape
    cast = x.dtype
    q = x @ p["wq"].astype(cast)
    k = x @ p["wk"].astype(cast)
    v = x @ p["wv"].astype(cast)
    if "bq" in p:
        q = q + p["bq"].astype(cast)
        k = k + p["bk"].astype(cast)
        v = v + p["bv"].astype(cast)
    q = q.reshape(b, s, cfg.num_heads, cfg.head_dim)
    k = k.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    v = v.reshape(b, s, cfg.num_kv_heads, cfg.head_dim)
    if cfg.use_rope:
        q = _rope_per_row(q, positions, cfg.rope_theta)
        k = _rope_per_row(k, positions, cfg.rope_theta)
    return q, k, v


def _rope_per_row(x: jnp.ndarray, positions: jnp.ndarray,
                  theta: float) -> jnp.ndarray:
    """RoPE with per-batch-row positions. x: [B, S, H, D]; positions: [B, S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[..., None].astype(jnp.float32) * freqs  # [B, S, D/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _mlp(y, p, cfg: TransformerConfig):
    cast = y.dtype
    if cfg.num_experts > 1:
        from ..ops import moe as moe_ops
        out, _ = moe_ops.moe_mlp(
            y, p["moe"]["router"], p["moe"]["w_gate"], p["moe"]["w_in"],
            p["moe"]["w_out"], cfg.experts_per_token,
            cfg.expert_capacity_factor)
        return out
    mp = p["mlp"]
    if cfg.use_swiglu:
        return (jax.nn.silu(y @ mp["w_gate"].astype(cast))
                * (y @ mp["w_in"].astype(cast))) @ mp["w_out"].astype(cast)
    h = jax.nn.gelu(y @ mp["w_in"].astype(cast) + mp["b_in"].astype(cast))
    return h @ mp["w_out"].astype(cast) + mp["b_out"].astype(cast)


def _proj_out(attn, p, cast):
    out = attn @ p["wo"].astype(cast)
    if "bo" in p:
        out = out + p["bo"].astype(cast)
    return out


# ---------------------------------------------------------------------------
# Prefill
# ---------------------------------------------------------------------------

def prefill(params: Params, cache: KVCache, tokens: jnp.ndarray,
            lengths: jnp.ndarray, slot_ids: jnp.ndarray,
            cfg: TransformerConfig,
            compute_dtype=jnp.bfloat16) -> Tuple[KVCache, jnp.ndarray]:
    """Run the causal forward over right-padded prompts, populate the cache.

    tokens: [B, S] int32 (right-padded to the bucket length S)
    lengths: [B] true prompt lengths; slot_ids: [B] cache rows to fill.
    Returns (cache, last-token logits [B, V] f32).
    """
    b, s = tokens.shape
    cast = compute_dtype
    x = params["embed"]["tokens"][tokens].astype(cast)
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][:s][None].astype(cast)
    positions = jnp.broadcast_to(jnp.arange(s)[None], (b, s))

    from ..ops.attention import mha

    def body(x, layer):
        lp, k_lay, v_lay = layer        # k/v_lay: [slots, max_len, NKV, D]
        y = _norm(x, lp["attn_norm"], cfg)
        q, k, v = _qkv(y, lp["attn"], cfg, positions)
        attn = mha(q, k, v, causal=True,
                   logit_softcap=cfg.attn_logit_softcap)
        x = x + _proj_out(attn.reshape(b, s, -1), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        # write this layer's K/V into the slots (padded tail included;
        # decode's length mask keeps it unread)
        k_lay = k_lay.at[slot_ids, :s].set(k.astype(k_lay.dtype))
        v_lay = v_lay.at[slot_ids, :s].set(v.astype(v_lay.dtype))
        return x, (k_lay, v_lay)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    # logits of each prompt's *last real token* (next-token distribution)
    last = jnp.take_along_axis(
        x, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)[:, 0]  # [B, H]
    logits = (last @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    cache = {
        "k": k_new, "v": v_new,
        "length": cache["length"].at[slot_ids].set(lengths),
    }
    return cache, logits


# ---------------------------------------------------------------------------
# Decode step
# ---------------------------------------------------------------------------

def decode_step(params: Params, cache: KVCache, tokens: jnp.ndarray,
                active: jnp.ndarray, cfg: TransformerConfig,
                compute_dtype=jnp.bfloat16) -> Tuple[KVCache, jnp.ndarray]:
    """One autoregressive step for every active slot.

    tokens: [slots] int32 — the last emitted token per slot
    active: [slots] bool — inactive slots compute garbage that is masked out
    Returns (cache, logits [slots, V] f32).  Appends K/V at position `length`
    and increments `length` for active slots.
    """
    n_slots = tokens.shape[0]
    max_len = cache["k"].shape[2]
    cast = compute_dtype
    lengths = cache["length"]                                  # [slots]
    x = params["embed"]["tokens"][tokens][:, None].astype(cast)  # [S,1,H]
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][jnp.minimum(
            lengths, cfg.max_seq_len - 1)][:, None].astype(cast)
    positions = lengths[:, None]                               # [slots, 1]
    scale = cfg.head_dim ** -0.5
    reps = cfg.num_heads // cfg.num_kv_heads
    # mask over cache positions: <= current length (the new token's position)
    pos_mask = (jnp.arange(max_len)[None] <= lengths[:, None])  # [slots, max_len]

    def body(x, layer):
        lp, k_lay, v_lay = layer
        y = _norm(x, lp["attn_norm"], cfg)
        q, k, v = _qkv(y, lp["attn"], cfg, positions)  # q:[S,1,NH,D] k/v:[S,1,NKV,D]
        # append at position `length` (one row per slot)
        k_lay = k_lay.at[jnp.arange(n_slots), lengths].set(
            k[:, 0].astype(k_lay.dtype))
        v_lay = v_lay.at[jnp.arange(n_slots), lengths].set(
            v[:, 0].astype(v_lay.dtype))
        # attention over the cache row
        qh = q[:, 0].reshape(n_slots, cfg.num_kv_heads, reps, cfg.head_dim)
        scores = jnp.einsum("sgrd,smgd->sgrm", qh.astype(jnp.float32),
                            k_lay.astype(jnp.float32)) * scale
        if cfg.attn_logit_softcap:
            c = cfg.attn_logit_softcap
            scores = c * jnp.tanh(scores / c)
        scores = jnp.where(pos_mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("sgrm,smgd->sgrd", probs,
                          v_lay.astype(jnp.float32))
        attn = attn.reshape(n_slots, 1, cfg.num_heads * cfg.head_dim)
        x = x + _proj_out(attn.astype(cast), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        return x, (k_lay, v_lay)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    logits = (x[:, 0] @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    cache = {
        "k": k_new, "v": v_new,
        "length": jnp.where(active, jnp.minimum(lengths + 1, max_len),
                            lengths),
    }
    return cache, logits


def sample(logits: jnp.ndarray, key: jax.Array, temperature: float = 0.0,
           top_k: int = 0) -> jnp.ndarray:
    """Greedy (temperature 0) or temperature/top-k sampling. logits: [B, V]."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        thresh = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < thresh, -1e30, logits)
    return jax.random.categorical(key, logits).astype(jnp.int32)


def sample_per_slot(logits: jnp.ndarray, key: jax.Array,
                    temperature: jnp.ndarray, top_k: int = 0) -> jnp.ndarray:
    """Traceable mixed sampling: per-row temperature (0 = greedy).

    logits: [B, V]; temperature: [B] f32.  Rows with temperature 0 take the
    argmax; others sample categorically at their temperature.  Lives inside
    the jitted decode step so sampled tokens never leave the device.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    t = jnp.maximum(temperature, 1e-6)[:, None]
    scaled = logits / t
    if top_k > 0:
        thresh = jax.lax.top_k(scaled, top_k)[0][..., -1:]
        scaled = jnp.where(scaled < thresh, -1e30, scaled)
    drawn = jax.random.categorical(key, scaled).astype(jnp.int32)
    return jnp.where(temperature > 0.0, drawn, greedy)


def decode_and_sample(params: Params, cache: KVCache, tokens: jnp.ndarray,
                      active: jnp.ndarray, temperature: jnp.ndarray,
                      key: jax.Array, cfg: TransformerConfig,
                      top_k: int = 0,
                      compute_dtype=jnp.bfloat16
                      ) -> Tuple[KVCache, jnp.ndarray]:
    """One decode step with on-device sampling: the whole autoregressive
    recurrence (embed -> attend-over-cache -> sample -> feed back) stays on
    the device, so the host only reads tokens back lazily (the engine fetches
    with a pipelined lag to hide readback RTT — crucial when the chip is
    reached over a network tunnel).  Inactive slots keep their token."""
    cache, logits = decode_step(params, cache, tokens, active, cfg,
                                compute_dtype)
    nxt = sample_per_slot(logits, key, temperature, top_k)
    return cache, jnp.where(active, nxt, tokens)


def decode_loop(params: Params, cache: KVCache, tokens: jnp.ndarray,
                active: jnp.ndarray, temperature: jnp.ndarray,
                key: jax.Array, n_steps: int, cfg: TransformerConfig,
                top_k: int = 0, compute_dtype=jnp.bfloat16
                ) -> Tuple[KVCache, jnp.ndarray, jnp.ndarray]:
    """``n_steps`` decode steps in one compiled program (``lax.scan``).

    One host dispatch + one readback per *n_steps* tokens-per-slot instead of
    per token — the decisive factor when the chip sits behind a network
    tunnel (dispatch RTT >> per-step compute).  Returns
    (cache, final tokens [slots], emitted [n_steps, slots])."""

    def body(carry, i):
        cache, toks = carry
        cache, nxt = decode_and_sample(
            params, cache, toks, active, temperature,
            jax.random.fold_in(key, i), cfg, top_k, compute_dtype)
        return (cache, nxt), nxt

    (cache, tokens), emitted = jax.lax.scan(
        body, (cache, tokens), jnp.arange(n_steps))
    return cache, tokens, emitted


def prefill_and_sample(params: Params, cache: KVCache, tokens: jnp.ndarray,
                       lengths: jnp.ndarray, slot_ids: jnp.ndarray,
                       temperature: jnp.ndarray, key: jax.Array,
                       cfg: TransformerConfig, top_k: int = 0,
                       compute_dtype=jnp.bfloat16
                       ) -> Tuple[KVCache, jnp.ndarray]:
    """Prefill + sample each prompt's first output token on device."""
    cache, logits = prefill(params, cache, tokens, lengths, slot_ids, cfg,
                            compute_dtype)
    return cache, sample_per_slot(logits, key, temperature, top_k)


# ---------------------------------------------------------------------------
# Device-resident autoregressive state (zero host ops in the serving loop)
# ---------------------------------------------------------------------------
#
# Over a tunneled backend every EAGER op or small host->device transfer costs
# a full round trip (~60-80 ms measured) while a jitted dispatch is async and
# ~0.1 ms.  The serving engine therefore keeps the complete per-slot
# autoregressive state ON DEVICE and only ever calls two jitted programs:
#
#   decode_state_loop(params, cache, state, n)   — n steps, state evolves
#   prefill_admit(params, cache, state, <numpy admit batch>)
#
# `state` carries tokens/active/temps/budget/eos + the PRNG key; active slots
# DECAY on device (budget exhausted or EOS sampled) by the same predicate the
# host applies to the emitted tokens, so the host's scheduling mirror stays
# consistent without a single eager device write.

def init_decode_state(num_slots: int, key: jax.Array) -> Dict[str, Any]:
    """All-device per-slot autoregressive state (incl. the scratch slot)."""
    return {
        "tokens": jnp.zeros((num_slots,), jnp.int32),
        "active": jnp.zeros((num_slots,), bool),
        "temps": jnp.zeros((num_slots,), jnp.float32),
        "budget": jnp.zeros((num_slots,), jnp.int32),
        "eos": jnp.full((num_slots,), -1, jnp.int32),
        "key": key,
    }


def _merge_admit(state: Dict[str, Any], first: jnp.ndarray,
                 slot_ids: jnp.ndarray, temps: jnp.ndarray,
                 budgets: jnp.ndarray, eos: jnp.ndarray,
                 real_mask: jnp.ndarray) -> Dict[str, Any]:
    """Merge one admit batch into the decode state.  The sampled first token
    spends one unit of budget; a 1-token request (or an immediate EOS) is
    born inactive."""
    budgets = budgets - 1
    act = real_mask & (budgets > 0) & (first != eos)
    return {
        "tokens": state["tokens"].at[slot_ids].set(first),
        "active": state["active"].at[slot_ids].set(act),
        "temps": state["temps"].at[slot_ids].set(temps),
        "budget": state["budget"].at[slot_ids].set(budgets),
        "eos": state["eos"].at[slot_ids].set(eos),
        "key": jax.random.fold_in(state["key"], 0x5EED),
    }


def prefill_admit(params: Params, cache: KVCache, state: Dict[str, Any],
                  tokens: jnp.ndarray, lengths: jnp.ndarray,
                  slot_ids: jnp.ndarray, temps: jnp.ndarray,
                  budgets: jnp.ndarray, eos: jnp.ndarray,
                  real_mask: jnp.ndarray, cfg: TransformerConfig,
                  top_k: int = 0, compute_dtype=jnp.bfloat16):
    """Prefill + sample + merge into the decode state, one fixed-shape
    program.  Returns (cache, state, first_tokens [B])."""
    cache, logits = prefill(params, cache, tokens, lengths, slot_ids, cfg,
                            compute_dtype)
    first = sample_per_slot(logits, state["key"], temps, top_k)
    state = _merge_admit(state, first, slot_ids, temps, budgets, eos,
                         real_mask)
    return cache, state, first


def decode_state_loop(params: Params, cache: KVCache, state: Dict[str, Any],
                      n_steps: int, cfg: TransformerConfig, top_k: int = 0,
                      compute_dtype=jnp.bfloat16):
    """``n_steps`` decode+sample steps with on-device active decay.

    Returns (cache, state, emitted [n_steps, slots]).  A slot goes inactive
    the step its budget hits zero or it samples its EOS token; inactive
    slots repeat their last token (the host emits only to live requests)."""
    temps, eos, key = state["temps"], state["eos"], state["key"]

    def body(carry, i):
        cache, toks, active, budget = carry
        cache, logits = decode_step(params, cache, toks, active, cfg,
                                    compute_dtype)
        nxt = sample_per_slot(logits, jax.random.fold_in(key, i), temps,
                              top_k)
        nxt = jnp.where(active, nxt, toks)
        budget = jnp.where(active, budget - 1, budget)
        active = active & (budget > 0) & (nxt != eos)
        return (cache, nxt, active, budget), nxt

    carry = (cache, state["tokens"], state["active"], state["budget"])
    (cache, toks, active, budget), emitted = jax.lax.scan(
        body, carry, jnp.arange(n_steps))
    state = {"tokens": toks, "active": active, "budget": budget,
             "temps": temps, "eos": eos,
             "key": jax.random.fold_in(key, n_steps)}
    return cache, state, emitted
