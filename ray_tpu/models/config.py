"""Model configs: one TransformerConfig covers the GPT-2, Llama-3 and Mixtral
families (BASELINE.json configs #1-#3).

The reference delegates model definitions to torch/HF; here models are first-class and
TPU-first: static shapes, stacked-layer params for ``lax.scan``, bf16 compute, and
explicit sharding rules (see ``ray_tpu/models/sharding.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

#: Per-chip peak bf16 matmul FLOP/s by device kind — the denominator of
#: every MFU number this repo reports (bench.py headline, the runtime
#: train-observability plane's running MFU, MULTICHIP captures).
PEAK_BF16_FLOPS = {
    "v5 lite": 197e12,   # v5e
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 459e12,
    "v4": 275e12,
    "v6 lite": 918e12,   # trillium
    "v6e": 918e12,
    "cpu": 1e12,         # nominal, for CI runs only
}


def detect_peak_flops(device) -> float:
    """Peak bf16 FLOP/s of one device, keyed on ``device_kind`` (falls
    back to the nominal CPU figure for CI runs)."""
    kind = getattr(device, "device_kind", "cpu").lower()
    for key, val in PEAK_BF16_FLOPS.items():
        if key in kind:
            return val
    return PEAK_BF16_FLOPS["cpu"]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    num_layers: int
    hidden_size: int
    num_heads: int
    num_kv_heads: int           # < num_heads => GQA (Llama-3/Mixtral)
    mlp_size: int
    max_seq_len: int
    # architecture flags
    use_rope: bool = True       # False => learned positional embeddings (GPT-2)
    rope_theta: float = 500_000.0
    use_rmsnorm: bool = True    # False => LayerNorm with bias (GPT-2)
    use_qkv_bias: bool = False  # True => biases on Q/K/V only (Qwen-2)
    use_swiglu: bool = True     # False => GELU MLP (GPT-2)
    tied_embeddings: bool = False
    # MoE (Mixtral): num_experts > 1 enables the sparse MLP
    num_experts: int = 1
    experts_per_token: int = 2
    expert_capacity_factor: float = 1.25
    # numerics
    norm_eps: float = 1e-5
    # attention
    causal: bool = True
    attn_logit_softcap: float = 0.0
    #: "auto" (mha dispatcher: flash on TPU, plain elsewhere), "plain",
    #: "flash" (ops/flash_attention), or "splash" (the pallas splash kernel
    #: with explicit backward block sizes; degrades to "auto" with one
    #: RuntimeWarning when unavailable or the shape doesn't qualify).
    attention_impl: str = "auto"

    @property
    def head_dim(self) -> int:
        return self.hidden_size // self.num_heads

    def num_params(self) -> int:
        """Approximate parameter count (for MFU math)."""
        h, v, L = self.hidden_size, self.vocab_size, self.num_layers
        attn = h * h + 2 * h * (self.num_kv_heads * self.head_dim) + h * h
        if self.num_experts > 1:
            mlp = self.num_experts * 3 * h * self.mlp_size + h * self.num_experts
        else:
            mlp = (3 if self.use_swiglu else 2) * h * self.mlp_size
        emb = v * h * (1 if self.tied_embeddings else 2)
        return L * (attn + mlp) + emb

    def flops_per_token(self, seq_len: Optional[int] = None) -> float:
        """Training FLOPs/token ≈ 6*N_active + attention quadratic term."""
        h, L = self.hidden_size, self.num_layers
        attn = L * (h * h + 2 * h * self.num_kv_heads * self.head_dim + h * h)
        if self.num_experts > 1:
            mlp = L * self.experts_per_token * 3 * h * self.mlp_size
        else:
            mlp = L * (3 if self.use_swiglu else 2) * h * self.mlp_size
        emb = self.vocab_size * h
        n_active = attn + mlp + emb
        s = seq_len or self.max_seq_len
        attn_quad = L * 2 * s * h  # 2*s*h per token for QK^T + AV (causal halves it)
        return 6.0 * n_active + 6.0 * attn_quad


# ---------------------------------------------------------------------------
# Presets
# ---------------------------------------------------------------------------

def gpt2_small(max_seq_len: int = 1024) -> TransformerConfig:
    """GPT-2 124M (BASELINE config #1). Vocab padded to a multiple of 128 for
    MXU-friendly embedding/logit matmuls."""
    return TransformerConfig(
        vocab_size=50304, num_layers=12, hidden_size=768, num_heads=12,
        num_kv_heads=12, mlp_size=3072, max_seq_len=max_seq_len,
        use_rope=False, use_rmsnorm=False, use_swiglu=False,
        tied_embeddings=True, norm_eps=1e-5)


def llama3_8b(max_seq_len: int = 8192) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128256, num_layers=32, hidden_size=4096, num_heads=32,
        num_kv_heads=8, mlp_size=14336, max_seq_len=max_seq_len,
        rope_theta=500_000.0)


def llama3_70b(max_seq_len: int = 8192) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=128256, num_layers=80, hidden_size=8192, num_heads=64,
        num_kv_heads=8, mlp_size=28672, max_seq_len=max_seq_len,
        rope_theta=500_000.0)


def llama_1b(max_seq_len: int = 2048) -> TransformerConfig:
    """~1.2B Llama-style model: fits one chip with optimizer state; used as the
    single-chip bench config."""
    return TransformerConfig(
        vocab_size=32768, num_layers=16, hidden_size=2048, num_heads=16,
        num_kv_heads=8, mlp_size=5632, max_seq_len=max_seq_len)


def llama_400m(max_seq_len: int = 2048) -> TransformerConfig:
    """~0.4B Llama-style model: fits a single 16 GB chip *with* f32 Adam state
    and remat headroom (llama-1b's state alone is ~16 GB — see bench.py's
    memory model). The single-chip bench config."""
    return TransformerConfig(
        vocab_size=32768, num_layers=12, hidden_size=1536, num_heads=12,
        num_kv_heads=6, mlp_size=4096, max_seq_len=max_seq_len)


def mixtral_8x7b(max_seq_len: int = 8192) -> TransformerConfig:
    return TransformerConfig(
        vocab_size=32000, num_layers=32, hidden_size=4096, num_heads=32,
        num_kv_heads=8, mlp_size=14336, max_seq_len=max_seq_len,
        rope_theta=1_000_000.0, num_experts=8, experts_per_token=2)


def gemma2_2b(max_seq_len: int = 8192) -> TransformerConfig:
    """Gemma-2-2B-class: GQA, GeGLU-family MLP, attention logit softcapping
    (the architectural marker of the family), tied embeddings."""
    return TransformerConfig(
        vocab_size=256128, num_layers=26, hidden_size=2304, num_heads=8,
        num_kv_heads=4, mlp_size=9216, max_seq_len=max_seq_len,
        rope_theta=10_000.0, attn_logit_softcap=50.0, tied_embeddings=True)


def qwen2_7b(max_seq_len: int = 8192) -> TransformerConfig:
    """Qwen-2-7B-class: Llama-like with QKV biases (use_qkv_bias marker)."""
    return TransformerConfig(
        vocab_size=152064, num_layers=28, hidden_size=3584, num_heads=28,
        num_kv_heads=4, mlp_size=18944, max_seq_len=max_seq_len,
        rope_theta=1_000_000.0, use_qkv_bias=True)


def tiny(vocab: int = 256, layers: int = 2, hidden: int = 64, heads: int = 4,
         seq: int = 64, experts: int = 1) -> TransformerConfig:
    """Test-size config (CPU mesh)."""
    return TransformerConfig(
        vocab_size=vocab, num_layers=layers, hidden_size=hidden, num_heads=heads,
        num_kv_heads=max(1, heads // 2), mlp_size=hidden * 3, max_seq_len=seq,
        num_experts=experts, experts_per_token=min(2, experts))


PRESETS = {
    "gpt2-124m": gpt2_small,
    "llama3-8b": llama3_8b,
    "llama3-70b": llama3_70b,
    "llama-1b": llama_1b,
    "llama-400m": llama_400m,
    "mixtral-8x7b": mixtral_8x7b,
    "gemma2-2b": gemma2_2b,
    "qwen2-7b": qwen2_7b,
    "tiny": tiny,
}
