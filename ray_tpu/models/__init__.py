"""ray_tpu.models — first-class TPU-native model zoo (GPT-2 / Llama-3 / Mixtral)."""

from .config import (PRESETS, TransformerConfig, gpt2_small, llama3_8b,
                     llama3_70b, llama_1b, mixtral_8x7b, tiny)
from .transformer import (ParallelContext, apply, causal_lm_loss, init_params)

__all__ = ["TransformerConfig", "PRESETS", "gpt2_small", "llama3_8b",
           "llama3_70b", "llama_1b", "mixtral_8x7b", "tiny", "init_params",
           "apply", "causal_lm_loss", "ParallelContext"]
