"""Paged KV cache + prefix caching — vLLM-style block tables, TPU-first.

The reference has no LLM inference engine (SURVEY §2.7: ``@serve.batch`` is
the primitive); this extends ``models/decode.py``'s slot cache with paging so
HBM scales with *actual* sequence lengths instead of ``slots x max_len``
worst case, and identical prompt prefixes share cache pages.

TPU-first shape choices:

* The cache is one static HBM tensor ``[L, num_pages, page, NKV, D]``; a
  sequence's cache is the pages its **block table** row points at
  (``[slots, max_pages]`` int32).  Shapes never change -> jit compiles one
  prefill per length bucket and one decode step, forever — the same
  static-shape discipline as the dense cache.
* Decode gathers each slot's pages with ``jnp.take`` (XLA lowers to dynamic
  slices); attention reads the whole gathered row anyway, so the gather is
  bandwidth-equivalent to the dense cache read.
* Page allocation/refcounting/prefix hashing is **host-side Python** in the
  engine (it is O(pages) per admit/retire, not per token) — the device
  program never sees the free list, only the block table array.
* Prefix caching: full pages of a prompt (page-aligned chunks) are keyed by
  a rolling content hash; an admit that hits reuses those pages read-only
  (refcount++) and prefills only the uncached suffix.  Decode always writes
  to pages at index >= ceil-boundary of the reused prefix, which are
  private by construction — no copy-on-write path is ever needed.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from .config import TransformerConfig
from .transformer import Params, _norm, lm_head_weight

from .decode import (_mlp, _proj_out, _qkv, sample_per_slot)

PagedKVCache = Dict[str, jnp.ndarray]


def init_paged_cache(cfg: TransformerConfig, num_pages: int, page_size: int,
                     num_slots: int, max_pages_per_slot: int,
                     dtype=jnp.bfloat16) -> PagedKVCache:
    """Allocate the paged HBM cache + block tables.

    Page 0 is reserved as the null page (block tables point unused entries
    at it); allocators hand out pages 1..num_pages-1.
    """
    shape = (cfg.num_layers, num_pages, page_size, cfg.num_kv_heads,
             cfg.head_dim)
    return {
        "k": jnp.zeros(shape, dtype),
        "v": jnp.zeros(shape, dtype),
        "block_table": jnp.zeros((num_slots, max_pages_per_slot), jnp.int32),
        "length": jnp.zeros((num_slots,), jnp.int32),
    }


def paged_cache_bytes(cfg: TransformerConfig, num_pages: int, page_size: int,
                      dtype_bytes: int = 2) -> int:
    return (2 * cfg.num_layers * num_pages * page_size * cfg.num_kv_heads
            * cfg.head_dim * dtype_bytes)


# ---------------------------------------------------------------------------
# Device programs
# ---------------------------------------------------------------------------

def paged_prefill(params: Params, cache: PagedKVCache, tokens: jnp.ndarray,
                  lengths: jnp.ndarray, slot_ids: jnp.ndarray,
                  start_pos: jnp.ndarray, cfg: TransformerConfig,
                  compute_dtype=jnp.bfloat16
                  ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """Causal forward over right-padded prompt suffixes; K/V land in pages.

    tokens:   [B, S] suffix tokens (positions start_pos .. start_pos+len)
    lengths:  [B] true suffix lengths (<= S)
    slot_ids: [B] slot whose block table routes the writes
    start_pos:[B] absolute position of tokens[:, 0] (0 unless a cached
              prefix was reused; reused pages are NOT written here)
    Returns (cache, last-real-token logits [B, V] f32).

    Attention inside the suffix is pure causal self-attention PLUS reads of
    the reused prefix pages (positions < start_pos) via the block table.
    """
    b, s = tokens.shape
    page = cache["k"].shape[2]
    max_pages = cache["block_table"].shape[1]
    cast = compute_dtype
    x = params["embed"]["tokens"][tokens].astype(cast)
    positions = start_pos[:, None] + jnp.arange(s)[None]        # [B, S]
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][
            jnp.minimum(positions, cfg.max_seq_len - 1)].astype(cast)
    bt = cache["block_table"][slot_ids]                          # [B, MP]
    # scatter coordinates for every suffix position
    page_idx = bt[jnp.arange(b)[:, None],
                  jnp.minimum(positions // page, max_pages - 1)]  # [B, S]
    page_off = positions % page                                  # [B, S]
    scale = cfg.head_dim ** -0.5
    reps = cfg.num_heads // cfg.num_kv_heads
    kv_span = max_pages * page
    # gathered-cache positions each query may read: absolute pos < q pos
    abs_kv_pos = jnp.arange(kv_span)[None]                       # [1, MP*page]
    valid_write = (jnp.arange(s)[None] < lengths[:, None])       # [B, S]

    def body(x, layer):
        lp, k_pages, v_pages = layer    # [P, page, NKV, D]
        y = _norm(x, lp["attn_norm"], cfg)
        q, k, v = _qkv(y, lp["attn"], cfg, positions)
        # write suffix K/V into pages first, then attend over the gathered
        # row (prefix pages + own suffix) with a causal mask on absolute
        # positions — one code path covers both.
        flat_pi = page_idx.reshape(-1)
        flat_po = page_off.reshape(-1)
        keep = valid_write.reshape(-1)
        safe_pi = jnp.where(keep, flat_pi, 0)  # dump padding into null page
        k_pages = k_pages.at[safe_pi, flat_po].set(
            k.reshape(b * s, cfg.num_kv_heads, -1).astype(k_pages.dtype),
            mode="drop")
        v_pages = v_pages.at[safe_pi, flat_po].set(
            v.reshape(b * s, cfg.num_kv_heads, -1).astype(v_pages.dtype),
            mode="drop")
        kg = jnp.take(k_pages, bt, axis=0)   # [B, MP, page, NKV, D]
        vg = jnp.take(v_pages, bt, axis=0)
        kg = kg.reshape(b, kv_span, cfg.num_kv_heads, cfg.head_dim)
        vg = vg.reshape(b, kv_span, cfg.num_kv_heads, cfg.head_dim)
        qh = q.reshape(b, s, cfg.num_kv_heads, reps, cfg.head_dim)
        scores = jnp.einsum("bsgrd,bmgd->bgrsm", qh.astype(jnp.float32),
                            kg.astype(jnp.float32)) * scale
        if cfg.attn_logit_softcap:
            c = cfg.attn_logit_softcap
            scores = c * jnp.tanh(scores / c)
        # causal on ABSOLUTE positions: [B, S, span] -> [B, 1, 1, S, span]
        causal = abs_kv_pos[:, None, :] <= positions[:, :, None]
        scores = jnp.where(causal[:, None, None, :, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bgrsm,bmgd->bsgrd", probs, vg.astype(jnp.float32))
        attn = attn.reshape(b, s, cfg.num_heads * cfg.head_dim)
        x = x + _proj_out(attn.astype(cast), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        return x, (k_pages, v_pages)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    last = jnp.take_along_axis(
        x, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1)[:, 0]
    logits = (last @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    new_len = start_pos + lengths
    cache = {
        "k": k_new, "v": v_new,
        "block_table": cache["block_table"],
        "length": cache["length"].at[slot_ids].set(new_len),
    }
    return cache, logits


def paged_decode_step(params: Params, cache: PagedKVCache,
                      tokens: jnp.ndarray, active: jnp.ndarray,
                      cfg: TransformerConfig, compute_dtype=jnp.bfloat16
                      ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """One token per active slot, attention over block-table pages."""
    n_slots = tokens.shape[0]
    page = cache["k"].shape[2]
    max_pages = cache["block_table"].shape[1]
    kv_span = max_pages * page
    cast = compute_dtype
    lengths = cache["length"]
    bt = cache["block_table"]                                    # [S, MP]
    x = params["embed"]["tokens"][tokens][:, None].astype(cast)
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][
            jnp.minimum(lengths, cfg.max_seq_len - 1)][:, None].astype(cast)
    positions = lengths[:, None]
    scale = cfg.head_dim ** -0.5
    reps = cfg.num_heads // cfg.num_kv_heads
    write_page = bt[jnp.arange(n_slots),
                    jnp.minimum(lengths // page, max_pages - 1)]  # [S]
    write_off = lengths % page
    pos_mask = (jnp.arange(kv_span)[None] <= lengths[:, None])   # [S, span]

    def body(x, layer):
        lp, k_pages, v_pages = layer
        y = _norm(x, lp["attn_norm"], cfg)
        q, k, v = _qkv(y, lp["attn"], cfg, positions)
        safe_page = jnp.where(active, write_page, 0)
        k_pages = k_pages.at[safe_page, write_off].set(
            k[:, 0].astype(k_pages.dtype), mode="drop")
        v_pages = v_pages.at[safe_page, write_off].set(
            v[:, 0].astype(v_pages.dtype), mode="drop")
        kg = jnp.take(k_pages, bt, axis=0).reshape(
            n_slots, kv_span, cfg.num_kv_heads, cfg.head_dim)
        vg = jnp.take(v_pages, bt, axis=0).reshape(
            n_slots, kv_span, cfg.num_kv_heads, cfg.head_dim)
        qh = q[:, 0].reshape(n_slots, cfg.num_kv_heads, reps, cfg.head_dim)
        scores = jnp.einsum("sgrd,smgd->sgrm", qh.astype(jnp.float32),
                            kg.astype(jnp.float32)) * scale
        if cfg.attn_logit_softcap:
            c = cfg.attn_logit_softcap
            scores = c * jnp.tanh(scores / c)
        scores = jnp.where(pos_mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("sgrm,smgd->sgrd", probs, vg.astype(jnp.float32))
        attn = attn.reshape(n_slots, 1, cfg.num_heads * cfg.head_dim)
        x = x + _proj_out(attn.astype(cast), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        return x, (k_pages, v_pages)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    logits = (x[:, 0] @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    cache = {
        "k": k_new, "v": v_new,
        "block_table": cache["block_table"],
        "length": jnp.where(active, lengths + 1, lengths),
    }
    return cache, logits


def paged_verify_window(params: Params, cache: PagedKVCache,
                        tokens: jnp.ndarray, active: jnp.ndarray,
                        cfg: TransformerConfig, compute_dtype=jnp.bfloat16
                        ) -> Tuple[PagedKVCache, jnp.ndarray]:
    """Speculative-decode verify: a k-token window per slot over the paged
    cache (``speculative.verify_window`` generalized to block tables).

    tokens: [slots, k] int32 — token j sits at absolute position
    ``length[s] + j``, scattered through slot s's block-table row.
    Returns (cache, logits [slots, k, V] f32); ``length`` advances by k
    for active slots.  Callers roll ``length`` back to the accepted
    prefix afterwards — rollback is a length reset ONLY, and it is
    page-exact by construction: every window position lands in a page
    the slot's block table already owns (private pages at index >= the
    shared-prefix boundary), so rejected positions become unread garbage
    the next round overwrites.  Writes for inactive slots and positions
    past the block-table span are dumped into the reserved null page 0
    (same discipline as ``paged_decode_step``) — an inactive slot's old
    pages may already belong to another sequence.
    """
    n_slots, kwin = tokens.shape
    page = cache["k"].shape[2]
    max_pages = cache["block_table"].shape[1]
    kv_span = max_pages * page
    cast = compute_dtype
    lengths = cache["length"]                                    # [slots]
    bt = cache["block_table"]                                    # [S, MP]
    x = params["embed"]["tokens"][tokens].astype(cast)           # [S,k,H]
    positions = lengths[:, None] + jnp.arange(kwin)[None]        # [S,k]
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][
            jnp.minimum(positions, cfg.max_seq_len - 1)].astype(cast)
    scale = cfg.head_dim ** -0.5
    reps = cfg.num_heads // cfg.num_kv_heads
    row = jnp.arange(n_slots)[:, None]
    page_idx = bt[row, jnp.minimum(positions // page, max_pages - 1)]
    page_off = positions % page
    valid = active[:, None] & (positions < kv_span)              # [S,k]
    safe_pi = jnp.where(valid, page_idx, 0).reshape(-1)
    flat_po = page_off.reshape(-1)
    # query j may read absolute positions <= length+j (its own position)
    causal = (jnp.arange(kv_span)[None, None]
              <= positions[:, :, None])            # [slots, k, span]

    def body(x, layer):
        lp, k_pages, v_pages = layer
        y = _norm(x, lp["attn_norm"], cfg)
        q, kk, vv = _qkv(y, lp["attn"], cfg, positions)  # [S,k,N*,D]
        k_pages = k_pages.at[safe_pi, flat_po].set(
            kk.reshape(n_slots * kwin, cfg.num_kv_heads,
                       -1).astype(k_pages.dtype), mode="drop")
        v_pages = v_pages.at[safe_pi, flat_po].set(
            vv.reshape(n_slots * kwin, cfg.num_kv_heads,
                       -1).astype(v_pages.dtype), mode="drop")
        kg = jnp.take(k_pages, bt, axis=0).reshape(
            n_slots, kv_span, cfg.num_kv_heads, cfg.head_dim)
        vg = jnp.take(v_pages, bt, axis=0).reshape(
            n_slots, kv_span, cfg.num_kv_heads, cfg.head_dim)
        qh = q.reshape(n_slots, kwin, cfg.num_kv_heads, reps, cfg.head_dim)
        scores = jnp.einsum("skgrd,smgd->skgrm", qh.astype(jnp.float32),
                            kg.astype(jnp.float32)) * scale
        if cfg.attn_logit_softcap:
            c = cfg.attn_logit_softcap
            scores = c * jnp.tanh(scores / c)
        scores = jnp.where(causal[:, :, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("skgrm,smgd->skgrd", probs,
                          vg.astype(jnp.float32))
        attn = attn.reshape(n_slots, kwin, cfg.num_heads * cfg.head_dim)
        x = x + _proj_out(attn.astype(cast), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        return x, (k_pages, v_pages)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    logits = (x @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    cache = {
        "k": k_new, "v": v_new,
        "block_table": bt,
        "length": jnp.where(active,
                            jnp.minimum(lengths + kwin, kv_span), lengths),
    }
    return cache, logits


def paged_decode_loop(params: Params, cache: PagedKVCache,
                      tokens: jnp.ndarray, active: jnp.ndarray,
                      temperature: jnp.ndarray, key: jax.Array,
                      n_steps: int, cfg: TransformerConfig, top_k: int = 0,
                      compute_dtype=jnp.bfloat16
                      ) -> Tuple[PagedKVCache, jnp.ndarray, jnp.ndarray]:
    """``n_steps`` paged decode+sample steps in one compiled scan."""

    def body(carry, i):
        cache, toks = carry
        cache, logits = paged_decode_step(params, cache, toks, active, cfg,
                                          compute_dtype)
        nxt = sample_per_slot(logits, jax.random.fold_in(key, i),
                              temperature, top_k)
        nxt = jnp.where(active, nxt, toks)
        return (cache, nxt), nxt

    (cache, tokens), emitted = jax.lax.scan(
        body, (cache, tokens), jnp.arange(n_steps))
    return cache, tokens, emitted


def paged_prefill_admit(params: Params, cache: PagedKVCache, state,
                        tokens: jnp.ndarray, lengths: jnp.ndarray,
                        slot_ids: jnp.ndarray, start_pos: jnp.ndarray,
                        bt_rows: jnp.ndarray, temps: jnp.ndarray,
                        budgets: jnp.ndarray, eos: jnp.ndarray,
                        real_mask: jnp.ndarray, cfg: TransformerConfig,
                        top_k: int = 0, compute_dtype=jnp.bfloat16):
    """Paged admit in one program: write the admitted slots' block-table
    rows, prefill the uncached suffixes, sample, merge into the decode
    state (``decode.init_decode_state`` layout).  bt_rows: [B, MP] int32."""
    from .decode import _merge_admit

    cache = dict(cache)
    cache["block_table"] = cache["block_table"].at[slot_ids].set(bt_rows)
    cache, logits = paged_prefill(params, cache, tokens, lengths, slot_ids,
                                  start_pos, cfg, compute_dtype)
    first = sample_per_slot(logits, state["key"], temps, top_k)
    state = _merge_admit(state, first, slot_ids, temps, budgets, eos,
                         real_mask)
    return cache, state, first


def paged_decode_state_loop(params: Params, cache: PagedKVCache, state,
                            n_steps: int, cfg: TransformerConfig,
                            top_k: int = 0, compute_dtype=jnp.bfloat16):
    """Paged twin of ``decode.decode_state_loop`` (on-device active decay)."""
    temps, eos, key = state["temps"], state["eos"], state["key"]

    def body(carry, i):
        cache, toks, active, budget = carry
        cache, logits = paged_decode_step(params, cache, toks, active, cfg,
                                          compute_dtype)
        nxt = sample_per_slot(logits, jax.random.fold_in(key, i), temps,
                              top_k)
        nxt = jnp.where(active, nxt, toks)
        budget = jnp.where(active, budget - 1, budget)
        active = active & (budget > 0) & (nxt != eos)
        return (cache, nxt, active, budget), nxt

    carry = (cache, state["tokens"], state["active"], state["budget"])
    (cache, toks, active, budget), emitted = jax.lax.scan(
        body, carry, jnp.arange(n_steps))
    state = {"tokens": toks, "active": active, "budget": budget,
             "temps": temps, "eos": eos,
             "key": jax.random.fold_in(key, n_steps)}
    return cache, state, emitted


# ---------------------------------------------------------------------------
# Host-side page allocator + prefix cache
# ---------------------------------------------------------------------------

class PageAllocator:
    """Free-list page allocator with refcounts (page 0 = reserved null page).

    Prefix sharing gives pages refcount > 1; a page returns to the free list
    when its count hits zero.  Pure host Python — called per admit/retire,
    never per token."""

    def __init__(self, num_pages: int):
        self.num_pages = num_pages
        self._free: List[int] = list(range(num_pages - 1, 0, -1))
        self._refs: Dict[int, int] = {}

    def available(self) -> int:
        return len(self._free)

    def used(self) -> int:
        """Pages currently referenced (the KV-utilization numerator; page 0
        is the reserved null page and counts as neither used nor free)."""
        return self.num_pages - 1 - len(self._free)

    def alloc(self, n: int) -> Optional[List[int]]:
        if n > len(self._free):
            return None
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def incref(self, pages: Sequence[int]):
        for p in pages:
            self._refs[p] += 1

    def release(self, pages: Sequence[int]):
        for p in pages:
            self._refs[p] -= 1
            if self._refs[p] == 0:
                del self._refs[p]
                self._free.append(p)


class PrefixCache:
    """Content-hash -> page mapping for full-page prompt prefixes.

    A chunk key is the rolling hash of ALL tokens up to the end of that page
    (so two prompts share page i only if they agree on every token before
    it).  Eviction: a cached page with refcount 1 (cache-only) is reclaimed
    lazily when the allocator runs dry."""

    def __init__(self, allocator: PageAllocator, page_size: int):
        self.alloc = allocator
        self.page = page_size
        self._map: Dict[bytes, int] = {}        # chunk hash -> page id
        self._lru: List[bytes] = []
        # FIRST-page chunk keys (insertion-ordered): the bounded routing
        # digest reads these — a request can only start reusing at page 0,
        # so deeper chunks add no routing signal
        self._first: Dict[bytes, None] = {}
        # lookup accounting (serve observability + bench_llm read these):
        # a lookup is a hit when >= 1 page was reused
        self.lookups = 0
        self.hits = 0
        self.tokens_reused = 0
        self.evictions = 0

    @staticmethod
    def _hash(tokens: Sequence[int]) -> bytes:
        return hashlib.blake2b(
            b"".join(int(t).to_bytes(4, "little") for t in tokens),
            digest_size=16).digest()

    def match_prefix(self, tokens: Sequence[int],
                     max_pages: Optional[int] = None
                     ) -> Tuple[int, List[int]]:
        """Longest reusable page-aligned prefix.  Returns (n_tokens_reused,
        page_ids) with refcounts already taken.  ``max_pages`` caps the
        reuse (the LLM engine must leave >= 1 prompt token to prefill for
        logits) — capping HERE keeps the hit/tokens_reused counters in
        agreement with what the caller actually reuses."""
        pages: List[int] = []
        n_full = len(tokens) // self.page
        if max_pages is not None:
            n_full = min(n_full, max_pages)
        reused = 0
        for i in range(n_full):
            key = self._hash(tokens[:(i + 1) * self.page])
            pid = self._map.get(key)
            if pid is None:
                break
            pages.append(pid)
            reused += self.page
        if pages:
            self.alloc.incref(pages)
        return reused, pages

    def count_lookup(self, tokens_reused: int):
        """Account one admission's prefix reuse — called once per ADMITTED
        request, not inside match_prefix: an arena-full backpressure retry
        re-runs the lookup and must not double-count, or hit_rate inflates
        exactly when the engine is under KV memory pressure."""
        self.lookups += 1
        if tokens_reused > 0:
            self.hits += 1
            self.tokens_reused += tokens_reused

    def stats(self) -> Dict[str, float]:
        return {"lookups": self.lookups, "hits": self.hits,
                "hit_rate": self.hits / self.lookups if self.lookups else 0.0,
                "tokens_reused": self.tokens_reused,
                "cached_pages": len(self._map),
                "evictions": self.evictions}

    def insert(self, tokens: Sequence[int], page_ids: Sequence[int]):
        """Register freshly-filled full pages for future reuse.  The cache
        holds one ref per registered page (released on eviction)."""
        n_full = min(len(tokens) // self.page, len(page_ids))
        for i in range(n_full):
            key = self._hash(tokens[:(i + 1) * self.page])
            if key in self._map:
                continue
            self._map[key] = page_ids[i]
            self.alloc.incref([page_ids[i]])
            self._lru.append(key)
            if i == 0:
                self._first[key] = None

    def evict_some(self, n: int = 8) -> int:
        """Drop up to n oldest cached chunks (returns pages whose only ref
        was the cache)."""
        dropped = 0
        while self._lru and dropped < n:
            key = self._lru.pop(0)
            pid = self._map.pop(key, None)
            self._first.pop(key, None)
            if pid is not None:
                self.alloc.release([pid])
                dropped += 1
        self.evictions += dropped
        return dropped

    def first_page_digest(self, cap: int = 32) -> List[str]:
        """Bounded digest of the hot first-page chunks for cache-aware
        routing: the NEWEST ``cap`` first-page keys as 8-hex-char (32-bit)
        prefixes of the chunk hash.  A router computes the same truncated
        hash over a request's first ``page`` tokens and scores replicas by
        membership — 32 bits keeps the heartbeat payload small while
        making a cross-prompt collision (a spurious routing *preference*,
        never a correctness issue) vanishingly rare at digest sizes."""
        keys = list(self._first)[-max(0, cap):]
        return [k.hex()[:8] for k in keys]
