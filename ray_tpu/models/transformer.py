"""Decoder-only transformer: one implementation for GPT-2 / Llama-3 / Mixtral.

TPU-first design choices:
* **Stacked layer params + lax.scan** — compile time independent of depth; XLA sees one
  block body (the reference's torch models unroll layers in Python).
* **bf16 compute, fp32 params/optimizer** — matmuls hit the MXU in bf16; the cast sits
  next to each einsum so XLA fuses it.
* **Static shapes everywhere** — no data-dependent control flow inside jit.
* Attention dispatches to plain XLA / Pallas flash / ring attention (`sp` axis)
  based on a `ParallelContext`.

Params are a plain pytree (dict) so sharding rules (models/sharding.py) are specs over
the same tree structure.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax.ad_checkpoint import checkpoint_name

from ..ops import moe as moe_ops
from ..ops.attention import attend, mha
from .config import TransformerConfig

Params = Dict[str, Any]

# Tensors tagged with checkpoint_name inside the block: the big matmul outputs
# whose recompute dominates the remat replay.  "save_acts" keeps all of them —
# the backward then replays only norms/elementwise — at ~(3*h + 2*m) bf16
# bytes/token/layer of HBM.  "save_mlp" keeps just the MLP half (the FLOP bulk)
# when the full set doesn't fit.
REMAT_SAVE_NAMES = ("attn_q", "attn_k", "attn_v", "attn_out", "attn_lse",
                    "mlp_gate", "mlp_up", "mlp_pre")


def remat_policy(remat: Union[bool, str, None]):
    """Map a remat spec to (enabled, jax.checkpoint policy).

    - False/None: no rematerialization (fastest when activations fit HBM)
    - True / "full": save nothing, replay the whole block (min memory)
    - "save_acts": save the named matmul outputs above (replay ~= norms only)
    - "save_mlp": save only the MLP intermediates
    - "dots": XLA-style save-all-matmul-outputs policy
    """
    if remat is None or remat is False:
        return False, None
    if remat is True or remat == "full":
        return True, jax.checkpoint_policies.nothing_saveable
    if remat == "save_acts":
        return True, jax.checkpoint_policies.save_only_these_names(
            *REMAT_SAVE_NAMES)
    if remat == "save_mlp":
        return True, jax.checkpoint_policies.save_only_these_names(
            "mlp_gate", "mlp_up", "mlp_pre")
    if remat == "dots":
        return True, jax.checkpoint_policies.dots_saveable
    raise ValueError(f"unknown remat policy {remat!r}")


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """How to run attention/MoE under a mesh. None mesh = single device."""
    mesh: Optional[Any] = None
    sp_axis: Optional[str] = None     # sequence-parallel axis name (ring attn)
    batch_axes: Tuple[str, ...] = ("dp",)
    # True when the caller is ALREADY inside a shard_map where sp_axis is
    # manual (the pipeline): ring attention then runs its per-shard body
    # directly instead of opening a nested shard_map.
    manual_collectives: bool = False

    @property
    def use_ring(self) -> bool:
        return (self.mesh is not None and self.sp_axis is not None
                and self.mesh.shape.get(self.sp_axis, 1) > 1)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: TransformerConfig,
                dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, m, L = cfg.num_heads, cfg.num_kv_heads, cfg.mlp_size, cfg.num_layers
    keys = iter(jax.random.split(key, 32))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype) * (fan_in ** -0.5)).astype(dtype)

    def norm_p():
        p = {"scale": jnp.ones((L, h), dtype)}
        if not cfg.use_rmsnorm:
            p["bias"] = jnp.zeros((L, h), dtype)
        return p

    blocks: Params = {
        "attn_norm": norm_p(),
        "attn": {
            "wq": dense(next(keys), (L, h, nh * hd), h),
            "wk": dense(next(keys), (L, h, nkv * hd), h),
            "wv": dense(next(keys), (L, h, nkv * hd), h),
            "wo": dense(next(keys), (L, nh * hd, h), nh * hd),
        },
        "mlp_norm": norm_p(),
    }
    if not cfg.use_rmsnorm or cfg.use_qkv_bias:
        # GPT-2 style (all biases) or Qwen-2 style (Q/K/V biases only)
        blocks["attn"]["bq"] = jnp.zeros((L, nh * hd), dtype)
        blocks["attn"]["bk"] = jnp.zeros((L, nkv * hd), dtype)
        blocks["attn"]["bv"] = jnp.zeros((L, nkv * hd), dtype)
    if not cfg.use_rmsnorm:
        blocks["attn"]["bo"] = jnp.zeros((L, h), dtype)
    if cfg.num_experts > 1:
        e = cfg.num_experts
        blocks["moe"] = {
            "router": dense(next(keys), (L, h, e), h),
            "w_gate": dense(next(keys), (L, e, h, m), h),
            "w_in": dense(next(keys), (L, e, h, m), h),
            "w_out": dense(next(keys), (L, e, m, h), m),
        }
    else:
        mlp: Params = {
            "w_in": dense(next(keys), (L, h, m), h),
            "w_out": dense(next(keys), (L, m, h), m),
        }
        if cfg.use_swiglu:
            mlp["w_gate"] = dense(next(keys), (L, h, m), h)
        else:
            mlp["b_in"] = jnp.zeros((L, m), dtype)
            mlp["b_out"] = jnp.zeros((L, h), dtype)
        blocks["mlp"] = mlp

    params: Params = {
        "embed": {"tokens": (jax.random.normal(next(keys), (cfg.vocab_size, h),
                                               dtype) * 0.02)},
        "blocks": blocks,
        "final_norm": {"scale": jnp.ones((h,), dtype)},
    }
    if not cfg.use_rope:
        params["embed"]["pos"] = (
            jax.random.normal(next(keys), (cfg.max_seq_len, h), dtype) * 0.01)
    if not cfg.use_rmsnorm:
        params["final_norm"]["bias"] = jnp.zeros((h,), dtype)
    if not cfg.tied_embeddings:
        params["lm_head"] = dense(next(keys), (h, cfg.vocab_size), h)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def _norm(x, p, cfg: TransformerConfig):
    x32 = x.astype(jnp.float32)
    if cfg.use_rmsnorm:
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True)
                                  + cfg.norm_eps)
        return (x32 * p["scale"].astype(jnp.float32)).astype(x.dtype)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    x32 = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
    return (x32 * p["scale"] + p["bias"]).astype(x.dtype)


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """x: [B, S, H, D]; positions: [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention_block(x, p, cfg: TransformerConfig, positions, pctx: ParallelContext):
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    cast = x.dtype
    q = x @ p["wq"].astype(cast)
    k = x @ p["wk"].astype(cast)
    v = x @ p["wv"].astype(cast)
    if "bq" in p:
        q, k, v = q + p["bq"].astype(cast), k + p["bk"].astype(cast), v + p["bv"].astype(cast)
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nkv, hd)
    v = v.reshape(b, s, nkv, hd)
    if cfg.use_rope:
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    q = checkpoint_name(q, "attn_q")
    k = checkpoint_name(k, "attn_k")
    v = checkpoint_name(v, "attn_v")
    if pctx.use_ring and pctx.manual_collectives:
        from ..ops.ring_attention import _ring_attn_shard
        out = _ring_attn_shard(q, k, v, pctx.sp_axis, causal=cfg.causal,
                               logit_softcap=cfg.attn_logit_softcap)
    elif pctx.use_ring:
        from ..ops.ring_attention import ring_attention
        out = ring_attention(q, k, v, pctx.mesh, pctx.sp_axis,
                             causal=cfg.causal, batch_axes=pctx.batch_axes,
                             logit_softcap=cfg.attn_logit_softcap)
    else:
        out = None
        impl = getattr(cfg, "attention_impl", "auto")
        if impl == "splash":
            from ..ops.splash_attention import splash_mha
            out = splash_mha(q, k, v, causal=cfg.causal,
                             logit_softcap=cfg.attn_logit_softcap,
                             mesh=pctx.mesh, batch_axes=pctx.batch_axes,
                             manual=pctx.manual_collectives)
        elif impl == "plain":
            out = attend(q, k, v, causal=cfg.causal,
                         logit_softcap=cfg.attn_logit_softcap)
        elif impl == "flash" and cfg.attn_logit_softcap == 0.0:
            from ..ops.flash_attention import flash_attention
            out = flash_attention(q, k, v, causal=cfg.causal)
        if out is None:  # "auto", or splash/flash declined this call
            out = mha(q, k, v, causal=cfg.causal,
                      logit_softcap=cfg.attn_logit_softcap)
    out = checkpoint_name(out, "attn_out")
    out = out.reshape(b, s, nh * hd) @ p["wo"].astype(cast)
    if "bo" in p:
        out = out + p["bo"].astype(cast)
    return out


def _mlp_block(x, p, cfg: TransformerConfig):
    cast = x.dtype
    if cfg.use_swiglu:
        gate = checkpoint_name(x @ p["w_gate"].astype(cast), "mlp_gate")
        up = checkpoint_name(x @ p["w_in"].astype(cast), "mlp_up")
        return (jax.nn.silu(gate) * up) @ p["w_out"].astype(cast)
    hmid = x @ p["w_in"].astype(cast) + p["b_in"].astype(cast)
    hmid = checkpoint_name(hmid, "mlp_pre")
    hmid = jax.nn.gelu(hmid)
    return hmid @ p["w_out"].astype(cast) + p["b_out"].astype(cast)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def block_forward(x: jnp.ndarray, layer_params: Params, cfg: TransformerConfig,
                  positions: jnp.ndarray,
                  pctx: ParallelContext = ParallelContext()):
    """One transformer block: x [B, S, H] -> (x, moe aux loss).  Shared by the
    layer scan below and the pipeline-parallel stage loop
    (parallel/pipeline.py)."""
    attn_out = _attention_block(
        _norm(x, layer_params["attn_norm"], cfg), layer_params["attn"],
        cfg, positions, pctx)
    x = x + attn_out
    y = _norm(x, layer_params["mlp_norm"], cfg)
    if cfg.num_experts > 1:
        out, aux = moe_ops.moe_mlp(
            y, layer_params["moe"]["router"], layer_params["moe"]["w_gate"],
            layer_params["moe"]["w_in"], layer_params["moe"]["w_out"],
            cfg.experts_per_token, cfg.expert_capacity_factor)
    else:
        out, aux = _mlp_block(y, layer_params["mlp"], cfg), jnp.zeros((), jnp.float32)
    return x + out, aux


def embed_tokens(params: Params, tokens: jnp.ndarray, cfg: TransformerConfig,
                 compute_dtype=jnp.bfloat16) -> jnp.ndarray:
    """Token (+ learned positional) embedding: [B, S] -> [B, S, H]."""
    x = params["embed"]["tokens"][tokens].astype(compute_dtype)
    if not cfg.use_rope:
        s = tokens.shape[1]
        x = x + params["embed"]["pos"][:s][None].astype(compute_dtype)
    return x


def apply_trunk(params: Params, tokens: jnp.ndarray, cfg: TransformerConfig,
                pctx: ParallelContext = ParallelContext(),
                compute_dtype=jnp.bfloat16,
                remat: Union[bool, str, None] = False
                ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """tokens: [B, S] int32 -> (final hidden states [B, S, H], aux dict).

    The trunk stops before the LM head so losses can run the head blockwise
    (see ``chunked_cross_entropy``) without ever materializing [B, S, V]."""
    b, s = tokens.shape
    x = embed_tokens(params, tokens, cfg, compute_dtype)
    # Positions are global sequence positions; under jit with a sequence-sharded
    # batch XLA partitions this computation (only ring attention, which runs in
    # shard_map, handles per-shard offsets itself).
    positions = jnp.arange(s)

    def scan_body(x, layer_params):
        return block_forward(x, layer_params, cfg, positions, pctx)

    enabled, policy = remat_policy(remat)
    if enabled:
        # Per-layer rematerialization: backward recomputes one block at a time,
        # so peak activation memory is O(saved names) in depth (HBM is the
        # bottleneck — trade FLOPs for memory). The policy picks which matmul
        # outputs survive; "save_acts" makes the replay nearly free while
        # keeping ~1/3 of the no-remat activation footprint.
        scan_body = jax.checkpoint(scan_body, policy=policy)

    x, aux_losses = jax.lax.scan(scan_body, x, params["blocks"])
    x = _norm(x, params["final_norm"], cfg)
    return x, {"moe_aux_loss": aux_losses.mean()}


def lm_head_weight(params: Params, cfg: TransformerConfig, dtype) -> jnp.ndarray:
    """[H, V] head weight (tied embedding transpose or separate lm_head)."""
    if cfg.tied_embeddings:
        return params["embed"]["tokens"].T.astype(dtype)
    return params["lm_head"].astype(dtype)


def apply(params: Params, tokens: jnp.ndarray, cfg: TransformerConfig,
          pctx: ParallelContext = ParallelContext(),
          compute_dtype=jnp.bfloat16,
          remat: Union[bool, str, None] = False
          ) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """tokens: [B, S] int32 -> (logits [B, S, V] f32, aux dict)."""
    x, aux = apply_trunk(params, tokens, cfg, pctx, compute_dtype, remat=remat)
    logits = x @ lm_head_weight(params, cfg, x.dtype)
    return logits.astype(jnp.float32), aux


def chunked_cross_entropy(x: jnp.ndarray, w: jnp.ndarray,
                          targets: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """Blockwise LM-head + softmax cross entropy: peak memory O(B*chunk*V)
    instead of O(B*S*V).

    The f32 [batch, seq, vocab] logits tensor is what OOMed the round-1 bench
    (llama-1b: 8*2048*32768*4B = 2 GiB forward + the same again in backward).
    Here the head matmul runs per sequence-chunk inside a rematerialized
    ``lax.scan``: forward keeps only the per-token NLL, backward recomputes one
    chunk's logits at a time.  MXU accumulation stays f32 via
    ``preferred_element_type`` so numerics match the unchunked f32 path.

    The backward is a hand-written VJP (not AD through a remat scan): forward
    saves only the per-token lse [B, S] f32; backward recomputes each chunk's
    logits once and forms d_logits = (softmax - onehot) * g analytically — the
    onehot is an iota-compare XLA fuses into the elementwise graph, so neither
    pass ever materializes more than one [B, chunk, V] tile, and the max/sum
    replay the generic remat path did is gone.

    x: [B, S, H] (compute dtype), w: [H, V], targets: [B, S] int. -> nll [B, S] f32.
    """
    s = x.shape[1]
    if s % chunk != 0:
        # Static shapes only — shrink to the largest divisor of s instead of
        # silently materializing the full [B,S,V] logits (the round-1 OOM).
        chunk = next((c for c in range(min(chunk, s), 0, -1) if s % c == 0), s)
    return _chunked_ce(x, w, targets, chunk)


def _ce_chunks(x, targets, chunk):
    b, s, h = x.shape
    n = s // chunk
    xs = x.reshape(b, n, chunk, h).swapaxes(0, 1)           # [n, B, C, H]
    ts = targets.reshape(b, n, chunk).swapaxes(0, 1)        # [n, B, C]
    return xs, ts


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _chunked_ce(x, w, targets, chunk):
    return _chunked_ce_fwd(x, w, targets, chunk)[0]


def _chunked_ce_fwd(x, w, targets, chunk):
    b, s, _ = x.shape
    xs, ts = _ce_chunks(x, targets, chunk)

    def body(carry, xt):
        xc, tc = xt
        logits = jnp.einsum("bch,hv->bcv", xc, w,
                            preferred_element_type=jnp.float32)
        lse = jax.nn.logsumexp(logits, axis=-1)
        ll = jnp.take_along_axis(logits, tc[..., None], axis=-1)[..., 0]
        return carry, (lse, lse - ll)

    _, (lses, nll) = jax.lax.scan(body, jnp.zeros((), jnp.float32), (xs, ts))
    nll = nll.swapaxes(0, 1).reshape(b, s)
    lse = lses.swapaxes(0, 1).reshape(b, s)
    return nll, (x, w, targets, lse)


def _chunked_ce_bwd(chunk, res, g):
    x, w, targets, lse = res
    b, s, h = x.shape
    v = w.shape[1]
    xs, ts = _ce_chunks(x, targets, chunk)
    gs = g.reshape(b, s // chunk, chunk).swapaxes(0, 1)     # [n, B, C] f32
    ls = lse.reshape(b, s // chunk, chunk).swapaxes(0, 1)

    def body(dw, xt):
        xc, tc, gc, lc = xt
        logits = jnp.einsum("bch,hv->bcv", xc, w,
                            preferred_element_type=jnp.float32)
        p = jnp.exp(logits - lc[..., None])
        onehot = (jax.lax.broadcasted_iota(jnp.int32, logits.shape, 2)
                  == tc[..., None])
        dlog = ((p - onehot) * gc[..., None]).astype(x.dtype)
        dxc = jnp.einsum("bcv,hv->bch", dlog, w)
        dw_c = jnp.einsum("bch,bcv->hv", xc, dlog,
                          preferred_element_type=jnp.float32)
        return dw + dw_c, dxc

    dw, dxs = jax.lax.scan(body, jnp.zeros((h, v), jnp.float32), (xs, ts, gs, ls))
    dx = dxs.swapaxes(0, 1).reshape(b, s, h)
    dt = np.zeros(targets.shape, jax.dtypes.float0)
    return dx, dw.astype(w.dtype), dt


_chunked_ce.defvjp(lambda x, w, t, chunk: _chunked_ce_fwd(x, w, t, chunk),
                   _chunked_ce_bwd)


def causal_lm_loss(params: Params, batch: Dict[str, jnp.ndarray],
                   cfg: TransformerConfig,
                   pctx: ParallelContext = ParallelContext(),
                   compute_dtype=jnp.bfloat16,
                   moe_aux_weight: float = 0.01,
                   remat: Union[bool, str, None] = False,
                   loss_chunk: Optional[int] = 0):
    """batch: {"tokens": [B, S+1] or "tokens"+"targets"}. Returns (loss, metrics).

    loss_chunk: sequence-chunk size for the blockwise LM head.  0 (default)
    auto-enables chunking when the full logits tensor would be large
    (S*V > 2**25 elements); None disables; an int forces that chunk size.
    """
    if "targets" in batch:
        tokens, targets = batch["tokens"], batch["targets"]
    else:
        tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    s = tokens.shape[1]
    if loss_chunk == 0:
        loss_chunk = 512 if s * cfg.vocab_size > 2 ** 25 else None
    if loss_chunk and pctx.use_ring:
        # sp shards the sequence dim; a seq-chunk scan would reshard it every
        # chunk.  The sp path already keeps per-shard logits small (S/sp).
        loss_chunk = None
    x, aux = apply_trunk(params, tokens, cfg, pctx, compute_dtype, remat=remat)
    if loss_chunk:
        w = lm_head_weight(params, cfg, x.dtype)
        nll = chunked_cross_entropy(x, w, targets, min(loss_chunk, s))
    else:
        logits = (x @ lm_head_weight(params, cfg, x.dtype)).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("loss_mask")
    if mask is None:
        loss = nll.mean()
        denom = nll.size
    else:
        loss = (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
        denom = mask.sum()
    total = loss + moe_aux_weight * aux["moe_aux_loss"]
    return total, {"loss": loss, "moe_aux_loss": aux["moe_aux_loss"],
                   "tokens": denom}
