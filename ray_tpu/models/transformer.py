"""Decoder-only transformer: one implementation for GPT-2 / Llama-3 / Mixtral.

TPU-first design choices:
* **Stacked layer params + lax.scan** — compile time independent of depth; XLA sees one
  block body (the reference's torch models unroll layers in Python).
* **bf16 compute, fp32 params/optimizer** — matmuls hit the MXU in bf16; the cast sits
  next to each einsum so XLA fuses it.
* **Static shapes everywhere** — no data-dependent control flow inside jit.
* Attention dispatches to plain XLA / Pallas flash / ring attention (`sp` axis)
  based on a `ParallelContext`.

Params are a plain pytree (dict) so sharding rules (models/sharding.py) are specs over
the same tree structure.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import moe as moe_ops
from ..ops.attention import attend, mha
from .config import TransformerConfig

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class ParallelContext:
    """How to run attention/MoE under a mesh. None mesh = single device."""
    mesh: Optional[Any] = None
    sp_axis: Optional[str] = None     # sequence-parallel axis name (ring attn)
    batch_axes: Tuple[str, ...] = ("dp",)

    @property
    def use_ring(self) -> bool:
        return (self.mesh is not None and self.sp_axis is not None
                and self.mesh.shape.get(self.sp_axis, 1) > 1)


# ---------------------------------------------------------------------------
# Init
# ---------------------------------------------------------------------------

def init_params(key: jax.Array, cfg: TransformerConfig,
                dtype=jnp.float32) -> Params:
    h, hd = cfg.hidden_size, cfg.head_dim
    nh, nkv, m, L = cfg.num_heads, cfg.num_kv_heads, cfg.mlp_size, cfg.num_layers
    keys = iter(jax.random.split(key, 32))

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, dtype) * (fan_in ** -0.5)).astype(dtype)

    def norm_p():
        p = {"scale": jnp.ones((L, h), dtype)}
        if not cfg.use_rmsnorm:
            p["bias"] = jnp.zeros((L, h), dtype)
        return p

    blocks: Params = {
        "attn_norm": norm_p(),
        "attn": {
            "wq": dense(next(keys), (L, h, nh * hd), h),
            "wk": dense(next(keys), (L, h, nkv * hd), h),
            "wv": dense(next(keys), (L, h, nkv * hd), h),
            "wo": dense(next(keys), (L, nh * hd, h), nh * hd),
        },
        "mlp_norm": norm_p(),
    }
    if not cfg.use_rmsnorm:  # GPT-2 style biases
        blocks["attn"]["bq"] = jnp.zeros((L, nh * hd), dtype)
        blocks["attn"]["bk"] = jnp.zeros((L, nkv * hd), dtype)
        blocks["attn"]["bv"] = jnp.zeros((L, nkv * hd), dtype)
        blocks["attn"]["bo"] = jnp.zeros((L, h), dtype)
    if cfg.num_experts > 1:
        e = cfg.num_experts
        blocks["moe"] = {
            "router": dense(next(keys), (L, h, e), h),
            "w_gate": dense(next(keys), (L, e, h, m), h),
            "w_in": dense(next(keys), (L, e, h, m), h),
            "w_out": dense(next(keys), (L, e, m, h), m),
        }
    else:
        mlp: Params = {
            "w_in": dense(next(keys), (L, h, m), h),
            "w_out": dense(next(keys), (L, m, h), m),
        }
        if cfg.use_swiglu:
            mlp["w_gate"] = dense(next(keys), (L, h, m), h)
        else:
            mlp["b_in"] = jnp.zeros((L, m), dtype)
            mlp["b_out"] = jnp.zeros((L, h), dtype)
        blocks["mlp"] = mlp

    params: Params = {
        "embed": {"tokens": (jax.random.normal(next(keys), (cfg.vocab_size, h),
                                               dtype) * 0.02)},
        "blocks": blocks,
        "final_norm": {"scale": jnp.ones((h,), dtype)},
    }
    if not cfg.use_rope:
        params["embed"]["pos"] = (
            jax.random.normal(next(keys), (cfg.max_seq_len, h), dtype) * 0.01)
    if not cfg.use_rmsnorm:
        params["final_norm"]["bias"] = jnp.zeros((h,), dtype)
    if not cfg.tied_embeddings:
        params["lm_head"] = dense(next(keys), (h, cfg.vocab_size), h)
    return params


# ---------------------------------------------------------------------------
# Building blocks
# ---------------------------------------------------------------------------

def _norm(x, p, cfg: TransformerConfig):
    x32 = x.astype(jnp.float32)
    if cfg.use_rmsnorm:
        x32 = x32 * jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True)
                                  + cfg.norm_eps)
        return (x32 * p["scale"].astype(jnp.float32)).astype(x.dtype)
    mean = x32.mean(-1, keepdims=True)
    var = ((x32 - mean) ** 2).mean(-1, keepdims=True)
    x32 = (x32 - mean) * jax.lax.rsqrt(var + cfg.norm_eps)
    return (x32 * p["scale"] + p["bias"]).astype(x.dtype)


def _rope(x: jnp.ndarray, positions: jnp.ndarray, theta: float) -> jnp.ndarray:
    """x: [B, S, H, D]; positions: [S]."""
    d = x.shape[-1]
    freqs = 1.0 / (theta ** (jnp.arange(0, d, 2, dtype=jnp.float32) / d))
    angles = positions[:, None].astype(jnp.float32) * freqs[None, :]  # [S, D/2]
    cos = jnp.cos(angles)[None, :, None, :]
    sin = jnp.sin(angles)[None, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)


def _attention_block(x, p, cfg: TransformerConfig, positions, pctx: ParallelContext):
    b, s, h = x.shape
    nh, nkv, hd = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim
    cast = x.dtype
    q = x @ p["wq"].astype(cast)
    k = x @ p["wk"].astype(cast)
    v = x @ p["wv"].astype(cast)
    if "bq" in p:
        q, k, v = q + p["bq"].astype(cast), k + p["bk"].astype(cast), v + p["bv"].astype(cast)
    q = q.reshape(b, s, nh, hd)
    k = k.reshape(b, s, nkv, hd)
    v = v.reshape(b, s, nkv, hd)
    if cfg.use_rope:
        q = _rope(q, positions, cfg.rope_theta)
        k = _rope(k, positions, cfg.rope_theta)
    if pctx.use_ring:
        from ..ops.ring_attention import ring_attention
        out = ring_attention(q, k, v, pctx.mesh, pctx.sp_axis,
                             causal=cfg.causal, batch_axes=pctx.batch_axes,
                             logit_softcap=cfg.attn_logit_softcap)
    else:
        out = mha(q, k, v, causal=cfg.causal,
                  logit_softcap=cfg.attn_logit_softcap)
    out = out.reshape(b, s, nh * hd) @ p["wo"].astype(cast)
    if "bo" in p:
        out = out + p["bo"].astype(cast)
    return out


def _mlp_block(x, p, cfg: TransformerConfig):
    cast = x.dtype
    if cfg.use_swiglu:
        gate = jax.nn.silu(x @ p["w_gate"].astype(cast))
        up = x @ p["w_in"].astype(cast)
        return (gate * up) @ p["w_out"].astype(cast)
    hmid = x @ p["w_in"].astype(cast) + p["b_in"].astype(cast)
    hmid = jax.nn.gelu(hmid)
    return hmid @ p["w_out"].astype(cast) + p["b_out"].astype(cast)


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------

def apply(params: Params, tokens: jnp.ndarray, cfg: TransformerConfig,
          pctx: ParallelContext = ParallelContext(),
          compute_dtype=jnp.bfloat16,
          remat: bool = False) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """tokens: [B, S] int32 -> (logits [B, S, V] f32, aux dict)."""
    b, s = tokens.shape
    x = params["embed"]["tokens"][tokens].astype(compute_dtype)
    # Positions are global sequence positions; under jit with a sequence-sharded
    # batch XLA partitions this computation (only ring attention, which runs in
    # shard_map, handles per-shard offsets itself).
    positions = jnp.arange(s)
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][:s][None].astype(compute_dtype)

    def block(x, layer_params):
        attn_out = _attention_block(
            _norm(x, layer_params["attn_norm"], cfg), layer_params["attn"],
            cfg, positions, pctx)
        x = x + attn_out
        y = _norm(x, layer_params["mlp_norm"], cfg)
        if cfg.num_experts > 1:
            out, aux = moe_ops.moe_mlp(
                y, layer_params["moe"]["router"], layer_params["moe"]["w_gate"],
                layer_params["moe"]["w_in"], layer_params["moe"]["w_out"],
                cfg.experts_per_token, cfg.expert_capacity_factor)
        else:
            out, aux = _mlp_block(y, layer_params["mlp"], cfg), jnp.zeros((), jnp.float32)
        return x + out, aux

    def scan_body(x, layer_params):
        x, aux = block(x, layer_params)
        return x, aux

    if remat:
        # Per-layer rematerialization: backward recomputes one block at a time,
        # so peak activation memory is O(1) in depth (HBM is the bottleneck —
        # trade FLOPs for memory). Checkpointing the whole loss instead would
        # still materialize every layer's residuals during the backward replay.
        scan_body = jax.checkpoint(
            scan_body, policy=jax.checkpoint_policies.nothing_saveable)

    x, aux_losses = jax.lax.scan(scan_body, x, params["blocks"])
    x = _norm(x, params["final_norm"], cfg)
    if cfg.tied_embeddings:
        logits = x @ params["embed"]["tokens"].T.astype(x.dtype)
    else:
        logits = x @ params["lm_head"].astype(x.dtype)
    return logits.astype(jnp.float32), {"moe_aux_loss": aux_losses.mean()}


def causal_lm_loss(params: Params, batch: Dict[str, jnp.ndarray],
                   cfg: TransformerConfig,
                   pctx: ParallelContext = ParallelContext(),
                   compute_dtype=jnp.bfloat16,
                   moe_aux_weight: float = 0.01,
                   remat: bool = False):
    """batch: {"tokens": [B, S+1] or "tokens"+"targets"}. Returns (loss, metrics)."""
    if "targets" in batch:
        tokens, targets = batch["tokens"], batch["targets"]
    else:
        tokens, targets = batch["tokens"][:, :-1], batch["tokens"][:, 1:]
    logits, aux = apply(params, tokens, cfg, pctx, compute_dtype, remat=remat)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = batch.get("loss_mask")
    if mask is None:
        loss = nll.mean()
        denom = nll.size
    else:
        loss = (nll * mask).sum() / jnp.maximum(mask.sum(), 1)
        denom = mask.sum()
    total = loss + moe_aux_weight * aux["moe_aux_loss"]
    return total, {"loss": loss, "moe_aux_loss": aux["moe_aux_loss"],
                   "tokens": denom}
