"""Sharding rules: PartitionSpec trees for transformer params and batches.

This is the heart of the TPU-native parallelism design (SURVEY §2.3): instead of the
reference's NCCL process groups (DDP/FSDP wrappers), parallelism is expressed as specs
over a named mesh and XLA inserts the collectives:

* ``dp``   — pure data parallel (batch axis)
* ``fsdp`` — ZeRO-style sharded data parallel: params/optimizer sharded, batch also
             split here (paper 2004.13336 in PAPERS.md)
* ``tp``   — tensor parallel: attention heads / MLP width
* ``sp``   — sequence/context parallel (ring attention)
* ``ep``   — expert parallel (MoE expert dim)
* ``pp``   — pipeline stages (see parallel/pipeline.py)
"""

from __future__ import annotations

from typing import Any, Dict

from jax.sharding import PartitionSpec as P

from .config import TransformerConfig

BATCH_AXES = ("dp", "fsdp")


def batch_spec() -> P:
    """tokens [B, S]: batch over dp+fsdp, sequence over sp."""
    return P(BATCH_AXES, "sp")


def logical_param_specs(cfg: TransformerConfig) -> Dict[str, Any]:
    """PartitionSpec tree matching init_params' structure."""
    def norm_spec(stacked: bool):
        p = {"scale": P(None, None) if stacked else P(None)}
        if not cfg.use_rmsnorm:
            p["bias"] = P(None, None) if stacked else P(None)
        return p

    attn = {
        "wq": P(None, "fsdp", "tp"),
        "wk": P(None, "fsdp", "tp"),
        "wv": P(None, "fsdp", "tp"),
        "wo": P(None, "tp", "fsdp"),
    }
    if not cfg.use_rmsnorm:
        attn.update({"bq": P(None, "tp"), "bk": P(None, "tp"),
                     "bv": P(None, "tp"), "bo": P(None, "fsdp")})

    blocks: Dict[str, Any] = {
        "attn_norm": norm_spec(True),
        "attn": attn,
        "mlp_norm": norm_spec(True),
    }
    if cfg.num_experts > 1:
        blocks["moe"] = {
            "router": P(None, "fsdp", None),
            "w_gate": P(None, "ep", "fsdp", "tp"),
            "w_in": P(None, "ep", "fsdp", "tp"),
            "w_out": P(None, "ep", "tp", "fsdp"),
        }
    else:
        mlp = {"w_in": P(None, "fsdp", "tp"), "w_out": P(None, "tp", "fsdp")}
        if cfg.use_swiglu:
            mlp["w_gate"] = P(None, "fsdp", "tp")
        else:
            mlp["b_in"] = P(None, "tp")
            mlp["b_out"] = P(None, "fsdp")
        blocks["mlp"] = mlp

    specs: Dict[str, Any] = {
        "embed": {"tokens": P("fsdp", "tp")},
        "blocks": blocks,
        "final_norm": norm_spec(False),
    }
    if not cfg.use_rope:
        specs["embed"]["pos"] = P(None, "fsdp")
    if not cfg.tied_embeddings:
        specs["lm_head"] = P("fsdp", "tp")
    return specs
