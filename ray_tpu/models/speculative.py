"""Speculative decoding: draft-model lookahead + one-shot target verify.

Beyond-reference TPU-native addition (the reference serves LLMs by
pairing with an external engine; our serve stack owns its engine —
serve/llm.py — so the classic latency lever is implementable natively).

Why this is a TPU win: autoregressive decode is HBM-bound — every step
streams all weights for ONE matvec per slot. Speculation turns k of
those matvecs into ONE [slots, k]-token matmul (`verify_window`): same
weight traffic, k× the useful FLOPs, which is exactly the regime the
MXU wants. The draft model is small enough that its k sequential steps
cost less than the saved target steps whenever acceptance is decent.

Greedy acceptance keeps the output EXACTLY equal to vanilla greedy
decode (tests pin this): accept draft tokens while they match the
target's argmax at the same position, then emit the target's own token
at the first mismatch — ≥1 token per verify call, so worst case equals
vanilla decode plus the (cheap) draft work.

Everything is fixed-shape and jittable: the multi-round driver is a
``lax.scan`` whose carry holds both caches, per-slot emit buffers and
lengths — no host round-trip between rounds (cf. decode.py's
``decode_loop``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .config import TransformerConfig
from .decode import (KVCache, Params, _mlp, _norm, _proj_out, _qkv,
                     decode_step, lm_head_weight, sample_per_slot)

__all__ = ["verify_window", "speculative_round", "speculative_decode_loop",
           "spec_state_round", "spec_decode_state_loop", "make_draft_params",
           "damp_block_outputs"]


def verify_window(params: Params, cache: KVCache, tokens: jnp.ndarray,
                  active: jnp.ndarray, cfg: TransformerConfig,
                  compute_dtype=jnp.bfloat16
                  ) -> Tuple[KVCache, jnp.ndarray]:
    """Process a k-token window per slot in one forward.

    tokens: [slots, k] int32 — token j sits at cache position length+j
    active: [slots] bool
    Returns (cache, logits [slots, k, V] f32); K/V for all k positions
    are appended and ``length`` advances by k for active slots (callers
    roll length back to the accepted prefix afterwards — the garbage
    tail beyond ``length`` is never read, same contract as prefill's
    padded tail).

    This is ``decode_step`` generalized from window 1 to window k; with
    k=1 it computes identical math.
    """
    n_slots, k = tokens.shape
    max_len = cache["k"].shape[2]
    cast = compute_dtype
    lengths = cache["length"]                                   # [slots]
    x = params["embed"]["tokens"][tokens].astype(cast)          # [S,k,H]
    positions = lengths[:, None] + jnp.arange(k)[None]          # [S,k]
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][
            jnp.minimum(positions, cfg.max_seq_len - 1)].astype(cast)
    scale = cfg.head_dim ** -0.5
    reps = cfg.num_heads // cfg.num_kv_heads
    # query j may see cache positions <= length+j (its own position)
    pos_mask = (jnp.arange(max_len)[None, None]
                <= positions[:, :, None])          # [slots, k, max_len]
    row = jnp.arange(n_slots)[:, None]                          # [S,1]

    def body(x, layer):
        lp, k_lay, v_lay = layer
        y = _norm(x, lp["attn_norm"], cfg)
        q, kk, vv = _qkv(y, lp["attn"], cfg, positions)  # [S,k,N*,D]
        # append the whole window's K/V rows (scatter at length..length+k-1)
        k_lay = k_lay.at[row, positions].set(kk.astype(k_lay.dtype))
        v_lay = v_lay.at[row, positions].set(vv.astype(v_lay.dtype))
        qh = q.reshape(n_slots, k, cfg.num_kv_heads, reps, cfg.head_dim)
        scores = jnp.einsum("skgrd,smgd->skgrm", qh.astype(jnp.float32),
                            k_lay.astype(jnp.float32)) * scale
        if cfg.attn_logit_softcap:
            c = cfg.attn_logit_softcap
            scores = c * jnp.tanh(scores / c)
        scores = jnp.where(pos_mask[:, :, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("skgrm,smgd->skgrd", probs,
                          v_lay.astype(jnp.float32))
        attn = attn.reshape(n_slots, k, cfg.num_heads * cfg.head_dim)
        x = x + _proj_out(attn.astype(cast), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        return x, (k_lay, v_lay)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    logits = (x @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    cache = {
        "k": k_new, "v": v_new,
        "length": jnp.where(active, jnp.minimum(lengths + k, max_len),
                            lengths),
    }
    return cache, logits


def speculative_round(target_params: Params, target_cache: KVCache,
                      draft_params: Params, draft_cache: KVCache,
                      last_tokens: jnp.ndarray, active: jnp.ndarray,
                      k: int, target_cfg: TransformerConfig,
                      draft_cfg: TransformerConfig,
                      ) -> Tuple[KVCache, KVCache, jnp.ndarray,
                                 jnp.ndarray, jnp.ndarray]:
    """One draft→verify→accept round for every slot.

    Returns (target_cache, draft_cache, emitted [slots, k] int32,
    emit_count [slots] int32 in 1..k, new_last [slots]).  Emitted slots
    beyond emit_count hold garbage; inactive slots emit 0 tokens.

    Greedy acceptance: with drafts d_1..d_{k-1} and target logits
    l_0..l_{k-1} over window [last, d_1..d_{k-1}], accept d_{j+1} while
    d_{j+1} == argmax(l_j); then emit argmax(l_a) at the first mismatch
    (the "free" correction) — output identical to vanilla greedy.
    """
    n_slots = last_tokens.shape[0]

    # -- draft rollout: k-1 small-model steps ------------------------------
    def draft_body(carry, _):
        dc, tok = carry
        dc, logits = decode_step(draft_params, dc, tok, active, draft_cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (dc, nxt), nxt

    (draft_cache, last_d), drafts = jax.lax.scan(
        draft_body, (draft_cache, last_tokens), None, length=k - 1)
    drafts = drafts.T                                   # [slots, k-1]
    # one extra KV-only draft step: when every draft is accepted the next
    # round needs d_{k-1}'s row in the draft cache too (its logits are
    # discarded — this is the fixed price of fixed shapes)
    draft_cache, _ = decode_step(draft_params, draft_cache, last_d,
                                 active, draft_cfg)

    # -- target verify: ONE k-token window ---------------------------------
    window = jnp.concatenate([last_tokens[:, None], drafts], axis=1)
    t_len0 = target_cache["length"]
    target_cache, logits = verify_window(target_params, target_cache,
                                         window, active, target_cfg)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [slots, k]

    # -- acceptance --------------------------------------------------------
    match = (drafts == greedy[:, :-1])                       # [slots, k-1]
    accepted = jnp.argmin(
        jnp.concatenate([match, jnp.zeros((n_slots, 1), bool)], 1), axis=1)
    # ^ index of first False; all-True gives k-1 (argmin of all-False tail
    #   trick: appended False guarantees a minimum exists)
    emit_count = jnp.where(active, accepted + 1, 0)          # drafts + fix
    # emitted tokens: d_1..d_a then greedy[a] at position a
    emitted = jnp.where(
        jnp.arange(k)[None] < accepted[:, None],
        jnp.concatenate([drafts, jnp.zeros((n_slots, 1), jnp.int32)], 1),
        jnp.take_along_axis(greedy, accepted[:, None], 1))   # [slots, k]
    new_last = jnp.take_along_axis(greedy, accepted[:, None], 1)[:, 0]
    new_last = jnp.where(active, new_last, last_tokens)

    # -- roll both caches back to the verified prefix ----------------------
    # context now ends with ...last, d_1..d_a; the correction token is
    # fed next round, so length = len0 + 1 + accepted
    new_len = t_len0 + 1 + accepted
    target_cache = dict(target_cache,
                        length=jnp.where(active, new_len, t_len0))
    # the draft ingested the same prefix (its rows cover last..d_{k-1})
    draft_cache = dict(draft_cache,
                       length=jnp.where(active, new_len,
                                        draft_cache["length"]))
    return target_cache, draft_cache, emitted, emit_count, new_last


@partial(jax.jit, static_argnames=("k", "num_rounds", "target_cfg",
                                   "draft_cfg", "eos_id"))
def speculative_decode_loop(target_params: Params, target_cache: KVCache,
                            draft_params: Params, draft_cache: KVCache,
                            last_tokens: jnp.ndarray, active: jnp.ndarray,
                            k: int, num_rounds: int,
                            target_cfg: TransformerConfig,
                            draft_cfg: TransformerConfig,
                            eos_id: int = -1,
                            ) -> Dict[str, Any]:
    """Fixed-shape multi-round driver: ``num_rounds`` spec rounds under
    one ``lax.scan`` — no host sync between rounds.

    Returns {tokens: [slots, num_rounds*k], counts: [slots],
    target_cache, draft_cache, last_tokens, rounds_accepted: [slots,
    num_rounds]} — tokens beyond counts are garbage; a slot that emits
    ``eos_id`` (if >= 0) deactivates for the remaining rounds.
    """
    n_slots = last_tokens.shape[0]
    out = jnp.zeros((n_slots, num_rounds * k), jnp.int32)
    counts = jnp.zeros((n_slots,), jnp.int32)

    def round_body(carry, _):
        tc, dc, last, act, out, counts = carry
        tc, dc, emitted, n_emit, last = speculative_round(
            target_params, tc, draft_params, dc, last, act,
            k, target_cfg, draft_cfg)
        # scatter emitted[0:n_emit] at out[counts:counts+n_emit]
        idx = counts[:, None] + jnp.arange(k)[None]          # [slots, k]
        keep = jnp.arange(k)[None] < n_emit[:, None]
        out = out.at[jnp.arange(n_slots)[:, None],
                     jnp.minimum(idx, out.shape[1] - 1)].set(
            jnp.where(keep, emitted, out[jnp.arange(n_slots)[:, None],
                                         jnp.minimum(idx, out.shape[1] - 1)]))
        counts = counts + n_emit
        if eos_id >= 0:
            hit_eos = (jnp.where(keep, emitted, -1) == eos_id).any(axis=1)
            act = act & ~hit_eos
        return (tc, dc, last, act, out, counts), n_emit

    (target_cache, draft_cache, last_tokens, active, out, counts), accs = \
        jax.lax.scan(round_body,
                     (target_cache, draft_cache, last_tokens, active,
                      out, counts), None, length=num_rounds)
    return {"tokens": out, "counts": counts,
            "target_cache": target_cache, "draft_cache": draft_cache,
            "last_tokens": last_tokens, "active": active,
            "rounds_accepted": accs.T}


# ---------------------------------------------------------------------------
# Serving-engine integration: decode-state rounds (continuous batching)
# ---------------------------------------------------------------------------

def spec_state_round(target_params: Params, target_cache, draft_params:
                     Params, draft_cache: KVCache, state: Dict[str, Any],
                     k: int, target_cfg: TransformerConfig,
                     draft_cfg: TransformerConfig, paged: bool = False,
                     top_k: int = 0, compute_dtype=jnp.bfloat16):
    """One speculative round against the engine's device-resident decode
    state (``decode.init_decode_state`` layout) — the serving twin of
    ``speculative_round``, run inside LLMEngine's scheduler thread.

    Differences from the standalone round (tier-1 tests pin all three):

    * **Sampling-aware.**  Greedy slots (temperature 0) take the classic
      accept-while-matching path; sampled slots accept NO drafts and emit
      exactly one token drawn from the target's own first-position logits
      via ``sample_per_slot`` — the identical distribution a vanilla
      decode step would sample, so turning speculation on never changes
      sampling semantics (it just wastes the drafts for hot slots).
    * **Budget/EOS exact.**  ``emit_count`` is clamped to the remaining
      budget and truncated at the first emitted EOS (inclusive), then
      budget and active decay on device by the same predicate
      ``decode_state_loop`` applies per step — the host scheduling mirror
      stays byte-consistent with the plain decode path.
    * **Paged or dense target.**  ``paged=True`` verifies through
      ``paged_decode.paged_verify_window``; either way rollback is a
      length reset to ``len0 + emit_count`` (the cache then covers
      ``last, e_1..e_{cnt-1}`` and ``e_cnt`` is fed back next round).

    The draft cache is always DENSE (the paged HBM win matters for the
    big target; the draft is layers-sliced and small).  Returns
    (target_cache, draft_cache, state, emitted [slots, k],
    emit_count [slots]).
    """
    n_slots = state["tokens"].shape[0]
    last = state["tokens"]
    active = state["active"]
    temps = state["temps"]
    key = state["key"]

    # -- draft rollout: k-1 small-model greedy steps -----------------------
    def draft_body(carry, _):
        dc, tok = carry
        dc, logits = decode_step(draft_params, dc, tok, active, draft_cfg,
                                 compute_dtype)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (dc, nxt), nxt

    (draft_cache, last_d), drafts = jax.lax.scan(
        draft_body, (draft_cache, last), None, length=k - 1)
    drafts = drafts.T if k > 1 else jnp.zeros((n_slots, 0), jnp.int32)
    # KV-only extra step so a fully-accepted round leaves d_{k-1}'s row in
    # the draft cache (fixed price of fixed shapes, as speculative_round)
    draft_cache, _ = decode_step(draft_params, draft_cache, last_d, active,
                                 draft_cfg, compute_dtype)

    # -- target verify: ONE k-token window ---------------------------------
    window = jnp.concatenate([last[:, None], drafts], axis=1)
    t_len0 = target_cache["length"]
    if paged:
        from .paged_decode import paged_verify_window
        target_cache, logits = paged_verify_window(
            target_params, target_cache, window, active, target_cfg,
            compute_dtype)
    else:
        target_cache, logits = verify_window(target_params, target_cache,
                                             window, active, target_cfg,
                                             compute_dtype)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [slots, k]

    # -- acceptance --------------------------------------------------------
    match = (drafts == greedy[:, :-1]) if k > 1 \
        else jnp.zeros((n_slots, 0), bool)
    accepted = jnp.argmin(
        jnp.concatenate([match, jnp.zeros((n_slots, 1), bool)], 1), axis=1)
    is_greedy = temps <= 0.0
    accepted = jnp.where(is_greedy, accepted, 0)
    # sampled slots draw token 0 from the target's own next-token logits
    samp = sample_per_slot(logits[:, 0], jax.random.fold_in(key, 0xD1CE),
                           temps, top_k)
    correction = jnp.take_along_axis(greedy, accepted[:, None], 1)[:, 0]
    first_tok = jnp.where(is_greedy, correction, samp)
    drafts_pad = jnp.concatenate(
        [drafts, jnp.zeros((n_slots, 1), jnp.int32)], 1)
    emitted = jnp.where(jnp.arange(k)[None] < accepted[:, None],
                        drafts_pad, first_tok[:, None])     # [slots, k]

    # -- budget clamp + EOS truncation (device mirrors the host retire) ----
    emit_count = jnp.where(active, accepted + 1, 0)
    emit_count = jnp.minimum(emit_count, jnp.maximum(state["budget"], 0))
    in_window = jnp.arange(k)[None] < emit_count[:, None]
    eos_hits = (emitted == state["eos"][:, None]) & in_window
    has_eos = eos_hits.any(axis=1)
    emit_count = jnp.where(has_eos, jnp.argmax(eos_hits, axis=1) + 1,
                           emit_count)

    # -- roll caches back to the verified prefix ---------------------------
    # cache now ends with ...last, e_1..e_{cnt-1}; the last emitted token
    # (correction or budget-cut draft) is fed next round
    new_len = t_len0 + emit_count
    target_cache = dict(target_cache,
                        length=jnp.where(active, new_len, t_len0))
    draft_cache = dict(draft_cache,
                       length=jnp.where(active, new_len,
                                        draft_cache["length"]))

    new_last = jnp.take_along_axis(
        emitted, jnp.maximum(emit_count - 1, 0)[:, None], 1)[:, 0]
    new_last = jnp.where(active & (emit_count > 0), new_last, last)
    new_budget = jnp.where(active, state["budget"] - emit_count,
                           state["budget"])
    new_active = active & (new_budget > 0) & ~has_eos
    state = {"tokens": new_last, "active": new_active, "temps": temps,
             "budget": new_budget, "eos": state["eos"],
             "key": jax.random.fold_in(key, 0x5BEC)}
    return target_cache, draft_cache, state, emitted, emit_count


def spec_decode_state_loop(target_params: Params, target_cache,
                           draft_params: Params, draft_cache: KVCache,
                           state: Dict[str, Any], k: int, num_rounds: int,
                           target_cfg: TransformerConfig,
                           draft_cfg: TransformerConfig, paged: bool = False,
                           top_k: int = 0, compute_dtype=jnp.bfloat16
                           ) -> Dict[str, Any]:
    """``num_rounds`` decode-state spec rounds under one ``lax.scan`` —
    the engine's speculative twin of ``decode_state_loop`` (one dispatch,
    no host sync between rounds).

    Returns {tokens: [slots, num_rounds*k] (per-slot emit buffer; entries
    beyond counts are garbage), counts: [slots], emit_counts:
    [num_rounds, slots] (per-round acceptance accounting — the host
    derives drafted/accepted/rollback tallies from these alone),
    target_cache, draft_cache, state}.
    """
    n_slots = state["tokens"].shape[0]
    out = jnp.zeros((n_slots, num_rounds * k), jnp.int32)
    counts = jnp.zeros((n_slots,), jnp.int32)
    row = jnp.arange(n_slots)[:, None]

    def body(carry, _):
        tc, dc, st, out, counts = carry
        tc, dc, st, emitted, n_emit = spec_state_round(
            target_params, tc, draft_params, dc, st, k, target_cfg,
            draft_cfg, paged, top_k, compute_dtype)
        idx = jnp.minimum(counts[:, None] + jnp.arange(k)[None],
                          out.shape[1] - 1)
        keep = jnp.arange(k)[None] < n_emit[:, None]
        out = out.at[row, idx].set(jnp.where(keep, emitted, out[row, idx]))
        counts = counts + n_emit
        return (tc, dc, st, out, counts), n_emit

    (target_cache, draft_cache, state, out, counts), emits = jax.lax.scan(
        body, (target_cache, draft_cache, state, out, counts), None,
        length=num_rounds)
    return {"tokens": out, "counts": counts, "emit_counts": emits,
            "target_cache": target_cache, "draft_cache": draft_cache,
            "state": state}


# ---------------------------------------------------------------------------
# Draft-model construction
# ---------------------------------------------------------------------------

def make_draft_params(params: Params, num_layers: int) -> Params:
    """Layers-sliced draft: the leading ``num_layers`` blocks of the
    stacked target params, SHARING embed/final_norm/lm_head (no copy —
    block params are stacked [L, ...] for the layer scan, so a slice is
    one gather).  This is the zero-training draft the serving engine
    defaults to: acceptance then measures how far the truncated trunk
    agrees with the full one, and greedy acceptance keeps the output
    exact regardless."""
    import jax as _jax
    return {key: (_jax.tree_util.tree_map(lambda a: a[:num_layers], val)
                  if key == "blocks" else val)
            for key, val in params.items()}


def damp_block_outputs(params: Params, scale: float = 0.05,
                       from_layer: int = 0) -> Params:
    """Benchmark/test param surgery for SYNTHETIC (randomly initialized)
    weights: scale the output projections (attention ``wo``, MLP
    ``w_out`` + their biases) of every block with index >= ``from_layer``
    by ``scale``.  With ``from_layer = draft_layers`` the target's deep
    tail contributes only a small residual perturbation on top of the
    layers a sliced draft shares, so the pair agrees at the acceptance
    rates a TRAINED draft/target pair exhibits — while the target still
    pays its full depth per step, which is the cost speculation saves.
    Untrained random blocks otherwise give a sliced draft ~chance
    acceptance, which benchmarks the overhead of speculation but none of
    its win.  The acceptance rate is recorded honestly either way, the
    SAME damped model runs in BOTH arms of the perf A/B (fair
    comparison), and this is never applied to real checkpoints."""
    import jax as _jax
    import jax.numpy as _jnp

    def _scale(keypath, leaf):
        path = "/".join(str(getattr(p, "key", p)) for p in keypath)
        tail = path.rsplit("/", 1)[-1]
        if tail in ("wo", "bo", "w_out", "b_out"):
            # stacked block params carry the leading layer dim
            mult = _jnp.where(_jnp.arange(leaf.shape[0]) >= from_layer,
                              _jnp.asarray(scale, leaf.dtype),
                              _jnp.asarray(1.0, leaf.dtype))
            return leaf * mult.reshape((-1,) + (1,) * (leaf.ndim - 1))
        return leaf
    out = dict(params)
    out["blocks"] = _jax.tree_util.tree_map_with_path(
        _scale, params["blocks"])
    return out
