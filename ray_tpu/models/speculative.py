"""Speculative decoding: draft-model lookahead + one-shot target verify.

Beyond-reference TPU-native addition (the reference serves LLMs by
pairing with an external engine; our serve stack owns its engine —
serve/llm.py — so the classic latency lever is implementable natively).

Why this is a TPU win: autoregressive decode is HBM-bound — every step
streams all weights for ONE matvec per slot. Speculation turns k of
those matvecs into ONE [slots, k]-token matmul (`verify_window`): same
weight traffic, k× the useful FLOPs, which is exactly the regime the
MXU wants. The draft model is small enough that its k sequential steps
cost less than the saved target steps whenever acceptance is decent.

Greedy acceptance keeps the output EXACTLY equal to vanilla greedy
decode (tests pin this): accept draft tokens while they match the
target's argmax at the same position, then emit the target's own token
at the first mismatch — ≥1 token per verify call, so worst case equals
vanilla decode plus the (cheap) draft work.

Everything is fixed-shape and jittable: the multi-round driver is a
``lax.scan`` whose carry holds both caches, per-slot emit buffers and
lengths — no host round-trip between rounds (cf. decode.py's
``decode_loop``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp

from .config import TransformerConfig
from .decode import (KVCache, Params, _mlp, _norm, _proj_out, _qkv,
                     decode_step, lm_head_weight)

__all__ = ["verify_window", "speculative_round", "speculative_decode_loop"]


def verify_window(params: Params, cache: KVCache, tokens: jnp.ndarray,
                  active: jnp.ndarray, cfg: TransformerConfig,
                  compute_dtype=jnp.bfloat16
                  ) -> Tuple[KVCache, jnp.ndarray]:
    """Process a k-token window per slot in one forward.

    tokens: [slots, k] int32 — token j sits at cache position length+j
    active: [slots] bool
    Returns (cache, logits [slots, k, V] f32); K/V for all k positions
    are appended and ``length`` advances by k for active slots (callers
    roll length back to the accepted prefix afterwards — the garbage
    tail beyond ``length`` is never read, same contract as prefill's
    padded tail).

    This is ``decode_step`` generalized from window 1 to window k; with
    k=1 it computes identical math.
    """
    n_slots, k = tokens.shape
    max_len = cache["k"].shape[2]
    cast = compute_dtype
    lengths = cache["length"]                                   # [slots]
    x = params["embed"]["tokens"][tokens].astype(cast)          # [S,k,H]
    positions = lengths[:, None] + jnp.arange(k)[None]          # [S,k]
    if not cfg.use_rope:
        x = x + params["embed"]["pos"][
            jnp.minimum(positions, cfg.max_seq_len - 1)].astype(cast)
    scale = cfg.head_dim ** -0.5
    reps = cfg.num_heads // cfg.num_kv_heads
    # query j may see cache positions <= length+j (its own position)
    pos_mask = (jnp.arange(max_len)[None, None]
                <= positions[:, :, None])          # [slots, k, max_len]
    row = jnp.arange(n_slots)[:, None]                          # [S,1]

    def body(x, layer):
        lp, k_lay, v_lay = layer
        y = _norm(x, lp["attn_norm"], cfg)
        q, kk, vv = _qkv(y, lp["attn"], cfg, positions)  # [S,k,N*,D]
        # append the whole window's K/V rows (scatter at length..length+k-1)
        k_lay = k_lay.at[row, positions].set(kk.astype(k_lay.dtype))
        v_lay = v_lay.at[row, positions].set(vv.astype(v_lay.dtype))
        qh = q.reshape(n_slots, k, cfg.num_kv_heads, reps, cfg.head_dim)
        scores = jnp.einsum("skgrd,smgd->skgrm", qh.astype(jnp.float32),
                            k_lay.astype(jnp.float32)) * scale
        if cfg.attn_logit_softcap:
            c = cfg.attn_logit_softcap
            scores = c * jnp.tanh(scores / c)
        scores = jnp.where(pos_mask[:, :, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("skgrm,smgd->skgrd", probs,
                          v_lay.astype(jnp.float32))
        attn = attn.reshape(n_slots, k, cfg.num_heads * cfg.head_dim)
        x = x + _proj_out(attn.astype(cast), lp["attn"], cast)
        x = x + _mlp(_norm(x, lp["mlp_norm"], cfg), lp, cfg)
        return x, (k_lay, v_lay)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["blocks"], cache["k"], cache["v"]))
    x = _norm(x, params["final_norm"], cfg)
    logits = (x @ lm_head_weight(params, cfg, cast)).astype(jnp.float32)
    cache = {
        "k": k_new, "v": v_new,
        "length": jnp.where(active, jnp.minimum(lengths + k, max_len),
                            lengths),
    }
    return cache, logits


def speculative_round(target_params: Params, target_cache: KVCache,
                      draft_params: Params, draft_cache: KVCache,
                      last_tokens: jnp.ndarray, active: jnp.ndarray,
                      k: int, target_cfg: TransformerConfig,
                      draft_cfg: TransformerConfig,
                      ) -> Tuple[KVCache, KVCache, jnp.ndarray,
                                 jnp.ndarray, jnp.ndarray]:
    """One draft→verify→accept round for every slot.

    Returns (target_cache, draft_cache, emitted [slots, k] int32,
    emit_count [slots] int32 in 1..k, new_last [slots]).  Emitted slots
    beyond emit_count hold garbage; inactive slots emit 0 tokens.

    Greedy acceptance: with drafts d_1..d_{k-1} and target logits
    l_0..l_{k-1} over window [last, d_1..d_{k-1}], accept d_{j+1} while
    d_{j+1} == argmax(l_j); then emit argmax(l_a) at the first mismatch
    (the "free" correction) — output identical to vanilla greedy.
    """
    n_slots = last_tokens.shape[0]

    # -- draft rollout: k-1 small-model steps ------------------------------
    def draft_body(carry, _):
        dc, tok = carry
        dc, logits = decode_step(draft_params, dc, tok, active, draft_cfg)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (dc, nxt), nxt

    (draft_cache, last_d), drafts = jax.lax.scan(
        draft_body, (draft_cache, last_tokens), None, length=k - 1)
    drafts = drafts.T                                   # [slots, k-1]
    # one extra KV-only draft step: when every draft is accepted the next
    # round needs d_{k-1}'s row in the draft cache too (its logits are
    # discarded — this is the fixed price of fixed shapes)
    draft_cache, _ = decode_step(draft_params, draft_cache, last_d,
                                 active, draft_cfg)

    # -- target verify: ONE k-token window ---------------------------------
    window = jnp.concatenate([last_tokens[:, None], drafts], axis=1)
    t_len0 = target_cache["length"]
    target_cache, logits = verify_window(target_params, target_cache,
                                         window, active, target_cfg)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)   # [slots, k]

    # -- acceptance --------------------------------------------------------
    match = (drafts == greedy[:, :-1])                       # [slots, k-1]
    accepted = jnp.argmin(
        jnp.concatenate([match, jnp.zeros((n_slots, 1), bool)], 1), axis=1)
    # ^ index of first False; all-True gives k-1 (argmin of all-False tail
    #   trick: appended False guarantees a minimum exists)
    emit_count = jnp.where(active, accepted + 1, 0)          # drafts + fix
    # emitted tokens: d_1..d_a then greedy[a] at position a
    emitted = jnp.where(
        jnp.arange(k)[None] < accepted[:, None],
        jnp.concatenate([drafts, jnp.zeros((n_slots, 1), jnp.int32)], 1),
        jnp.take_along_axis(greedy, accepted[:, None], 1))   # [slots, k]
    new_last = jnp.take_along_axis(greedy, accepted[:, None], 1)[:, 0]
    new_last = jnp.where(active, new_last, last_tokens)

    # -- roll both caches back to the verified prefix ----------------------
    # context now ends with ...last, d_1..d_a; the correction token is
    # fed next round, so length = len0 + 1 + accepted
    new_len = t_len0 + 1 + accepted
    target_cache = dict(target_cache,
                        length=jnp.where(active, new_len, t_len0))
    # the draft ingested the same prefix (its rows cover last..d_{k-1})
    draft_cache = dict(draft_cache,
                       length=jnp.where(active, new_len,
                                        draft_cache["length"]))
    return target_cache, draft_cache, emitted, emit_count, new_last


@partial(jax.jit, static_argnames=("k", "num_rounds", "target_cfg",
                                   "draft_cfg", "eos_id"))
def speculative_decode_loop(target_params: Params, target_cache: KVCache,
                            draft_params: Params, draft_cache: KVCache,
                            last_tokens: jnp.ndarray, active: jnp.ndarray,
                            k: int, num_rounds: int,
                            target_cfg: TransformerConfig,
                            draft_cfg: TransformerConfig,
                            eos_id: int = -1,
                            ) -> Dict[str, Any]:
    """Fixed-shape multi-round driver: ``num_rounds`` spec rounds under
    one ``lax.scan`` — no host sync between rounds.

    Returns {tokens: [slots, num_rounds*k], counts: [slots],
    target_cache, draft_cache, last_tokens, rounds_accepted: [slots,
    num_rounds]} — tokens beyond counts are garbage; a slot that emits
    ``eos_id`` (if >= 0) deactivates for the remaining rounds.
    """
    n_slots = last_tokens.shape[0]
    out = jnp.zeros((n_slots, num_rounds * k), jnp.int32)
    counts = jnp.zeros((n_slots,), jnp.int32)

    def round_body(carry, _):
        tc, dc, last, act, out, counts = carry
        tc, dc, emitted, n_emit, last = speculative_round(
            target_params, tc, draft_params, dc, last, act,
            k, target_cfg, draft_cfg)
        # scatter emitted[0:n_emit] at out[counts:counts+n_emit]
        idx = counts[:, None] + jnp.arange(k)[None]          # [slots, k]
        keep = jnp.arange(k)[None] < n_emit[:, None]
        out = out.at[jnp.arange(n_slots)[:, None],
                     jnp.minimum(idx, out.shape[1] - 1)].set(
            jnp.where(keep, emitted, out[jnp.arange(n_slots)[:, None],
                                         jnp.minimum(idx, out.shape[1] - 1)]))
        counts = counts + n_emit
        if eos_id >= 0:
            hit_eos = (jnp.where(keep, emitted, -1) == eos_id).any(axis=1)
            act = act & ~hit_eos
        return (tc, dc, last, act, out, counts), n_emit

    (target_cache, draft_cache, last_tokens, active, out, counts), accs = \
        jax.lax.scan(round_body,
                     (target_cache, draft_cache, last_tokens, active,
                      out, counts), None, length=num_rounds)
    return {"tokens": out, "counts": counts,
            "target_cache": target_cache, "draft_cache": draft_cache,
            "last_tokens": last_tokens, "active": active,
            "rounds_accepted": accs.T}
