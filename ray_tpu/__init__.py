"""ray_tpu — a TPU-native distributed AI framework.

Capabilities of Ray (tasks, actors, objects, placement groups, Data/Train/Tune/
Serve/RLlib libraries), re-architected TPU-first: the data plane is XLA
(pjit/shard_map collectives over ICI/DCN, Pallas kernels); the runtime around it
is this package.  See SURVEY.md for the reference blueprint.

Top-level import is lightweight (no jax): the compute-path modules
(ray_tpu.parallel, ray_tpu.models, ray_tpu.ops) import jax lazily.
"""

from .core import (ActorDiedError, ActorUnavailableError, GetTimeoutError,
                   NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
                   ObjectLostError, ObjectRef, ObjectRefGenerator, OutOfMemoryError,
                   PlacementGroup,
                   PlacementGroupSchedulingStrategy, RayTpuError, TaskError,
                   WorkerCrashedError, as_future, available_resources, cancel,
                   cluster_resources, exit_actor, get, get_actor, get_async, get_runtime_context,
                   init, is_initialized, kill, method, nodes, placement_group,
                   placement_group_table, put, remote, remove_placement_group,
                   shutdown, timeline, wait)

__version__ = "0.1.0"

__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get", "put", "wait",
    "kill", "cancel", "get_actor", "exit_actor", "get_async", "as_future", "nodes",
    "cluster_resources", "available_resources", "timeline", "ObjectRef",
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "ObjectRefGenerator", "get_runtime_context", "TaskError", "RayTpuError",
    "ActorDiedError", "ActorUnavailableError", "GetTimeoutError", "ObjectLostError",
    "OutOfMemoryError",
    "WorkerCrashedError", "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy", "PlacementGroupSchedulingStrategy", "__version__",
]
