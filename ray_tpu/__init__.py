"""ray_tpu — a TPU-native distributed AI framework.

Capabilities of Ray (tasks, actors, objects, placement groups, Data/Train/Tune/
Serve/RLlib libraries), re-architected TPU-first: the data plane is XLA
(pjit/shard_map collectives over ICI/DCN, Pallas kernels); the runtime around it
is this package.  See SURVEY.md for the reference blueprint.

Top-level import is lightweight (no jax): the compute-path modules
(ray_tpu.parallel, ray_tpu.models, ray_tpu.ops) import jax lazily.
"""

from .core import (ActorDiedError, ActorUnavailableError, GetTimeoutError,
                   NodeAffinitySchedulingStrategy, NodeLabelSchedulingStrategy,
                   ObjectLostError, ObjectRef, ObjectRefGenerator, OutOfMemoryError,
                   PlacementGroup,
                   PlacementGroupSchedulingStrategy, RayTpuError, TaskError,
                   WorkerCrashedError, as_future, available_resources, cancel,
                   cluster_resources, exit_actor, get, get_actor, get_async, get_runtime_context,
                   init, is_initialized, kill, method, nodes, placement_group,
                   placement_group_table, put, remote, remove_placement_group,
                   shutdown, timeline, wait)

from .core.ids import (ActorID, JobID, NodeID, ObjectID, PlacementGroupID,
                       TaskID, WorkerID)

__version__ = "0.1.0"


def get_gpu_ids():
    """Accelerator ids granted to this worker (reference:
    ``ray.get_gpu_ids`` — here the TPU chips from TPU_VISIBLE_CHIPS;
    the name is kept for drop-in parity, ``get_tpu_ids`` is the honest
    alias)."""
    return get_runtime_context().get_accelerator_ids().get("TPU", [])


get_tpu_ids = get_gpu_ids

#: Library submodules resolve lazily (PEP 562) so ``import ray_tpu``
#: stays light but ``ray_tpu.data`` etc. work as attributes, matching
#: the reference's top-level module surface.
_LAZY_SUBMODULES = ("data", "train", "tune", "serve", "rllib", "workflow",
                    "util", "dag", "autoscaler", "experimental", "job")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib
        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute {name!r}")


__all__ = [
    "init", "shutdown", "is_initialized", "remote", "method", "get", "put", "wait",
    "kill", "cancel", "get_actor", "exit_actor", "get_async", "as_future", "nodes",
    "cluster_resources", "available_resources", "timeline", "ObjectRef",
    "placement_group", "remove_placement_group", "placement_group_table",
    "PlacementGroup", "ObjectRefGenerator", "get_runtime_context", "TaskError", "RayTpuError",
    "ActorDiedError", "ActorUnavailableError", "GetTimeoutError", "ObjectLostError",
    "OutOfMemoryError",
    "WorkerCrashedError", "NodeAffinitySchedulingStrategy",
    "NodeLabelSchedulingStrategy", "PlacementGroupSchedulingStrategy",
    "ActorID", "TaskID", "NodeID", "JobID", "ObjectID", "PlacementGroupID",
    "WorkerID", "get_gpu_ids", "get_tpu_ids", "__version__",
]
