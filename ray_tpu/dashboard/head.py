"""Dashboard head: aiohttp REST server over cluster state.

Reference endpoints mirrored (dashboard/modules/*):
  GET  /api/healthz            liveness (healthz module)
  GET  /api/cluster            cluster summary (snapshot module)
  GET  /api/nodes              node table + resources (node module)
  GET  /api/actors             actor table (actor module)
  GET  /api/tasks              task events (state module)
  GET  /api/tasks/summarize    task state counts
  GET  /api/objects            Objects/Memory view: object rows, per-node
                               store stats (arena frag, spill tiers),
                               transfer flight records (?leaks=1 adds the
                               ref-debt report)
  GET  /api/objects/{id}       one object's lifecycle flight-recorder trail
  GET  /api/placement_groups   PG table
  GET  /api/jobs               submitted jobs (job module)
  POST /api/jobs               submit a job {entrypoint, env?, metadata?}
  GET  /api/jobs/{id}          job info
  GET  /api/jobs/{id}/logs     job logs (text)
  POST /api/jobs/{id}/stop     stop a job
  GET  /api/serve              serve app status + per-deployment SLO rollup
  GET  /api/serve/signal       SLO autoscaler signal (queue depth, TTFT pXX)
  GET  /api/serve/autoscale    autoscale decision ring tail
                               (?deployment=<name>&limit=N)
  GET  /api/sched              scheduler explain plane: pending reasons,
                               decision-ring tail, GCS handler busy seconds
                               (?limit=N&id=<task|actor|pg>)
  GET  /api/timeline           chrome://tracing export (timeline)

Runs inside the driver (``start_dashboard()``) or as a standalone actor.
"""

from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Any, Optional

_STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "static")


def _json(data: Any, status: int = 200):
    from aiohttp import web
    return web.json_response(data, status=status, dumps=lambda d: json.dumps(
        d, default=str))


async def _off(fn, *args):
    """Run a blocking state/API call off the IO loop (the public APIs block
    on RPC round-trips that are serviced by this same loop)."""
    return await asyncio.get_event_loop().run_in_executor(None, fn, *args)


class DashboardHead:
    """The REST app; state comes from the public APIs so the dashboard can
    never diverge from what users see programmatically."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self.host = host
        self.port = port
        self._runner = None
        #: cluster metrics history (dashboard/history.py): fed by the
        #: background scrape loop, serves /api/metrics (+/history)
        self.history = None
        self._scrape_task = None
        #: health plane (util/health.py): the head-side rule subset runs
        #: piggybacked on the scrape loop — None while the kill switch
        #: is off (zero detector CPU, zero raytpu_health_* series)
        self._health_detector = None
        self._health_had_active = False

    # ---------------------------------------------------------- handlers

    async def healthz(self, _req):
        from aiohttp import web
        return web.Response(text="success")

    async def cluster(self, _req):
        import ray_tpu

        def snap():
            return {
                "nodes": len(ray_tpu.nodes()),
                "resources_total": ray_tpu.cluster_resources(),
                "resources_available": ray_tpu.available_resources(),
            }

        return _json(await _off(snap))

    async def nodes(self, _req):
        import ray_tpu
        return _json(await _off(ray_tpu.nodes))

    async def node_detail(self, req):
        """Per-node drill-down (reference: dashboard/client/src/pages/
        node/NodeDetailPage): the GCS view row + the agent's live
        node_info (workers, store stats, OOM kills)."""
        import ray_tpu
        from ray_tpu.core.core_worker import global_worker
        nid = req.match_info["node_id"]
        rows = await _off(ray_tpu.nodes)
        row = next((n for n in rows
                    if (n.get("NodeID") or "").startswith(nid)), None)
        if row is None:
            return _json({"error": f"no node {nid!r}"}, status=404)
        info = {}
        if row.get("Alive"):
            w = global_worker()
            try:
                # this handler runs on the worker's IO loop, so await the
                # pooled client directly — no executor bounce
                info = await asyncio.wait_for(
                    w.agent_clients.get(row["AgentAddress"]).call(
                        "node_info", _timeout=10.0), 15)
            except Exception as e:
                info = {"error": str(e)}
        return _json({"node": row, "info": info})

    async def actors(self, req):
        from ray_tpu.util import state
        filters = self._filters(req)
        return _json(await _off(lambda: state.list_actors(filters=filters)))

    async def tasks(self, req):
        from ray_tpu.util import state
        filters = self._filters(req)
        return _json(await _off(lambda: state.list_tasks(filters=filters)))

    async def tasks_summarize(self, _req):
        from ray_tpu.util import state
        return _json(await _off(state.summarize_tasks))

    async def objects(self, req):
        """Objects/Memory view: owner-side object rows, per-node store
        stats (arena fragmentation, spill tiers), the per-pull transfer
        flight records, and — with ``?leaks=1`` — the ref-debt report
        (the probe pings owners, so it is opt-in per request)."""
        from ray_tpu.util import state

        want_leaks = req.query.get("leaks") in ("1", "true")

        def collect():
            out = {
                "objects": state.list_objects(),
                "memory": state.memory_summary(),
                "transfers": state.transfers(limit=50),
            }
            if want_leaks:
                out["leaks"] = state.memory_leaks()
            return out

        return _json(await _off(collect))

    async def object_detail(self, req):
        """One object's flight-recorder lifecycle trail."""
        from ray_tpu.util import state
        oid = req.match_info["object_id"]
        return _json(await _off(lambda: state.explain_object(oid)))

    async def placement_groups(self, _req):
        from ray_tpu.util import state
        return _json(await _off(state.list_placement_groups))

    async def jobs(self, _req):
        from ray_tpu.job import JobSubmissionClient
        return _json(await _off(lambda: JobSubmissionClient().list_jobs()))

    async def submit_job(self, req):
        from ray_tpu.job import JobSubmissionClient
        body = await req.json()
        job_id = await _off(lambda: JobSubmissionClient().submit_job(
            entrypoint=body["entrypoint"],
            runtime_env=body.get("runtime_env"),
            metadata=body.get("metadata")))
        return _json({"job_id": job_id})

    async def job_info(self, req):
        from ray_tpu.job import JobSubmissionClient
        job_id = req.match_info["job_id"]
        return _json(await _off(
            lambda: JobSubmissionClient().get_job_info(job_id)))

    async def job_logs(self, req):
        from aiohttp import web
        from ray_tpu.job import JobSubmissionClient
        job_id = req.match_info["job_id"]
        return web.Response(text=await _off(
            lambda: JobSubmissionClient().get_job_logs(job_id)))

    async def job_stop(self, req):
        from ray_tpu.job import JobSubmissionClient
        job_id = req.match_info["job_id"]
        await _off(lambda: JobSubmissionClient().stop_job(job_id))
        return _json({"stopped": True})

    async def serve_status(self, _req):
        import ray_tpu
        from ray_tpu.serve.controller import CONTROLLER_NAME

        # get_actor blocks on a GCS round-trip serviced by this same loop —
        # it must run in the executor like every other blocking API here
        # (calling it inline raised in run_async and leaked the un-awaited
        # RPC coroutine while this handler silently answered {}).
        def _status():
            try:
                ctrl = ray_tpu.get_actor(CONTROLLER_NAME)
            except Exception:
                return {}
            return ray_tpu.get(ctrl.get_status.remote(), timeout=30)

        return _json(await _off(_status))

    async def serve_signal(self, _req):
        """The per-deployment SLO signal (queue depth + rolling TTFT
        percentiles) in the autoscaler-contract shape — see
        ServeController.get_serve_signal."""
        from ray_tpu import serve as serve_api

        def _signal():
            try:
                return serve_api.slo_signal()
            except Exception:
                return {}

        return _json(await _off(_signal))

    async def serve_autoscale(self, req):
        """Tail of the autoscaler decision ring: every scale event with
        direction/reason/from->to and the signal snapshot it acted on —
        see ServeController.get_autoscale_decisions.
        ``?deployment=<name>&limit=N``."""
        from ray_tpu import serve as serve_api
        deployment = req.query.get("deployment") or None
        limit = int(req.query.get("limit", 50))

        def _decisions():
            try:
                return serve_api.autoscale_decisions(deployment=deployment,
                                                     limit=limit)
            except Exception:
                return []

        return _json(await _off(_decisions))

    async def serve_deploy(self, req):
        """Declarative deploy over REST (reference:
        dashboard/modules/serve — PUT /api/serve/applications)."""
        from ray_tpu.serve import schema as serve_schema
        config = await req.json()
        names = await _off(
            lambda: serve_schema.deploy_config(config, blocking=False))
        return _json({"deployed": names})

    async def timeline(self, _req):
        from ray_tpu.util.tracing import chrome_trace
        return _json(await _off(chrome_trace))

    async def events(self, req):
        """Structured cluster events (reference: dashboard/modules/event)."""
        from ray_tpu.util import events as ev
        severity = req.query.get("severity")
        source = req.query.get("source")
        return _json(await _off(
            lambda: ev.list_events(severity=severity, source=source)))

    async def usage_stats(self, _req):
        """The usage rollup the reference would upload (reference:
        usage_lib.generate_report_data) — served locally instead."""
        from ray_tpu.util import usage_stats as us
        if not us.usage_stats_enabled():
            return _json({"enabled": False})
        report = await _off(us.generate_report)
        return _json({"enabled": True, **report})

    async def actor_detail(self, req):
        """Per-actor drill-down (reference: dashboard/client/src/pages/
        actor/ActorDetailPage): the actor row + its task events."""
        from ray_tpu.util import state
        aid = req.match_info["actor_id"]
        actors = await _off(lambda: state.list_actors(limit=5000))
        row = next((a for a in actors
                    if (a.get("actor_id") or "").startswith(aid)), None)
        if row is None:
            return _json({"error": f"no actor {aid!r}"}, status=404)
        full = row.get("actor_id") or aid
        tasks = await _off(lambda: state.list_tasks(limit=10000))
        mine = [t for t in tasks if t.get("actor_id") == full]
        mine.sort(key=lambda e: e.get("ts", 0))
        return _json({"actor": row, "tasks": mine[-500:]})

    async def task_detail(self, req):
        """Per-task drill-down: the task's full event history (SUBMITTED →
        RUNNING → FINISHED/FAILED with node, error, span ids)."""
        from ray_tpu.util import state
        tid = req.match_info["task_id"]
        rows = await _off(lambda: state.list_tasks(limit=10000))
        evs = [t for t in rows if (t.get("task_id") or "").startswith(tid)]
        if not evs:
            return _json({"error": f"no task {tid!r}"}, status=404)
        evs.sort(key=lambda e: e.get("ts", 0))
        return _json({"task_id": evs[-1].get("task_id"),
                      "name": evs[-1].get("name"),
                      "state": evs[-1].get("state"),
                      "events": evs})

    # ------------------------------------------------- metrics history

    def _ensure_history(self):
        if self.history is None:
            from ray_tpu.core.config import get_config
            from .history import MetricsHistory
            cfg = get_config()
            self.history = MetricsHistory(
                window_s=getattr(cfg, "metrics_history_window_s", 600.0),
                period_s=getattr(cfg, "metrics_scrape_period_s", 5.0))
        return self.history

    async def _scrape_once(self):
        """One scrape pass over every alive node's /metrics into the
        history store.  Unreachable nodes are RECORDED as errors (they
        must show up as explicit {"error": ...} entries, not silently
        vanish from the response)."""
        import aiohttp

        from ray_tpu.util import state
        from .history import parse_prometheus
        store = self._ensure_history()
        nodes = await _off(state.list_nodes)

        async def scrape(sess, nid: str, host: str, port: str):
            try:
                async with sess.get(
                        f"http://{host}:{port}/metrics",
                        timeout=aiohttp.ClientTimeout(total=5)) as resp:
                    text = await resp.text()
            except Exception as e:  # noqa: BLE001 — surfaced to the API
                store.record_error(nid, f"{type(e).__name__}: {e}")
                return
            samples, counters = parse_prometheus(text)
            store.add_sample(nid, samples, counters)

        jobs = []
        alive_ids = set()
        for n in nodes:
            nid = (n.get("node_id") or "")[:12]
            if not n.get("alive"):
                continue
            alive_ids.add(nid)
            port = (n.get("labels") or {}).get("metrics_port")
            if not port:
                store.record_error(nid, "no metrics_port advertised")
                continue
            # scrape at the node's agent host — loopback is only right for
            # the head's own machine
            host = (n.get("address") or "127.0.0.1:0").rsplit(":", 1)[0]
            jobs.append((nid, host, port))
        # nodes that died or left the cluster must DROP from the store —
        # serving a dead node's last sample as live data reads as a
        # healthy, saturated node (unreachable-but-alive nodes stay, as
        # explicit error entries)
        for known in store.nodes():
            if known not in alive_ids:
                store.forget(known)
        async with aiohttp.ClientSession() as sess:
            # concurrent: one timeout of wall clock, not one per dead node
            await asyncio.gather(
                *[scrape(sess, nid, host, port) for nid, host, port in jobs])

    async def _scrape_loop(self):
        store = self._ensure_history()
        while True:
            try:
                await self._scrape_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            try:
                # health detector rides the scrape tick it just paid for
                await self._health_tick()
            except asyncio.CancelledError:
                raise
            except Exception:
                pass
            await asyncio.sleep(store.period_s)

    async def _health_tick(self):
        """Evaluate the head-side health rules over the sample the
        scrape loop just collected, then flush transitions + the active
        set to the GCS alert ring.  With ``health_metrics_enabled``
        off this is one boolean check — no snapshot walk, no detector
        state, no series."""
        from ray_tpu.util import health as health_plane
        if not health_plane.enabled():
            self._health_detector = None
            self._health_had_active = False
            return
        from ray_tpu.util import state
        store = self._ensure_history()
        det = self._health_detector
        if det is None:
            det = self._health_detector = health_plane.head_detector()

        def _slo():
            try:
                from ray_tpu import serve as serve_api
                return serve_api.slo_signal()
            except Exception:
                return {}

        slo = await _off(_slo)
        snap = health_plane.build_head_snapshot(store, slo=slo)
        events = det.observe(snap)
        health_plane.record_transitions(events, det)
        active = det.active()
        if events or active or self._health_had_active:
            # push on every interesting tick (and one trailing empty
            # push so handle_health's merged active set drains to zero)
            def _push():
                try:
                    state._gcs_call("add_health_alerts", records=events,
                                    active=active, source="head")
                except Exception:
                    pass
            await _off(_push)
        self._health_had_active = bool(active)

    async def metrics(self, _req):
        """Freshest parsed /metrics sample per node, served from the
        history store (the background loop scrapes; this handler never
        re-scrapes the cluster per request).  Nodes whose last scrape
        failed report {"error": ...} explicitly."""
        store = self._ensure_history()
        ts, nodes = store.latest()
        if not nodes:
            # first request racing the first scrape tick: do one pass
            await self._scrape_once()
            ts, nodes = store.latest()
        return _json({"ts": ts or time.time(), "nodes": nodes})

    async def metrics_history(self, req):
        """Windowed time series + derived counter rates per node.
        Query params: ``node`` (12-hex prefix; default all), ``prefix``
        (metric-name filter, default ``raytpu_`` to bound the payload)."""
        store = self._ensure_history()
        want = req.query.get("node")
        prefix = req.query.get("prefix", "raytpu_")
        out: dict = {}
        for nid in store.nodes():
            if want and not nid.startswith(want):
                continue
            out[nid] = {**store.summary(nid),
                        "series": store.series(nid, prefix=prefix),
                        "rates": store.rates(nid, prefix=prefix)}
        return _json({"ts": time.time(), "window_s": store.window_s,
                      "period_s": store.period_s, "nodes": out})

    async def telemetry(self, _req):
        """Per-node runtime telemetry + task-stage latency percentiles —
        the self-instrumentation plane's aggregate view (live agent
        node_info per node, summarize_tasks' stage_latency rollup)."""
        import ray_tpu
        from ray_tpu.core.core_worker import global_worker
        from ray_tpu.util import state

        rows = await _off(ray_tpu.nodes)
        w = global_worker()
        nodes: dict = {}

        async def probe(nid: str, address: str):
            try:
                nodes[nid] = await asyncio.wait_for(
                    w.agent_clients.get(address).call(
                        "node_info", _timeout=10.0), 15)
            except Exception as e:  # noqa: BLE001 — report what answered
                nodes[nid] = {"error": str(e)}

        # concurrent like the metrics scrape above: one timeout of wall
        # clock, not one per wedged node
        await asyncio.gather(*[
            probe((row.get("NodeID") or "")[:12], row["AgentAddress"])
            for row in rows
            if row.get("Alive") and row.get("AgentAddress")])
        summary = await _off(state.summarize_tasks)
        return _json({"ts": time.time(), "nodes": nodes,
                      "total_tasks": summary.get("total_tasks", 0),
                      "stage_latency": summary.get("stage_latency", {})})

    async def health_view(self, _req):
        """Health plane: deduplicated active alerts + the recent
        transition trail from the GCS ring (``state.health()`` shape) —
        the Health tab's feed and the REST twin of ``raytpu doctor``."""
        from ray_tpu.util import state

        def _health():
            try:
                return state.health()
            except Exception as e:  # noqa: BLE001 — surfaced to the API
                return {"error": str(e)}

        return _json(await _off(_health))

    async def sched(self, req):
        """Scheduler explain plane rollup: pending-reason counts, the
        decision-ring tail, per-GCS-handler busy seconds and per-loop
        busy fractions (query params: ``limit`` for the ring tail,
        ``id`` to filter records to one task/actor/pg)."""
        from ray_tpu.util import state
        try:
            limit = int(req.query.get("limit", 100))
        except ValueError:
            limit = 100
        want_id = req.query.get("id")

        def collect():
            summary = state.summarize_tasks()
            return {
                "pending_reasons": summary.get("pending_reasons", {}),
                "total_tasks": summary.get("total_tasks", 0),
                "stats": state.sched_stats(),
                "decisions": state.sched_decisions(limit=limit, id=want_id),
            }

        return _json({"ts": time.time(), **await _off(collect)})

    async def workflow_send_event(self, req):
        """HTTP event provider (reference: workflow/http_event_provider.py):
        external systems POST a JSON payload here to unblock every workflow
        waiting on ``wait_for_event(key)``."""
        from ray_tpu.workflow import events as wf_events
        key = req.match_info["key"]
        try:
            payload = await req.json() if req.can_read_body else None
        except Exception:
            payload = (await req.read()).decode() or None
        await _off(lambda: wf_events.send_event(key, payload))
        return _json({"delivered": True, "key": key})

    async def workflow_event_status(self, req):
        from ray_tpu.workflow import events as wf_events
        key = req.match_info["key"]
        received = await _off(lambda: wf_events.event_received(key))
        return _json({"key": key, "received": received})

    async def stacks(self, _req):
        """Cluster-wide thread stacks (reference: dashboard reporter's
        py-spy endpoint; here via each node agent's node_stacks)."""
        import ray_tpu
        from ray_tpu.core.rpc import RpcClient, run_async

        def collect():
            out = {}
            for n in ray_tpu.nodes():
                addr = n.get("AgentAddress")
                if not (n.get("Alive") and addr):
                    continue
                try:
                    client = RpcClient(addr)
                    out[n["NodeID"][:12]] = run_async(
                        client.call("node_stacks", _timeout=15.0),
                        timeout=20)
                    run_async(client.close(), timeout=2)
                except Exception as e:  # noqa: BLE001
                    out[n["NodeID"][:12]] = {"error": str(e)}
            return out

        return _json(await _off(collect))

    def _agent_addr(self, node_id: str) -> Optional[str]:
        import ray_tpu
        for n in ray_tpu.nodes():
            if n.get("NodeID", "").startswith(node_id) and n.get("Alive"):
                return n.get("AgentAddress")
        return None

    async def node_logs(self, req):
        """List a node's session log files (reference: dashboard log module
        backed by the per-node agent)."""
        from ray_tpu.core.rpc import RpcClient, run_async
        node_id = req.match_info["node_id"]

        def fetch():
            addr = self._agent_addr(node_id)
            if addr is None:
                return []
            client = RpcClient(addr)
            try:
                return run_async(client.call("list_logs", _timeout=10.0),
                                 timeout=15)
            finally:
                run_async(client.close(), timeout=2)

        return _json(await _off(fetch))

    async def node_log_tail(self, req):
        from aiohttp import web
        from ray_tpu.core.rpc import RpcClient, run_async
        node_id = req.match_info["node_id"]
        name = req.match_info["name"]
        try:
            nbytes = int(req.query.get("bytes", 64 * 1024))
        except ValueError:
            nbytes = 64 * 1024

        def fetch():
            addr = self._agent_addr(node_id)
            if addr is None:
                return "(node not found)"
            client = RpcClient(addr)
            try:
                return run_async(client.call("tail_log", name=name,
                                             nbytes=nbytes, _timeout=10.0),
                                 timeout=15)
            finally:
                run_async(client.close(), timeout=2)

        return web.Response(text=await _off(fetch))

    async def index(self, _req):
        from aiohttp import web
        return web.FileResponse(os.path.join(_STATIC_DIR, "index.html"))

    @staticmethod
    def _filters(req) -> Optional[list]:
        out = []
        for k, v in req.query.items():
            out.append((k, "=", v))
        return out or None

    # ---------------------------------------------------------- lifecycle

    async def start(self) -> int:
        from aiohttp import web

        app = web.Application()
        r = app.router
        r.add_get("/api/healthz", self.healthz)
        r.add_get("/api/cluster", self.cluster)
        r.add_get("/api/nodes", self.nodes)
        r.add_get("/api/nodes/{node_id:[0-9a-f]{8,}}", self.node_detail)
        r.add_get("/api/actors", self.actors)
        r.add_get("/api/actors/{actor_id}", self.actor_detail)
        r.add_get("/api/tasks", self.tasks)
        r.add_get("/api/tasks/{task_id:[0-9a-f]{8,}}", self.task_detail)
        r.add_get("/api/metrics", self.metrics)
        r.add_get("/api/metrics/history", self.metrics_history)
        r.add_get("/api/telemetry", self.telemetry)
        r.add_get("/api/health", self.health_view)
        r.add_get("/api/sched", self.sched)
        r.add_get("/api/tasks/summarize", self.tasks_summarize)
        r.add_get("/api/objects", self.objects)
        r.add_get("/api/objects/{object_id:[0-9a-f]{8,}}",
                  self.object_detail)
        r.add_get("/api/placement_groups", self.placement_groups)
        r.add_get("/api/jobs", self.jobs)
        r.add_post("/api/jobs", self.submit_job)
        r.add_get("/api/jobs/{job_id}", self.job_info)
        r.add_get("/api/jobs/{job_id}/logs", self.job_logs)
        r.add_post("/api/jobs/{job_id}/stop", self.job_stop)
        r.add_get("/api/serve", self.serve_status)
        r.add_get("/api/serve/signal", self.serve_signal)
        r.add_get("/api/serve/autoscale", self.serve_autoscale)
        r.add_post("/api/serve/deploy", self.serve_deploy)
        r.add_get("/api/stacks", self.stacks)
        r.add_get("/api/timeline", self.timeline)
        r.add_get("/api/logs/{node_id}", self.node_logs)
        r.add_get("/api/logs/{node_id}/{name}", self.node_log_tail)
        r.add_get("/api/events", self.events)
        r.add_get("/api/usage_stats", self.usage_stats)
        r.add_post("/api/workflow/events/{key}", self.workflow_send_event)
        r.add_get("/api/workflow/events/{key}", self.workflow_event_status)
        # Web UI (reference: dashboard/client React SPA; here a no-build
        # vanilla SPA served from package data over the same REST API).
        r.add_get("/", self.index)
        r.add_static("/static/", _STATIC_DIR)
        self._runner = web.AppRunner(app, access_log=None)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if self.port == 0:
            self.port = site._server.sockets[0].getsockname()[1]
        # cluster metrics history: one background scrape loop per head
        self._scrape_task = asyncio.ensure_future(self._scrape_loop())
        return self.port

    async def stop(self):
        if self._scrape_task is not None:
            self._scrape_task.cancel()
            try:
                await self._scrape_task
            except (asyncio.CancelledError, Exception):
                pass
            self._scrape_task = None
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None


_dashboard: Optional[DashboardHead] = None


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> int:
    """Start the REST server on the driver's IO loop; returns the port."""
    global _dashboard
    from ray_tpu.core.rpc import run_async

    if _dashboard is not None:
        return _dashboard.port
    _dashboard = DashboardHead(host, port)
    return run_async(_dashboard.start())


def stop_dashboard():
    global _dashboard
    from ray_tpu.core.rpc import run_async

    if _dashboard is not None:
        run_async(_dashboard.stop())
        _dashboard = None
