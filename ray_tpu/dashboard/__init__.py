"""ray_tpu.dashboard — the control-plane REST API.

Reference: ``dashboard/head.py:81`` + ``dashboard/modules/{node,actor,job,
serve,healthz,state}`` (aiohttp REST the React UI and CLI consume).  The
REST surface is implemented here over the GCS + state API; the web UI is out
of scope (the reference's is ~25k LoC of TypeScript), but every endpoint
returns plain JSON consumable by curl / the CLI / a future UI.
"""

from .head import DashboardHead, start_dashboard, stop_dashboard

__all__ = ["DashboardHead", "start_dashboard", "stop_dashboard"]
