"""Cluster metrics history: a bounded ring-buffer time-series store over
every node agent's Prometheus ``/metrics`` endpoint, with counter->rate
derivation.

The dashboard's ``/api/metrics`` used to re-scrape every node per
request and could only answer "what is the value NOW" — no history, no
rates, no way to see whether chips stayed saturated through a run.  The
head now runs ONE background scrape loop (knobs
``metrics_scrape_period_s`` / ``metrics_history_window_s``) feeding this
store; ``/api/metrics`` serves the freshest sample (unreachable nodes
become explicit ``{"error": ...}`` entries instead of silently
vanishing) and ``/api/metrics/history`` serves the windowed series plus
derived per-second rates for every counter/histogram sample.

``raytpu top`` drives the same store from the CLI process (synchronous
scrapes via urllib), so the terminal view and the REST surface can never
disagree about what a sample means.
"""

from __future__ import annotations

import threading
import time
import urllib.request
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


def parse_prometheus(text: str) -> Tuple[Dict[str, float], set]:
    """Prometheus exposition text -> ({'name{tags}': value}, counter-like
    base names).  ``# TYPE`` lines classify counters AND histograms (whose
    ``_bucket``/``_sum``/``_count`` samples are cumulative too) so the
    store knows which keys are rate-derivable."""
    samples: Dict[str, float] = {}
    counters: set = set()
    for line in text.splitlines():
        line = line.strip()
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) >= 4 and parts[3] in ("counter", "histogram"):
                counters.add(parts[2])
            continue
        if not line or line.startswith("#"):
            continue
        try:
            key, val = line.rsplit(None, 1)
            samples[key] = float(val)
        except ValueError:
            continue
    return samples, counters


def scrape_node_sync(host: str, port: str, timeout: float = 5.0):
    """One synchronous scrape (the CLI path; the head scrapes async)."""
    with urllib.request.urlopen(f"http://{host}:{port}/metrics",
                                timeout=timeout) as resp:
        return parse_prometheus(resp.read().decode("utf-8", "replace"))


def find_samples(samples: Dict[str, float], name: str,
                 **labels: str) -> List[float]:
    """Values of every series of ``name`` whose rendered key carries all
    the given label pairs (substring match on the exposition key — label
    order varies, the quoting does not)."""
    out = []
    prefix = name + "{"
    for key, val in samples.items():
        if key == name or key.startswith(prefix):
            if all(f'{k}="{v}"' in key for k, v in labels.items()):
                out.append(val)
    return out


def find_one(samples: Dict[str, float], name: str, default=None,
             agg=max, **labels: str):
    vals = find_samples(samples, name, **labels)
    return agg(vals) if vals else default


class MetricsHistory:
    """Per-node ring buffers of (ts, samples) capped by count AND age.

    ``add_sample``/``record_error`` append; ``latest()`` answers the
    instantaneous ``/api/metrics`` shape; ``series``/``rates`` answer the
    history endpoint.  Rates handle counter RESETS (an agent or worker
    restart zeroes its registry): a decrease is treated as a restart from
    zero, so the derived rate is ``new_value / dt`` rather than a bogus
    negative."""

    def __init__(self, window_s: float = 600.0, period_s: float = 5.0,
                 stale_after_s: Optional[float] = None):
        self.window_s = float(window_s)
        self.period_s = float(period_s)
        #: a success gap longer than this marks a node DEPARTED-and-
        #: REJOINED (vs a blip): its pre-gap sample tail is a previous
        #: incarnation and ages out rather than being served as history
        self.stale_after_s = (max(3 * self.period_s, 15.0)
                              if stale_after_s is None
                              else float(stale_after_s))
        self._maxlen = max(4, int(self.window_s
                                  / max(self.period_s, 0.1)) + 2)
        #: node -> deque[(ts, samples-or-None, error-or-None)]
        self._samples: Dict[str, Deque[tuple]] = {}
        #: node -> ts of its newest GOOD sample (rejoin detection)
        self._last_success: Dict[str, float] = {}
        self._counters: set = set()
        self._lock = threading.Lock()

    # ------------------------------------------------------------ writes

    def add_sample(self, node: str, samples: Dict[str, float],
                   counters=(), ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            dq = self._samples.get(node)
            if dq is None:
                dq = self._samples[node] = deque(maxlen=self._maxlen)
            last_ok = self._last_success.get(node)
            if last_ok is not None and ts - last_ok > self.stale_after_s:
                # rejoin after a dark gap: drop the stale good-sample
                # tail (the error markers stay — they are the flap
                # evidence); rates re-chain from this fresh sample
                kept = [e for e in dq if e[1] is None]
                dq.clear()
                dq.extend(kept)
            self._last_success[node] = ts
            dq.append((ts, samples, None))
            if counters:
                self._counters.update(counters)
            self._prune(dq, ts)

    def record_error(self, node: str, error: str,
                     ts: Optional[float] = None) -> None:
        ts = time.time() if ts is None else ts
        with self._lock:
            dq = self._samples.get(node)
            if dq is None:
                dq = self._samples[node] = deque(maxlen=self._maxlen)
            dq.append((ts, None, str(error)))
            self._prune(dq, ts)

    def _prune(self, dq: Deque[tuple], now: float) -> None:
        horizon = now - self.window_s
        while dq and dq[0][0] < horizon:
            dq.popleft()

    def forget(self, node: str) -> None:
        with self._lock:
            self._samples.pop(node, None)
            self._last_success.pop(node, None)

    # ------------------------------------------------------------- reads

    def nodes(self) -> List[str]:
        with self._lock:
            return list(self._samples)

    def latest(self) -> Tuple[float, Dict[str, dict]]:
        """Freshest sample per node — the ``/api/metrics`` feed.  A node
        whose last scrape failed reports ``{"error": ...}`` explicitly."""
        out: Dict[str, dict] = {}
        newest = 0.0
        with self._lock:
            for node, dq in self._samples.items():
                if not dq:
                    continue
                ts, samples, err = dq[-1]
                newest = max(newest, ts)
                out[node] = {"error": err} if err is not None else samples
        return newest, out

    def _is_cumulative(self, key: str) -> bool:
        name = key.split("{", 1)[0]
        if name in self._counters:
            return True
        for suf in ("_bucket", "_sum", "_count"):
            if name.endswith(suf) and name[:-len(suf)] in self._counters:
                return True
        return False

    def series(self, node: str, prefix: str = "") -> Dict[str, list]:
        """{key: [[ts, value], ...]} over the retained window."""
        with self._lock:
            items = list(self._samples.get(node) or ())
        out: Dict[str, list] = {}
        for ts, samples, err in items:
            if err is not None or samples is None:
                continue
            t = round(ts, 3)
            for key, val in samples.items():
                if prefix and not key.startswith(prefix):
                    continue
                out.setdefault(key, []).append([t, val])
        return out

    def rates(self, node: str, prefix: str = "") -> Dict[str, list]:
        """Per-second rates of every cumulative (counter/histogram)
        series: {key: [[ts, rate], ...]} between consecutive good
        samples.  An error sample breaks the chain (no rate across the
        gap); a value DECREASE is a counter reset and rates as
        ``new / dt``."""
        with self._lock:
            items = list(self._samples.get(node) or ())
            # snapshot the counter-name set once; _is_cumulative below
            # runs lock-free against it
        out: Dict[str, list] = {}
        prev: Optional[Tuple[float, Dict[str, float]]] = None
        for ts, samples, err in items:
            if err is not None or samples is None:
                prev = None
                continue
            if prev is not None:
                pts, psamples = prev
                dt = ts - pts
                if dt > 0:
                    for key, val in samples.items():
                        if prefix and not key.startswith(prefix):
                            continue
                        if not self._is_cumulative(key):
                            continue
                        pval = psamples.get(key)
                        if pval is None:
                            continue
                        delta = val - pval
                        if delta < 0:  # counter reset (process restart)
                            delta = val
                        out.setdefault(key, []).append(
                            [round(ts, 3), delta / dt])
            prev = (ts, samples)
        return out

    def flaps(self, node: str, window_s: Optional[float] = None,
              now: Optional[float] = None) -> int:
        """Error->success transitions for a node inside the window — the
        NODE_FLAPPING evidence.  Trustworthy because ``add_sample`` ages
        out pre-rejoin tails: every counted recovery happened inside
        THIS incarnation's retained history."""
        now = time.time() if now is None else now
        horizon = now - (self.window_s if window_s is None else window_s)
        with self._lock:
            items = list(self._samples.get(node) or ())
        count = 0
        prev_err: Optional[bool] = None
        for ts, _samples, err in items:
            if ts < horizon:
                continue
            is_err = err is not None
            if prev_err is True and not is_err:
                count += 1
            prev_err = is_err
        return count

    def summary(self, node: str) -> dict:
        with self._lock:
            dq = self._samples.get(node)
            if not dq:
                return {"n_samples": 0}
            ts, _samples, err = dq[-1]
            return {"n_samples": len(dq), "latest_ts": round(ts, 3),
                    "error": err}
